"""Bounded-depth chunk pipeline: overlap metric fetch, judgment, write-back.

The worker's slow path processes a cold claim set in doc chunks
(`FOREMAST_COLD_CHUNK_DOCS`), and before this module each chunk ran
fetch → judge → write strictly serially: the device idled for the whole
Prometheus round trip and ES write-back of every chunk, and the host
idled while the device judged. That is the host/device overlap problem
every training/inference input pipeline solves with prefetch + double
buffering — steady-state wall clock should approach
max(fetch, judge, write) per chunk, not their sum.

Stage contract (what keeps the pipeline write-equivalent to the serial
loop — pinned by tests/test_worker_pipeline.py):

  * ``fetch(chunk)``          — runs on the caller-owned prefetch pool,
    up to ``depth - 1`` chunks ahead of the judge. Side effects are
    limited to caches that are already thread-safe (the hist/fit/gap
    ModelCaches, which today's per-chunk fetch pool mutates from worker
    threads too). Per-doc failures are VALUES (``None`` entries), never
    exceptions — a failed fetch marks only its own doc and cannot stall
    or poison in-flight chunks.
  * ``judge(chunk, payload)`` — tick thread only, strictly in chunk
    order: device dispatch order is load-bearing (arena row assignment
    evolves identically to the serial loop, and pod-mode collectives
    would deadlock under reordering).
  * ``write(chunk, result)``  — store writes + verdict hooks; runs on
    ONE writer thread consuming a FIFO queue, so the store sees the
    same write sequence per chunk the serial loop produced, one chunk
    behind the judgment.

Depth semantics: the prefetch stage runs at most ``depth - 1`` chunks
ahead of the judge, and the write queue holds at most ``depth`` judged
chunks before the judge stalls — so up to ``2 * depth`` chunks can be
resident at once (prefetching + judging + queued), which together with
the chunk size is the host-memory bound for packed histories and
un-persisted verdicts. ``depth <= 1``, a single
chunk, or no prefetch pool all degrade to the inline serial loop — the
worker passes no pool when the source declares
``concurrent_fetch = False`` (pod-mode ``LeaderSource``, whose fetches
are ordered broadcast collectives; in-memory test sources).

Failure semantics ("clean drain"): a fetch-stage exception surfaces on
the tick thread when that chunk's turn to be judged comes; a judge
exception stops feeding immediately (raise :class:`StageError` to also
ship a final partial result — the chunk's fetch-failure markings —
through the writer before the error propagates); a write exception
stops the writer
(later chunks drain unwritten — fail fast, exactly where the serial
loop would have stopped) and re-raises on the tick thread. On every
path the writer thread is joined and in-flight prefetches are awaited
before ``run()`` returns, so no stage thread outlives the tick and
every chunk judged before the failure is persisted.

ISSUE 15 extensions (the warm-path sliced sweep rides the same class):

  * ``run()`` accepts an unbounded ITERATOR of chunk specs; a lazy
    fetch stage (a claim-pool-backed slice preparer) returns ``END``
    to stop feeding — the pipeline drains cleanly, never judging END.
  * ``boundary`` — a tick-thread hook invoked between chunks, after
    chunk N's result is handed to the writer: the sliced sweep's
    micro-tick PREEMPTION POINT.
  * ``on_drained(chunk, payload)`` — abort-path callback for chunks
    whose fetch completed but whose judgment never ran, so a fetch
    stage with side effects (claimed documents) can give them back.
"""

from __future__ import annotations

import collections
import logging
import queue
import threading
import time

log = logging.getLogger("foremast_tpu.pipeline")

DEFAULT_DEPTH = 2

_DONE = object()

# Lazy-source exhaustion sentinel (ISSUE 15): a fetch stage backed by a
# claim pool (the sliced sweep) signals "no more work" by RETURNING
# `END` — the pipeline stops feeding, drains in-flight stages, and
# never judges or writes the END chunk. Lists keep working unchanged;
# END just lets `run()` accept an unbounded iterator of slice specs
# whose real extent only the fetch stage can discover.
END = object()


class StageError(Exception):
    """Raised by a judge stage that died partway but still owes the
    write stage a final partial result (e.g. the chunk's fetch-failure
    markings, which the pre-pipeline loop persisted before judging).
    The pipeline writes ``result`` through the ordinary writer path —
    store access stays single-threaded — then stops feeding immediately
    and re-raises ``error`` on the tick thread after the drain."""

    def __init__(self, error: BaseException, result):
        super().__init__(str(error))
        self.error = error
        self.result = result


class PipelineStats:
    """One run's occupancy accounting.

    Mutated only from the tick thread: concurrent stages report their
    timings through return values (fetch) or a post-``join`` merge
    (write), so the counters need no lock and a ``/debug/state`` reader
    sees a consistent snapshot via ``as_dict``.
    """

    __slots__ = (
        "depth",
        "pipelined",
        "completed",
        "chunks",
        "docs",
        "fetch_seconds",
        "judge_seconds",
        "write_seconds",
        "judge_stall_seconds",
        "write_queue_peak",
        "wall_seconds",
    )

    def __init__(self, depth: int):
        self.depth = depth
        self.pipelined = False
        # False while (or after) a run that raised: /debug/state readers
        # must be able to tell a mid-abort snapshot from a healthy tick
        self.completed = False
        self.chunks = 0
        self.docs = 0
        self.fetch_seconds = 0.0  # stage-busy, summed over chunks
        self.judge_seconds = 0.0  # device dispatch + verdict decode
        self.write_seconds = 0.0  # status decide + store round trips
        # time the judge stage spent waiting for its chunk's windows —
        # the device sat idle for exactly this long
        self.judge_stall_seconds = 0.0
        self.write_queue_peak = 0
        self.wall_seconds = 0.0

    def overlap_ratio(self) -> float:
        """Fraction of stage-busy time hidden by overlap: ~0 when the
        stages ran back to back (serial), approaching 2/3 at perfect
        three-stage overlap."""
        busy = self.fetch_seconds + self.judge_seconds + self.write_seconds
        if busy <= 0.0 or self.wall_seconds <= 0.0:
            return 0.0
        return max(0.0, 1.0 - self.wall_seconds / busy)

    def as_dict(self) -> dict:
        return {
            "depth": self.depth,
            "pipelined": self.pipelined,
            "completed": self.completed,
            "chunks": self.chunks,
            "docs": self.docs,
            "fetch_seconds": round(self.fetch_seconds, 4),
            "judge_seconds": round(self.judge_seconds, 4),
            "write_seconds": round(self.write_seconds, 4),
            "device_idle_seconds": round(self.judge_stall_seconds, 4),
            "write_queue_peak": self.write_queue_peak,
            "wall_seconds": round(self.wall_seconds, 4),
            "overlap_ratio": round(self.overlap_ratio(), 4),
        }


class ChunkPipeline:
    """Run fetch → judge → write over an ordered chunk list with bounded
    lookahead.

    Generic over the stage callables so the worker (and tests) can
    inject instrumented stages. The prefetch pool is OWNED BY THE
    CALLER and reused across ticks (persistent threads — the worker
    keeps one per process instead of spawning a pool per chunk); pass
    ``prefetch_pool=None`` to force the serial loop.
    """

    def __init__(
        self,
        fetch,
        judge,
        write,
        depth: int = DEFAULT_DEPTH,
        prefetch_pool=None,
        boundary=None,
        on_drained=None,
    ):
        self.fetch = fetch
        self.judge = judge
        self.write = write
        self.depth = max(1, int(depth))
        self.prefetch_pool = prefetch_pool
        # `boundary` (ISSUE 15): a tick-thread hook invoked between
        # chunks — after chunk N's judgment is handed to the writer and
        # before chunk N+1's is started. The sliced sweep's PREEMPTION
        # POINT: the worker drains pending dirty arrivals here while
        # the writer flushes N and the prefetch pool prepares N+1. A
        # boundary exception aborts the run exactly like a judge
        # exception (clean drain, then re-raise).
        self.boundary = boundary
        # `on_drained(chunk, payload)` (tick thread, abort path only):
        # invoked for every chunk whose fetch COMPLETED but whose
        # judgment never ran when run() aborts. Fetch stages with side
        # effects (the sliced sweep's prepare stage holds CLAIMED docs)
        # use it to give that work back instead of leaving it to the
        # stuck-claim takeover window.
        self.on_drained = on_drained
        # stats of the most recent run(), including one that raised —
        # callers surface occupancy on the abort path from here
        self.last_stats: PipelineStats | None = None

    def run(self, chunks) -> PipelineStats:
        stats = PipelineStats(self.depth)
        self.last_stats = stats
        sized = hasattr(chunks, "__len__")
        if sized:
            stats.chunks = len(chunks)
            stats.docs = sum(
                len(c) if hasattr(c, "__len__") else 1 for c in chunks
            )
        t_wall = time.perf_counter()
        try:
            if self.depth <= 1 or self.prefetch_pool is None or (
                sized and len(chunks) <= 1
            ):
                self._run_serial(chunks, stats)
            else:
                stats.pipelined = True
                self._run_pipelined(chunks, stats)
            stats.completed = True
        finally:
            stats.wall_seconds = time.perf_counter() - t_wall
        return stats

    def _run_serial(self, chunks, stats: PipelineStats) -> None:
        sized = hasattr(chunks, "__len__")
        for chunk in chunks:
            t0 = time.perf_counter()
            payload = self.fetch(chunk)
            t1 = time.perf_counter()
            # accumulated before judging so the abort-path snapshot
            # (completed=False) still carries the chunk's fetch cost
            stats.fetch_seconds += t1 - t0
            if payload is END:
                break
            if not sized:
                stats.chunks += 1
                stats.docs += len(chunk) if hasattr(chunk, "__len__") else 1
            try:
                result = self.judge(chunk, payload)
            except StageError as se:
                t2 = time.perf_counter()
                stats.judge_seconds += t2 - t1
                self.write(chunk, se.result)  # partial: failure markings
                stats.write_seconds += time.perf_counter() - t2
                raise se.error
            t2 = time.perf_counter()
            stats.judge_seconds += t2 - t1
            self.write(chunk, result)
            stats.write_seconds += time.perf_counter() - t2
            if self.boundary is not None:
                self.boundary()

    def _run_pipelined(self, chunks, stats: PipelineStats) -> None:
        write_errors: list[BaseException] = []
        write_seconds = [0.0]  # writer-thread local; read after join()
        wq: queue.Queue = queue.Queue(maxsize=self.depth)

        def writer():
            # One thread, FIFO: the store sees the serial loop's
            # per-chunk write order. After a write error, later chunks
            # drain UNWRITTEN — fail fast at the same point the serial
            # loop would have stopped.
            while True:
                item = wq.get()
                if item is _DONE:
                    return
                if write_errors:
                    continue
                chunk, result = item
                t0 = time.perf_counter()
                try:
                    self.write(chunk, result)
                except BaseException as e:  # noqa: BLE001 — re-raised on the tick thread
                    write_errors.append(e)
                    # log HERE, not only via the tick-thread re-raise: if
                    # a judge/fetch error propagates first it wins the
                    # raise, and a store outage recorded only in
                    # write_errors would otherwise vanish unreported
                    log.exception(
                        "pipeline write-back failed; remaining chunks "
                        "drain unwritten"
                    )
                finally:
                    write_seconds[0] += time.perf_counter() - t0

        wt = threading.Thread(
            target=writer, name="foremast-writeback", daemon=True
        )
        wt.start()

        def timed_fetch(chunk):
            t0 = time.perf_counter()
            payload = self.fetch(chunk)
            return time.perf_counter() - t0, payload

        # one iterator serves lists and lazy sources alike; a lazy
        # source's true extent surfaces as an END payload from fetch
        sized = hasattr(chunks, "__len__")
        it = iter(chunks)
        pending: collections.deque = collections.deque()
        exhausted = [False]

        def submit_next():
            if exhausted[0]:
                return
            try:
                chunk = next(it)
            except StopIteration:
                exhausted[0] = True
                return
            pending.append(
                (chunk, self.prefetch_pool.submit(timed_fetch, chunk))
            )

        try:
            for _ in range(self.depth - 1):
                submit_next()
            while pending:
                if write_errors:
                    break  # writer failed; don't burn device time on
                    # a judgment whose result could never be written
                chunk, fut = pending.popleft()
                t0 = time.perf_counter()
                fetch_s, payload = fut.result()
                stats.judge_stall_seconds += time.perf_counter() - t0
                stats.fetch_seconds += fetch_s
                if payload is END:
                    # lazy source drained: stop SUBMITTING, but keep
                    # consuming the deque — with 2+ prefetch workers
                    # (depth >= 3) a fully prepared chunk can sit
                    # QUEUED BEHIND the END that raced it for the
                    # source's last items; abandoning it to the drain
                    # path would un-do real work on a healthy run
                    exhausted[0] = True
                    continue
                if not sized:
                    stats.chunks += 1
                    stats.docs += (
                        len(chunk) if hasattr(chunk, "__len__") else 1
                    )
                submit_next()  # keep the lookahead window full
                t1 = time.perf_counter()
                try:
                    result = self.judge(chunk, payload)
                except StageError as se:
                    # stop feeding NOW (no further chunk touches the
                    # broken judge), but the partial result still rides
                    # the writer queue so the failure markings persist;
                    # the finally block drains it before `error`
                    # propagates off the tick thread
                    stats.judge_seconds += time.perf_counter() - t1
                    wq.put((chunk, se.result))
                    raise se.error
                stats.judge_seconds += time.perf_counter() - t1
                if write_errors:
                    break  # writer failed mid-judgment; stop feeding
                wq.put((chunk, result))
                # measured after the put: the peak reflects queued
                # chunks only, so it never exceeds the documented
                # `depth` bound even while the put above is blocking
                stats.write_queue_peak = max(
                    stats.write_queue_peak, wq.qsize()
                )
                if self.boundary is not None:
                    self.boundary()
        finally:
            # Clean drain, even when the try-body raised: the writer
            # finishes every queued chunk (or skips the rest after its
            # own error), and in-flight prefetches are awaited so no
            # stage thread outlives the tick. The sentinel put cannot
            # deadlock on a full queue — the writer only exits on the
            # sentinel, so it keeps freeing slots until it sees it.
            wq.put(_DONE)
            wt.join()
            stats.write_seconds += write_seconds[0]
            for chunk, fut in pending:
                if fut.cancel():
                    continue
                try:
                    _, payload = fut.result()
                except BaseException:  # noqa: BLE001 — the primary error propagates
                    log.exception(
                        "draining in-flight prefetch after pipeline abort"
                    )
                    continue
                # a completed prefetch whose judgment never ran: let
                # the caller give the work back (released claims)
                if payload is not END and self.on_drained is not None:
                    try:
                        self.on_drained(chunk, payload)
                    except BaseException:  # noqa: BLE001 — the primary error propagates
                        log.exception(
                            "on_drained failed for an unjudged chunk"
                        )
        if write_errors:
            raise write_errors[0]
