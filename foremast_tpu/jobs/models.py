"""Wire + storage models, byte-compatible with the reference service.

Mirrors `foremast-service/pkg/models/models.go:6-146` (request/response and
ES document structs) and `pkg/converter/converter.go:11-30` (the brain's
internal status machine and its external translation). These contracts are
preserved exactly so reference clients (barrelman) interoperate
(SURVEY.md section 5, "contracts worth preserving byte-for-byte").
"""

from __future__ import annotations

import dataclasses
import hashlib
import hmac
from typing import Any, Mapping

# ---------------------------------------------------------------------------
# Status state machine (converter.go:13-26; design.md:47-51)
# ---------------------------------------------------------------------------

STATUS_INITIAL = "initial"
STATUS_PREPROCESS_INPROGRESS = "preprocess_inprogress"
STATUS_PREPROCESS_COMPLETED = "preprocess_completed"
STATUS_PREPROCESS_FAILED = "preprocess_failed"
STATUS_POSTPROCESS_INPROGRESS = "postprocess_inprogress"
STATUS_COMPLETED_HEALTH = "completed_health"
STATUS_COMPLETED_UNHEALTH = "completed_unhealth"
STATUS_COMPLETED_UNKNOWN = "completed_unknown"
STATUS_ABORT = "abort"

TERMINAL_STATUSES = frozenset(
    {
        STATUS_COMPLETED_HEALTH,
        STATUS_COMPLETED_UNHEALTH,
        STATUS_COMPLETED_UNKNOWN,
        STATUS_PREPROCESS_FAILED,
        STATUS_ABORT,
    }
)

# in-progress statuses: claimable only via stuck-job takeover
# (modified_at older than MAX_STUCK_IN_SECONDS, design.md:39)
INPROGRESS_STATUSES = (
    STATUS_PREPROCESS_INPROGRESS,
    STATUS_POSTPROCESS_INPROGRESS,
)

# one source of truth with the store's server-side claimability query:
# fresh work + the in-progress family (the latter claimable only when stuck)
CLAIMABLE_STATUSES = (
    STATUS_INITIAL,
    STATUS_PREPROCESS_COMPLETED,
    *INPROGRESS_STATUSES,
)

# External view (converter.go:11-30): internal -> {new, inprogress,
# success, anomaly, abort}.
_EXTERNAL = {
    STATUS_INITIAL: "new",
    STATUS_PREPROCESS_INPROGRESS: "inprogress",
    STATUS_POSTPROCESS_INPROGRESS: "inprogress",
    STATUS_PREPROCESS_COMPLETED: "inprogress",
    STATUS_COMPLETED_HEALTH: "success",
    STATUS_COMPLETED_UNHEALTH: "anomaly",
    STATUS_COMPLETED_UNKNOWN: "abort",
    STATUS_PREPROCESS_FAILED: "abort",
}


def status_to_external(status: str) -> str:
    """converter.ConvertStatusToExternal parity; unknown statuses pass
    through unchanged (the Go switch's default branch)."""
    return _EXTERNAL.get(status, status)


# ---------------------------------------------------------------------------
# Request / response wire structs (models.go:35-80)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class MetricQuery:
    """{dataSourceType, parameters} — models.go:6-17."""

    data_source_type: str
    parameters: dict[str, Any]

    @staticmethod
    def from_json(d: Mapping[str, Any]) -> "MetricQuery":
        return MetricQuery(
            data_source_type=d.get("dataSourceType", "prometheus"),
            parameters=dict(d.get("parameters", {})),
        )

    def to_json(self) -> dict[str, Any]:
        return {
            "dataSourceType": self.data_source_type,
            "parameters": self.parameters,
        }


@dataclasses.dataclass
class MetricsInfo:
    """{current, baseline, historical}: alias -> MetricQuery maps."""

    current: dict[str, MetricQuery] = dataclasses.field(default_factory=dict)
    baseline: dict[str, MetricQuery] = dataclasses.field(default_factory=dict)
    historical: dict[str, MetricQuery] = dataclasses.field(default_factory=dict)

    @staticmethod
    def from_json(d: Mapping[str, Any]) -> "MetricsInfo":
        def conv(m):
            return {k: MetricQuery.from_json(v) for k, v in (m or {}).items()}

        return MetricsInfo(
            current=conv(d.get("current")),
            baseline=conv(d.get("baseline")),
            historical=conv(d.get("historical")),
        )

    def to_json(self) -> dict[str, Any]:
        return {
            "current": {k: v.to_json() for k, v in self.current.items()},
            "baseline": {k: v.to_json() for k, v in self.baseline.items()},
            "historical": {k: v.to_json() for k, v in self.historical.items()},
        }


@dataclasses.dataclass
class AnalyzeRequest:
    """ApplicationHealthAnalyzeRequest — models.go:35-49."""

    app_name: str
    start_time: str
    end_time: str
    metrics: MetricsInfo
    strategy: str  # rollingUpdate | canary | continuous (metricsquery.go:16-19)
    namespace: str = ""
    pods: list[str] = dataclasses.field(default_factory=list)

    @staticmethod
    def from_json(d: Mapping[str, Any]) -> "AnalyzeRequest":
        return AnalyzeRequest(
            app_name=d.get("appName", ""),
            start_time=d.get("startTime", ""),
            end_time=d.get("endTime", ""),
            metrics=MetricsInfo.from_json(d.get("metrics", {})),
            strategy=d.get("strategy", ""),
            namespace=d.get("namespace", ""),
            pods=list(d.get("podCountURL", []) or []),
        )

    def to_json(self) -> dict[str, Any]:
        return {
            "appName": self.app_name,
            "startTime": self.start_time,
            "endTime": self.end_time,
            "metrics": self.metrics.to_json(),
            "strategy": self.strategy,
        }


@dataclasses.dataclass
class AnomalyInfo:
    """{tags, values} with values the flat [t1,v1,t2,v2,...] pairs decoded
    by barrelman's convertToAnomaly (Barrelman.go:593-620)."""

    tags: str = ""
    values: dict[str, list[float]] = dataclasses.field(default_factory=dict)

    def to_json(self) -> dict[str, Any]:
        return {"tags": self.tags, "values": self.values}


# ---------------------------------------------------------------------------
# ES document (models.go:96-146)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Document:
    id: str
    app_name: str
    created_at: str = ""
    modified_at: str = ""
    start_time: str = ""
    end_time: str = ""
    current_config: str = ""
    baseline_config: str = ""
    historical_config: str = ""
    current_metric_store: str = ""
    baseline_metric_store: str = ""
    historical_metric_store: str = ""
    status: str = STATUS_INITIAL
    status_code: str = "201"
    strategy: str = ""
    reason: str = ""
    processing_content: str = ""
    anomaly_info: dict[str, Any] | None = None
    # correlation ID minted by the service at create time (observe/spans):
    # carried through the store so worker/controller logs and traces can
    # join back to the originating request. Additive — serialized only
    # when set, so reference clients see an unchanged document shape.
    trace_id: str = ""

    def to_json(self) -> dict[str, Any]:
        return {
            "id": self.id,
            "appName": self.app_name,
            "createdAt": self.created_at,
            "modifiedAt": self.modified_at,
            "startTime": self.start_time,
            "endTime": self.end_time,
            "currentConfig": self.current_config,
            "baselineConfig": self.baseline_config,
            "historicalConfig": self.historical_config,
            "currentMetricStore": self.current_metric_store,
            "baselineMetricStore": self.baseline_metric_store,
            "historicalMetricStore": self.historical_metric_store,
            "status": self.status,
            "statusCode": self.status_code,
            "strategy": self.strategy,
            "reason": self.reason,
            "processingContent": self.processing_content,
            **({"traceId": self.trace_id} if self.trace_id else {}),
            **({"anomalyInfo": self.anomaly_info} if self.anomaly_info else {}),
        }

    @staticmethod
    def from_json(d: Mapping[str, Any]) -> "Document":
        return Document(
            id=d.get("id", ""),
            app_name=d.get("appName", ""),
            created_at=d.get("createdAt", ""),
            modified_at=d.get("modifiedAt", ""),
            start_time=d.get("startTime", ""),
            end_time=d.get("endTime", ""),
            current_config=d.get("currentConfig", ""),
            baseline_config=d.get("baselineConfig", ""),
            historical_config=d.get("historicalConfig", ""),
            current_metric_store=d.get("currentMetricStore", ""),
            baseline_metric_store=d.get("baselineMetricStore", ""),
            historical_metric_store=d.get("historicalMetricStore", ""),
            status=d.get("status", STATUS_INITIAL),
            status_code=str(d.get("statusCode", "201")),
            strategy=d.get("strategy", ""),
            reason=d.get("reason", ""),
            processing_content=d.get("processingContent", ""),
            anomaly_info=d.get("anomalyInfo"),
            trace_id=d.get("traceId", ""),
        )


def document_response(doc: Document) -> dict[str, Any]:
    """GET /v1/healthcheck/id/:id body: external status view + anomaly
    (converter.ConvertESToResp, converter.go:33-73)."""
    return {
        "jobId": doc.id,
        "appName": doc.app_name,
        "status": status_to_external(doc.status),
        "statusCode": doc.status_code,
        "reason": doc.reason,
        **({"anomalyInfo": doc.anomaly_info} if doc.anomaly_info else {}),
    }


# ---------------------------------------------------------------------------
# Idempotent job ids (common/stringutils.go:11-18; elasticsearchstore.go:29)
# ---------------------------------------------------------------------------


def job_id(
    app_name: str,
    start_time: str,
    end_time: str,
    configs: tuple[str, str, str],
    sources: tuple[str, str, str],
    strategy: str,
) -> str:
    """hex(HMAC-SHA256(key="", msg=appName+times+configs+sources+strategy)).

    Identical requests hash to the same id, making job creation idempotent
    and retries safe (reference UUIDGen + CreateNewDoc search-first).
    """
    msg = "".join((app_name, start_time, end_time, *configs, *sources, strategy))
    return hmac.new(b"", msg.encode("utf-8"), hashlib.sha256).hexdigest()
