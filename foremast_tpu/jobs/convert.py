"""Request -> ES document conversion (service RegisterEntry logic).

Parity with `foremast-service/cmd/manager/main.go:33-168`: validate appName
and non-empty `metrics.current`, flatten each window's alias->MetricQuery
map into the config string + parallel metric-source string, derive the
idempotent job id, and fill the Document.
"""

from __future__ import annotations

from foremast_tpu.jobs.models import AnalyzeRequest, Document, job_id
from foremast_tpu.metrics.promql import encode_config


class InvalidRequest(ValueError):
    pass


def request_to_document(req: AnalyzeRequest) -> Document:
    if not req.app_name:
        raise InvalidRequest("appName is required")
    if not req.metrics.current:
        raise InvalidRequest("metrics.current must not be empty")
    cur_cfg, cur_src = encode_config(req.metrics.current)
    base_cfg, base_src = encode_config(req.metrics.baseline)
    hist_cfg, hist_src = encode_config(req.metrics.historical)
    jid = job_id(
        req.app_name,
        req.start_time,
        req.end_time,
        (cur_cfg, base_cfg, hist_cfg),
        (cur_src, base_src, hist_src),
        req.strategy,
    )
    return Document(
        id=jid,
        app_name=req.app_name,
        start_time=req.start_time,
        end_time=req.end_time,
        current_config=cur_cfg,
        baseline_config=base_cfg,
        historical_config=hist_cfg,
        current_metric_store=cur_src,
        baseline_metric_store=base_src,
        historical_metric_store=hist_src,
        strategy=req.strategy,
    )
