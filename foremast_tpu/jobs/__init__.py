"""Job plane: wire models, idempotent ids, stores, the brain worker."""

from foremast_tpu.jobs.models import (
    CLAIMABLE_STATUSES,
    STATUS_ABORT,
    STATUS_COMPLETED_HEALTH,
    STATUS_COMPLETED_UNHEALTH,
    STATUS_COMPLETED_UNKNOWN,
    STATUS_INITIAL,
    STATUS_PREPROCESS_COMPLETED,
    STATUS_PREPROCESS_FAILED,
    STATUS_PREPROCESS_INPROGRESS,
    STATUS_POSTPROCESS_INPROGRESS,
    TERMINAL_STATUSES,
    AnalyzeRequest,
    AnomalyInfo,
    Document,
    MetricQuery,
    MetricsInfo,
    document_response,
    job_id,
    status_to_external,
)
from foremast_tpu.jobs.store import (
    ElasticsearchStore,
    InMemoryStore,
    JobStore,
    now_rfc3339,
)

def __getattr__(name):
    # worker imports the metrics package, which imports jobs.models via this
    # package — resolve BrainWorker lazily so either side can load first
    if name in ("BrainWorker", "infer_metric_type"):
        from foremast_tpu.jobs import worker

        return getattr(worker, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "CLAIMABLE_STATUSES",
    "STATUS_ABORT",
    "STATUS_COMPLETED_HEALTH",
    "STATUS_COMPLETED_UNHEALTH",
    "STATUS_COMPLETED_UNKNOWN",
    "STATUS_INITIAL",
    "STATUS_PREPROCESS_COMPLETED",
    "STATUS_PREPROCESS_FAILED",
    "STATUS_PREPROCESS_INPROGRESS",
    "STATUS_POSTPROCESS_INPROGRESS",
    "TERMINAL_STATUSES",
    "AnalyzeRequest",
    "AnomalyInfo",
    "Document",
    "MetricQuery",
    "MetricsInfo",
    "document_response",
    "job_id",
    "status_to_external",
    "ElasticsearchStore",
    "InMemoryStore",
    "JobStore",
    "now_rfc3339",
    "BrainWorker",
    "infer_metric_type",
]
