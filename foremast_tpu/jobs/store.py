"""Job stores: the durable queue + state store.

The reference uses Elasticsearch as both durable queue and state store
(index `documents`, type `document`,
`foremast-service/pkg/search/elasticsearchstore.go:16-19`), with
search-first idempotent creation (`CreateNewDoc`, `:22-62`) and a
`ByStatus` fuzzy search used by the brain to claim work (`:124-149`).
Semantics preserved here:

  * idempotent create — same id (HMAC of request) never duplicates;
  * claimable = status in {initial, *_inprogress stuck > MAX_STUCK_IN_SECONDS,
    preprocess_completed} — the lease-style work-stealing of
    `design.md:39` / `foremast-brain.yaml:80-81`;
  * claiming is a compare-and-set on (status, modified_at) so two workers
    cannot double-claim (the reference gets this from ES versioned
    updates).

`InMemoryStore` is the test/standalone backend; `ElasticsearchStore`
speaks the ES REST API directly (no client lib in the image).
"""

from __future__ import annotations

import logging
import random
import threading
import time
from datetime import datetime, timezone
from typing import Iterable

from foremast_tpu.jobs.models import (
    CLAIMABLE_STATUSES,
    INPROGRESS_STATUSES,
    STATUS_INITIAL,
    STATUS_PREPROCESS_COMPLETED,
    STATUS_PREPROCESS_INPROGRESS,
    TERMINAL_STATUSES,
    Document,
)
from foremast_tpu.observe.spans import span


log = logging.getLogger("foremast_tpu.jobs.store")


def now_rfc3339() -> str:
    return datetime.now(timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")


def parse_time(s: str) -> float:
    """Unix-seconds or ISO-8601/RFC3339 (any offset/fraction form) ->
    epoch seconds; 0.0 when empty or unparseable. The one shared time
    parser for document timestamps across the job and watch planes."""
    if not s:
        return 0.0
    try:
        return float(s)
    except ValueError:
        pass
    try:
        dt = datetime.fromisoformat(s.replace("Z", "+00:00"))
        if dt.tzinfo is None:
            dt = dt.replace(tzinfo=timezone.utc)
        return dt.timestamp()
    except ValueError:
        return 0.0


class JobStore:
    """Interface: idempotent create, lookup, claim, update."""

    def create(self, doc: Document) -> tuple[Document, bool]:
        """Insert if no document with doc.id exists. Returns
        (stored_document, created) — on conflict the existing doc wins
        (CreateNewDoc search-first, elasticsearchstore.go:22-62)."""
        raise NotImplementedError

    def get(self, doc_id: str) -> Document | None:
        raise NotImplementedError

    def claim(
        self,
        worker_id: str,
        max_stuck_seconds: float,
        limit: int = 64,
        claim_filter=None,
    ) -> list[Document]:
        """Atomically take up to `limit` claimable docs: status==initial or
        preprocess_completed (re-check loop), or in-progress but stuck
        longer than max_stuck_seconds (work stealing).

        `claim_filter` (doc -> bool, optional) restricts WHICH claimable
        docs this worker takes — the mesh partition predicate
        (mesh/node.py). It must run BEFORE the status flip: a filtered
        doc stays claimable for its owner, it is never parked
        in-progress by a worker that won't judge it."""
        raise NotImplementedError

    def update(self, doc: Document) -> Document:
        raise NotImplementedError

    def update_many(self, docs: list[Document]) -> None:
        """Persist a batch of updated docs. Default: loop over update();
        stores with a cheaper bulk path (one lock, one bulk request)
        override — a fleet tick writes back thousands of docs."""
        for doc in docs:
            self.update(doc)

    def list_open(self) -> list[Document]:
        raise NotImplementedError

    def count_open(self) -> int:
        """Open (non-terminal) document count — the queue-depth varz.
        Default materializes list_open(); stores with a server-side
        count (ES `_count`) override so liveness probes don't page full
        documents (and aren't capped by list_open's fetch size)."""
        return len(self.list_open())

    def list_app(self, app_name: str) -> list[Document]:
        """Every open document of one app — mesh membership discovery
        (`mesh/membership.py` member records all share one app name).
        Default filters list_open(); stores with server-side term
        queries override, because at fleet scale the list_open page can
        starve out a handful of member records."""
        return [d for d in self.list_open() if d.app_name == app_name]


def _is_claimable(doc: Document, now: float, max_stuck: float) -> bool:
    if doc.status in (STATUS_INITIAL, STATUS_PREPROCESS_COMPLETED):
        return True
    if doc.status in TERMINAL_STATUSES:
        return False
    if doc.status in CLAIMABLE_STATUSES:  # *_inprogress
        return now - parse_time(doc.modified_at) > max_stuck
    return False


class InMemoryStore(JobStore):
    def __init__(self):
        self._docs: dict[str, Document] = {}
        self._lock = threading.Lock()

    def create(self, doc: Document) -> tuple[Document, bool]:
        with self._lock:
            existing = self._docs.get(doc.id)
            if existing is not None:
                return existing, False
            doc.created_at = doc.created_at or now_rfc3339()
            doc.modified_at = now_rfc3339()
            self._docs[doc.id] = doc
            return doc, True

    def get(self, doc_id: str) -> Document | None:
        with self._lock:
            return self._docs.get(doc_id)

    def claim(
        self,
        worker_id: str,
        max_stuck_seconds: float,
        limit: int = 64,
        claim_filter=None,
    ):
        now = time.time()
        stamp = now_rfc3339()  # one strftime per claim, not per doc
        out = []
        with self._lock:
            for doc in self._docs.values():
                if len(out) >= limit:
                    break
                # claimability FIRST (cheap), partition filter second —
                # matching the ES path's search-then-filter order, so
                # the mesh claim counters mean the same thing on both
                # stores and terminal docs never pay the ring hash
                if not _is_claimable(doc, now, max_stuck_seconds):
                    continue
                if claim_filter is None or claim_filter(doc):
                    # flip to in-progress inside the lock so a concurrent
                    # claimer sees the doc as taken (not claimable again
                    # until the stuck timeout)
                    doc.status = STATUS_PREPROCESS_INPROGRESS
                    doc.modified_at = stamp
                    doc.processing_content = worker_id
                    out.append(doc)
        return out

    def update(self, doc: Document) -> Document:
        with self._lock:
            doc.modified_at = now_rfc3339()
            self._docs[doc.id] = doc
            return doc

    def update_many(self, docs: list[Document]) -> None:
        stamp = now_rfc3339()
        with self._lock:
            for doc in docs:
                doc.modified_at = stamp
                self._docs[doc.id] = doc

    def list_open(self):
        with self._lock:
            return [d for d in self._docs.values() if d.status not in TERMINAL_STATUSES]

    def list_app(self, app_name: str) -> list[Document]:
        # one filtered pass — the base class materializes list_open()
        # first, which at fleet scale builds a 64k-entry list to find a
        # handful of mesh member records, on every router refresh
        with self._lock:
            return [
                d
                for d in self._docs.values()
                if d.app_name == app_name
                and d.status not in TERMINAL_STATUSES
            ]


# Explicit mapping for the `documents` index. The claim query depends on
# exact-match `terms` over `status`/`processingContent` and `range`+`sort`
# over `modifiedAt` (see `ElasticsearchStore.claim`); with dynamic mapping
# those land as analyzed `text` (term queries then hit analyzer behavior)
# and date detection depends on the cluster's settings — the semantics
# this store is built on must come from a template, not mapping luck.
# The reference inherited defaults from the olivere client
# (`elasticsearchstore.go:16-19`); this framework pins them. Config/
# content blobs are stored but never queried, so they are unindexed.
INDEX_MAPPINGS = {
    "properties": {
        "id": {"type": "keyword"},
        "appName": {"type": "keyword"},
        "status": {"type": "keyword"},
        "statusCode": {"type": "keyword"},
        "strategy": {"type": "keyword"},
        "processingContent": {"type": "keyword"},
        "createdAt": {"type": "date"},
        "modifiedAt": {"type": "date"},
        "startTime": {"type": "date", "ignore_malformed": True},
        "endTime": {"type": "date", "ignore_malformed": True},
        "currentConfig": {"type": "keyword", "index": False, "doc_values": False},
        "baselineConfig": {"type": "keyword", "index": False, "doc_values": False},
        "historicalConfig": {"type": "keyword", "index": False, "doc_values": False},
        "currentMetricStore": {"type": "keyword", "index": False, "doc_values": False},
        "baselineMetricStore": {"type": "keyword", "index": False, "doc_values": False},
        "historicalMetricStore": {"type": "keyword", "index": False, "doc_values": False},
        "reason": {"type": "keyword", "index": False, "doc_values": False},
        "traceId": {"type": "keyword", "index": False, "doc_values": False},
        "anomalyInfo": {"type": "object", "enabled": False},
    }
}


class MappingDivergence(RuntimeError):
    """The live `documents` index mapping contradicts the claim-critical
    field types — a permanent config error (ES cannot retype in place)."""


class ElasticsearchStore(JobStore):
    """ES REST backend — index/type parity with elasticsearchstore.go:16-19.

    Connection-retry semantics mirror the service's forever-retry loop
    (`service main.go:248-260`) via `wait_ready`, which also creates the
    index with the explicit `INDEX_MAPPINGS` (idempotent) — but bounded
    on request (`max_wait` / `stop`): a worker stuck in connect-retry
    must be stoppable promptly, and `connect_state` surfaces the retry
    loop's progress on `/debug/state` instead of looking like a hang.

    Chaos/degradation seams (ISSUE 9): `chaos`/`breaker` wrap the main
    session once (`chaos.GuardedSession`) — every store round trip
    passes the one choke point; both default None = raw session, zero
    overhead. The probe session stays unwrapped: varz/liveness must
    report THROUGH an outage, not degrade with it.
    """

    INDEX = "documents"
    TYPE = "document"

    def __init__(
        self,
        endpoint: str,
        session=None,
        timeout: float = 10.0,
        chaos=None,
        breaker=None,
    ):
        import requests

        self.endpoint = endpoint.rstrip("/")
        self._s = session or requests.Session()
        # wait_ready's retry-loop progress, replaced (never mutated) so
        # varz threads always read a consistent snapshot
        self.connect_state = {
            "connected": False,
            "attempts": 0,
            "waiting_seconds": 0.0,
            "last_error": None,
        }
        # probe/varz handlers (count_open) run on their own threads and
        # requests.Session is not thread-safe — give them a dedicated
        # session mirroring the main one's auth/TLS config. Injected
        # test doubles are reused directly.
        if isinstance(self._s, requests.Session):
            probe = requests.Session()
            probe.headers.update(self._s.headers)
            probe.auth = self._s.auth
            probe.verify = self._s.verify
            probe.cert = self._s.cert
            probe.proxies.update(self._s.proxies)
            # transport adapters carry pinned SSLContexts/retry/pool
            # config; urllib3 pools are thread-safe, so sharing the
            # instances is fine — losing them would make probes fail TLS
            # against an ES the main session reaches
            for prefix, adapter in self._s.adapters.items():
                probe.mount(prefix, adapter)
            self._probe_s = probe
        else:
            self._probe_s = self._s
        # several probe threads can overlap (service /healthz +
        # /debug/state, worker ThreadingHTTPServer scrapes) — serialize
        # their use of the one probe session
        self._probe_lock = threading.Lock()
        self.timeout = timeout
        if chaos is not None or breaker is not None:
            from foremast_tpu.chaos.guard import GuardedSession

            self._s = GuardedSession(self._s, chaos=chaos, breaker=breaker)

    # -- helpers --------------------------------------------------------

    def _url(self, *parts: str) -> str:
        return "/".join((self.endpoint, self.INDEX, *parts))

    def wait_ready(
        self,
        retry_seconds: float = 3.0,
        max_wait: float | None = None,
        stop=None,
    ):
        """Block until ES answers and the index is ensured. Returns
        False (instead of looping forever) when `max_wait` seconds
        elapse or `stop()` (a callable, e.g. a shutdown event's
        ``is_set``) turns true — the deadline + clean-shutdown bound on
        the reference's forever-retry loop. Progress is published on
        ``self.connect_state`` (attempts, last error, elapsed) so a
        worker stuck here reads as "retrying ES", not as a hang."""
        start = time.time()
        attempts = 0
        last_error = None
        # probe with the RAW session, bypassing any chaos/breaker guard
        # (GuardedSession.inner): the connect loop's repeated failures
        # would otherwise open the store breaker, after which every
        # retry reports "BreakerOpen" instead of the real refused-
        # connection/DNS error the runbook tells the operator to read,
        # and reconnection would wait out breaker cooldowns instead of
        # the retry interval. Runtime traffic stays guarded.
        probe_s = getattr(self._s, "inner", self._s)
        while True:
            reachable = False
            attempts += 1
            try:
                r = probe_s.get(self.endpoint, timeout=self.timeout)
                reachable = r.ok
                if not reachable:
                    last_error = f"HTTP {r.status_code}"
            except Exception as e:
                last_error = f"{type(e).__name__}: {e}"
            self.connect_state = {
                "connected": False,
                "attempts": attempts,
                "waiting_seconds": round(time.time() - start, 3),
                "last_error": last_error,
            }
            if reachable:
                # connectivity retries are silent (the reference's
                # forever-retry loop); index/mapping problems are CONFIG
                # errors and must not be mistaken for "ES still down" —
                # permanent (4xx / divergence) raises, transient (5xx,
                # races during cluster start) logs and retries
                try:
                    self.ensure_index()
                    self.connect_state = {
                        "connected": True,
                        "attempts": attempts,
                        "waiting_seconds": round(time.time() - start, 3),
                        "last_error": None,
                    }
                    return True
                except MappingDivergence:
                    raise
                except Exception as e:
                    status = getattr(
                        getattr(e, "response", None), "status_code", None
                    )
                    if status is not None and 400 <= status < 500 and status != 429:
                        raise
                    last_error = f"ensure_index: {e}"
                    log.warning("ensure_index failed, retrying: %s", e)
            if max_wait is not None and time.time() - start > max_wait:
                return False
            # sleep in short slices so a stop request (SIGTERM during
            # startup) is honored within ~a quarter second, not after a
            # full retry interval
            deadline = time.time() + retry_seconds
            while True:
                if stop is not None and stop():
                    return False
                remaining = deadline - time.time()
                if remaining <= 0:
                    break
                time.sleep(min(0.25, remaining))

    # claim()'s server-side semantics stand on exactly these field types;
    # ensure_index verifies them against a pre-existing index's live
    # mapping (full equality would be too strict — ES normalizes
    # mappings and other fields are never queried)
    CLAIM_CRITICAL_TYPES = {
        "status": "keyword",
        "processingContent": "keyword",
        "appName": "keyword",
        "createdAt": "date",
        "modifiedAt": "date",
    }

    def ensure_index(self) -> bool:
        """Create the `documents` index with the explicit mappings.

        Idempotent — but NOT blindly so: an existing index (a previous
        deployment, or an auto-created one from a write that raced ahead
        of wait_ready) answers 400 resource_already_exists, and its LIVE
        mapping is then fetched and checked against the claim-critical
        field types; divergence raises `MappingDivergence` instead of
        silently running the claim query against analyzed-text/dynamic
        fields (the exact luck this template exists to remove). ES
        forbids changing existing field types in place, so divergence
        needs operator action (reindex), not a retry."""
        r = self._s.put(
            self._url(), json={"mappings": INDEX_MAPPINGS}, timeout=self.timeout
        )
        if r.status_code == 400:
            body = {}
            try:
                body = r.json()
            except Exception:
                pass
            err = str(body.get("error", body))
            if "resource_already_exists" not in err:
                r.raise_for_status()
            rm = self._s.get(self._url("_mapping"), timeout=self.timeout)
            rm.raise_for_status()
            props = (
                rm.json()
                .get(self.INDEX, {})
                .get("mappings", {})
                .get("properties", {})
            )
            bad = {
                f: (props.get(f) or {}).get("type")
                for f, want in self.CLAIM_CRITICAL_TYPES.items()
                if (props.get(f) or {}).get("type") != want
            }
            if bad:
                raise MappingDivergence(
                    f"index '{self.INDEX}' exists with incompatible mappings "
                    f"{bad}; claim semantics require "
                    f"{self.CLAIM_CRITICAL_TYPES} — reindex required"
                )
            # additive upgrade: fields the template gained since the
            # index was created (e.g. traceId) would otherwise fall to
            # dynamic mapping on first write — analyzed text + doc_values
            # for a field the template pins as unindexed keyword. ES
            # allows ADDING fields in place, so pin them now; best-effort
            # because dynamic mapping is merely today's pre-upgrade cost.
            missing = {
                f: spec
                for f, spec in INDEX_MAPPINGS["properties"].items()
                if f not in props
            }
            if missing:
                pm = self._s.put(
                    self._url("_mapping"),
                    json={"properties": missing},
                    timeout=self.timeout,
                )
                if pm.status_code >= 400:
                    log.warning(
                        "could not add %s to existing '%s' mapping "
                        "(HTTP %d); new fields will be dynamically mapped",
                        sorted(missing),
                        self.INDEX,
                        pm.status_code,
                    )
            return True
        r.raise_for_status()
        return True

    # -- JobStore -------------------------------------------------------

    def create(self, doc: Document) -> tuple[Document, bool]:
        existing = self.get(doc.id)
        if existing is not None:
            return existing, False
        doc.created_at = doc.created_at or now_rfc3339()
        doc.modified_at = now_rfc3339()
        r = self._s.put(
            self._url("_doc", doc.id) + "?op_type=create",
            json=doc.to_json(),
            timeout=self.timeout,
        )
        if r.status_code == 409:  # lost the race — fetch winner
            return self.get(doc.id) or doc, False
        r.raise_for_status()
        return doc, True

    def get(self, doc_id: str) -> Document | None:
        r = self._s.get(self._url("_doc", doc_id), timeout=self.timeout)
        if r.status_code == 404:
            return None
        r.raise_for_status()
        body = r.json()
        if not body.get("found"):
            return None
        return Document.from_json(body["_source"])

    def claim(
        self,
        worker_id: str,
        max_stuck_seconds: float,
        limit: int = 64,
        claim_filter=None,
    ):
        """Claim up to `limit` docs in exactly TWO round trips.

        (1) a server-side claimability search — fresh work (`initial` /
        `preprocess_completed`) OR stuck in-progress docs (`modified_at`
        older than the stuck cutoff), sorted oldest-first so a crowd of
        recently-touched in-progress docs can never fill the page and
        starve fresh jobs; (2) one `_bulk` request carrying a
        seq_no/primary_term CAS per doc — items another worker won come
        back 409 and are skipped. (The previous shape — match any
        claimable status, then one CAS PUT per hit — was O(limit) round
        trips and page-starvation-prone.)

        `claim_filter` (mesh partitioning) applies CLIENT-SIDE between
        the search and the bulk CAS — a hash-ring ownership test cannot
        be expressed as an ES query. Filtered hits are simply not CASed,
        so they stay claimable for their owner; mesh workers size
        `limit` to the fleet, so one page still reaches every partition.

        Contention decorrelation: the page is OVERSAMPLED (2x limit)
        and the fresh hits are shuffled before up-to-`limit` CAS
        attempts. Concurrent shared-nothing claimers all receive the
        same oldest-first page; if every worker CASed its head, one
        bulk would win the whole page and the losers' ticks would claim
        NOTHING (a measured winner-takes-all race —
        tests/test_multihost_worker.py). Shuffled subsets of a 2x page
        overlap only partially, so contending workers each win a share
        per tick. Stuck-takeover hits keep strict oldest-first priority
        ahead of the shuffle (the starvation guarantee), and the
        ES-side sort still bounds which docs enter the page at all.
        """
        now = time.time()
        cutoff = datetime.fromtimestamp(
            now - max_stuck_seconds, timezone.utc
        ).strftime("%Y-%m-%dT%H:%M:%SZ")
        # children of the worker's claim stage span: the two ES round
        # trips (search, bulk CAS) separate on the trace timeline, so a
        # slow claim attributes to the store, not to scoring
        query = {
            "size": min(2 * limit, limit + 512),
            "seq_no_primary_term": True,  # required for the CAS below
            "sort": [{"modifiedAt": {"order": "asc", "unmapped_type": "date"}}],
            "query": {
                "bool": {
                    "should": [
                        {
                            "terms": {
                                "status": [
                                    STATUS_INITIAL,
                                    STATUS_PREPROCESS_COMPLETED,
                                ]
                            }
                        },
                        {
                            "bool": {
                                "must": [
                                    {
                                        "terms": {
                                            "status": list(INPROGRESS_STATUSES)
                                        }
                                    },
                                    {"range": {"modifiedAt": {"lt": cutoff}}},
                                ]
                            }
                        },
                    ],
                    "minimum_should_match": 1,
                }
            },
        }
        with span("es.claim_search", limit=limit):
            r = self._s.post(
                self._url("_search"), json=query, timeout=self.timeout
            )
            r.raise_for_status()
        hits = r.json().get("hits", {}).get("hits", [])

        import json as _json

        if len(hits) > limit:
            # decorrelate concurrent claimers (see docstring): stuck
            # takeovers stay strictly oldest-first, fresh hits shuffle
            stuck = [
                h
                for h in hits
                if h["_source"].get("status") in INPROGRESS_STATUSES
            ]
            fresh = [
                h
                for h in hits
                if h["_source"].get("status") not in INPROGRESS_STATUSES
            ]
            random.shuffle(fresh)
            hits = stuck + fresh

        lines: list[str] = []
        docs: list[Document] = []
        for h in hits:
            if len(docs) >= limit:
                break
            doc = Document.from_json(h["_source"])
            # partition filter BEFORE the CAS: a foreign doc must stay
            # claimable for its owner, not get parked in-progress here
            if claim_filter is not None and not claim_filter(doc):
                continue
            # defense in depth: the server answered claimability, but a
            # mapping/clock divergence must never double-claim
            if not _is_claimable(doc, now, max_stuck_seconds):
                continue
            doc.status = STATUS_PREPROCESS_INPROGRESS
            doc.modified_at = now_rfc3339()
            doc.processing_content = worker_id
            action: dict = {"index": {"_id": doc.id}}
            if "_seq_no" in h:
                action["index"]["if_seq_no"] = h["_seq_no"]
                action["index"]["if_primary_term"] = h["_primary_term"]
            lines.append(_json.dumps(action))
            lines.append(_json.dumps(doc.to_json()))
            docs.append(doc)
        if not docs:
            return []
        with span("es.claim_bulk_cas", docs=len(docs)):
            rr = self._s.post(
                self._url("_bulk"),
                data="\n".join(lines) + "\n",
                headers={"Content-Type": "application/x-ndjson"},
                timeout=self.timeout,
            )
            rr.raise_for_status()
        items = rr.json().get("items", [])
        out = []
        for doc, item in zip(docs, items):
            status = item.get("index", {}).get("status", 500)
            if status in (200, 201):
                out.append(doc)
            elif status != 409:
                # 409 = another worker won (expected, skip); anything else
                # (read-only index block, 429 rejections, mapping errors)
                # must SURFACE like the old per-doc CAS path did — a
                # silent [] would stop the claim pipeline with no signal
                raise RuntimeError(
                    f"bulk claim item failed for {doc.id}: {item}"
                )
        return out

    def update(self, doc: Document) -> Document:
        doc.modified_at = now_rfc3339()
        r = self._s.put(
            self._url("_doc", doc.id), json=doc.to_json(), timeout=self.timeout
        )
        r.raise_for_status()
        return doc

    def update_many(self, docs: list[Document]) -> None:
        """One `_bulk` request for a whole tick's write-backs — a fleet
        tick finalizes thousands of docs, and a PUT per doc would make
        write-back latency scale with claim size (same rationale as the
        two-round-trip claim). No CAS here: the docs are owned by this
        worker's in-progress claim, and last-writer-wins matches the
        per-doc update() semantics."""
        if not docs:
            return
        import json as _json

        stamp = now_rfc3339()
        lines = []
        for doc in docs:
            doc.modified_at = stamp
            lines.append(_json.dumps({"index": {"_id": doc.id}}))
            lines.append(_json.dumps(doc.to_json()))
        with span("es.update_bulk", docs=len(docs)):
            r = self._s.post(
                self._url("_bulk"),
                data="\n".join(lines) + "\n",
                headers={"Content-Type": "application/x-ndjson"},
                timeout=self.timeout,
            )
            r.raise_for_status()
        body = r.json()
        if body.get("errors"):
            for item in body.get("items", []):
                info = item.get("index", {})
                if info.get("status", 200) >= 300:
                    raise RuntimeError(
                        f"bulk update item failed for {info.get('_id')}: {item}"
                    )

    _OPEN_QUERY = {
        "bool": {"must_not": {"terms": {"status": list(TERMINAL_STATUSES)}}}
    }

    def list_open(self):
        query = {"size": 1000, "query": self._OPEN_QUERY}
        r = self._s.post(self._url("_search"), json=query, timeout=self.timeout)
        r.raise_for_status()
        return [
            Document.from_json(h["_source"])
            for h in r.json().get("hits", {}).get("hits", [])
        ]

    def list_app(self, app_name: str) -> list[Document]:
        # server-side term query: mesh member records must be findable
        # regardless of how many fleet documents share the index (the
        # base-class list_open page would starve them out at scale).
        # Matches the base contract — OPEN documents only (the InMemory
        # override filters terminal statuses too); the page bounds an
        # app with pathologically many open docs, which membership (a
        # handful of records under one app) never approaches.
        query = {
            "size": 1000,
            "query": {
                "bool": {
                    "must": [{"terms": {"appName": [app_name]}}],
                    "must_not": {
                        "terms": {"status": list(TERMINAL_STATUSES)}
                    },
                }
            },
        }
        r = self._s.post(self._url("_search"), json=query, timeout=self.timeout)
        r.raise_for_status()
        return [
            Document.from_json(h["_source"])
            for h in r.json().get("hits", {}).get("hits", [])
        ]

    def count_open(self) -> int:
        # runs on probe/varz handler threads: uses the dedicated probe
        # session (never self._s, which the tick thread owns); the short
        # timeout keeps liveness probes fast even when ES is wedged
        # _probe_lock exists to serialize the one probe Session between
        # scrape/health threads — the HTTP round trip IS its critical
        # section; worker ticks use the main session, never this lock
        with span("es.count_open"), self._probe_lock:
            # foremast: ignore[blocking-under-lock]
            r = self._probe_s.post(
                self._url("_count"),
                json={"query": self._OPEN_QUERY},
                timeout=min(self.timeout, 2.0),
            )
            r.raise_for_status()
        return int(r.json().get("count", 0))
