"""Provisional-fit bookkeeping for short-history admission (ISSUE 10).

A newcomer admitted on 1-2 days of ring coverage (`RingSource.
hist_columns` status "partial") carries a PROVISIONAL fit: verdict-
capable now, but fitted on less history than the document requested.
This module is the worker's ledger of those fits and the pacing logic
that upgrades them in the background:

  * `RefineBook.note_*` records a provisional fit the moment the
    worker's fetch path admits one (fetch-pool threads — the book is
    lock-guarded);
  * on idle/steady ticks the worker drains up to
    `FOREMAST_REFINE_DOCS_PER_TICK` records through `take()` (round-
    robin, so no record starves) and INVALIDATES the ones whose ring
    coverage grew enough — the next claim refits them from the ring
    through the production slow path, which is what makes the
    band-parity guarantee trivial: a refined fit IS a from-scratch fit
    on the larger window, same code path, same columns;
  * growth is paced geometrically (`GROWTH_FACTOR`): each refit needs
    ~50% more points than the last, so a fit refines O(log) times on
    its way from the admission floor to the full 7-day window, not
    once per tick;
  * a record FINALIZES (one last refit, then dropped) when the ring
    covers the full requested window or the window's end — past the
    window head nothing new can arrive inside it, so that refit is the
    terminal, from-scratch-identical one.

The book is bounded: past `cap` the oldest record is dropped (its fit
simply stays at whatever refinement it last reached — degraded pacing,
never a wrong verdict).
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict

from foremast_tpu.models.cache import is_pad_fit_key

# each refit must see ~this factor more points than the previous fit —
# geometric pacing bounds a fit's lifetime refits to O(log(full/floor))
GROWTH_FACTOR = 1.5

DEFAULT_CAP = 16_384

DEFAULT_REFINE_DOCS_PER_TICK = 256


def refine_docs_per_tick_from_env() -> int:
    """THE resolution of FOREMAST_REFINE_DOCS_PER_TICK (empty string
    means unset) — one definition so the worker's budget and the cli's
    startup log can never report different values."""
    return int(
        os.environ.get("FOREMAST_REFINE_DOCS_PER_TICK", "")
        or DEFAULT_REFINE_DOCS_PER_TICK
    )


class RefineBook:
    """Thread-safe ledger of provisional fits awaiting refinement.

    Records are keyed ("uni", fit_cache_fullkey) for univariate fits
    and ("joint", doc_id) for joint docs (whose cache keys the worker
    resolves through its admission cache at invalidation time). Each
    record carries the historical URLs to probe and the point count
    the current fit was made from.
    """

    def __init__(self, cap: int = DEFAULT_CAP):
        self.cap = int(cap)
        self._lock = threading.Lock()
        self._recs: OrderedDict[tuple, dict] = OrderedDict()
        self._counts = {
            "noted": 0, "refit": 0, "finalized": 0, "settled": 0,
            "dropped": 0,
        }
        # optional write-through hook (FitJournal.append, duck-typed
        # like ModelCache.journal): provisional records must survive a
        # restart — the PR-7 fit journals restore the FIT warm, so the
        # restored doc takes the fast path and nothing would ever
        # re-note it; without persistence the fit stays parked at its
        # admitted short history forever. Called OUTSIDE self._lock
        # (the ModelCache precedent — per-key records, last-write-wins)
        self.journal = None

    # -- write side (fetch-pool threads) ---------------------------------

    def _note(self, bkey: tuple, rec: dict) -> None:
        # defense in depth (ISSUE 13 satellite): a batch-padding fit
        # key must never become a provisional record — refinement would
        # chase a document that does not exist, and the provisional
        # gauge would count dispatch artifacts as fleet debt. The
        # worker's note_* calls are keyed off real doc aliases today;
        # this guard keeps that true for every future caller too.
        if is_pad_fit_key(bkey):
            return
        puts: list = []
        dels: list = []
        with self._lock:
            prev = self._recs.get(bkey)
            if prev is None:
                self._counts["noted"] += 1
                self._recs[bkey] = rec
                puts.append((bkey, dict(rec)))
            else:
                # re-noted after a refit: update the fitted point count
                # (pacing baseline), keep the round-robin position
                prev["points"] = rec["points"]
                prev["urls"] = rec["urls"]
                puts.append((bkey, dict(prev)))
            while len(self._recs) > self.cap:
                k, _ = self._recs.popitem(last=False)
                self._counts["dropped"] += 1
                dels.append((k, None))
        j = self.journal
        if j is not None:
            j(puts)
            if dels:
                j(dels, deleted=True)

    def note_uni(
        self, fullkey, gap_key, url: str, points: int
    ) -> None:
        """One univariate alias fitted on a partial ring window."""
        self._note(
            ("uni", fullkey),
            {
                "kind": "uni",
                "fullkey": fullkey,
                "gap_key": gap_key,
                "urls": (url,),
                "points": int(points),
            },
        )

    def note_joint(
        self, doc_id: str, app: str, urls: tuple, points: int
    ) -> None:
        """A joint (multi-alias) doc fitted on partial ring windows.
        `app` rides along because the joint judge's slow-path cache
        keys carry no history content — invalidating a doc that never
        warmed into the fast-path admission cache has to pop by app."""
        self._note(
            ("joint", doc_id),
            {
                "kind": "joint",
                "doc_id": doc_id,
                "app": app,
                "urls": tuple(urls),
                "points": int(points),
            },
        )

    # -- refinement pass (tick thread) -----------------------------------

    def take(self, limit: int) -> list[tuple[tuple, dict]]:
        """Up to `limit` records in round-robin order: taken records
        rotate to the back so every record gets probed eventually even
        when the book outnumbers the per-tick budget."""
        with self._lock:
            n = min(int(limit), len(self._recs))
            out = []
            for _ in range(n):
                bkey, rec = self._recs.popitem(last=False)
                self._recs[bkey] = rec  # rotate to the back
                out.append((bkey, dict(rec)))
            return out

    def refit(self, bkey: tuple, points: int) -> None:
        """Record a growth-triggered invalidation: the record stays
        provisional with the new pacing baseline."""
        snap = None
        with self._lock:
            rec = self._recs.get(bkey)
            if rec is None:
                # evicted by a cap-pressed note_* between take() and
                # here — nothing is being paced, so nothing to count
                return
            rec["points"] = int(points)
            self._counts["refit"] += 1
            snap = dict(rec)
        j = self.journal
        if j is not None:
            j([(bkey, snap)])

    def drop(self, bkey: tuple, reason: str = "finalized") -> None:
        """Remove a record (reason "finalized" after the terminal
        refit, "settled" when the window closed with nothing left to
        refit, "dropped" when the ring lost the series)."""
        removed = False
        with self._lock:
            if self._recs.pop(bkey, None) is not None:
                self._counts[reason] += 1
                removed = True
        j = self.journal
        if removed and j is not None:
            j([(bkey, None)], deleted=True)

    # -- persistence (duck-typed FitJournal surface) ----------------------

    def restore_lazy(self, items) -> int:
        """Seed restored records (FitJournal.restore output); resident
        records win. Named for the ModelCache surface FitJournal
        attaches to — the book is small, so restore is eager."""
        with self._lock:
            n = 0
            for k, v in dict(items).items():
                if k not in self._recs:
                    self._recs[k] = dict(v)
                    n += 1
            while len(self._recs) > self.cap:
                self._recs.popitem(last=False)
            return n

    def persistable_snapshot(self) -> dict:
        """Point-in-time copy for journal compaction."""
        with self._lock:
            return {k: dict(v) for k, v in self._recs.items()}

    @staticmethod
    def due(points_then: int, points_now: int) -> bool:
        """Geometric pacing rule: is a refit worth it yet?"""
        return points_now >= max(
            points_then + 1, int(points_then * GROWTH_FACTOR)
        )

    # -- introspection ----------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._recs)

    def debug_state(self) -> dict:
        with self._lock:
            return {"pending": len(self._recs), **self._counts}
