"""Per-series columnar ring buffer: (int64 times, float32 values) columns.

One `SeriesRing` holds one metric series as a pair of pow2-sized numpy
columns written circularly — the in-memory mirror of the shape every
`MetricSource.fetch` already returns, so a warm query is two
`searchsorted` calls and a slice copy, never a parse. Capacity starts
small and doubles up to `max_points`; past that the ring overwrites its
oldest samples (a 7-day 60 s-step history is 10,080 points, so the
16,384-point default ceiling keeps a full reference history resident
with headroom).

Thread-ownership contract: a SeriesRing has NO lock of its own — it is
only ever touched under its owning shard's lock (`shards.RingShard`),
the same single-writer discipline the model caches use. Keeping the
lock one level up lets a shard evict and account bytes atomically with
the mutation that overflowed them.

Coverage intervals: the ring records the (few) contiguous spans it is
AUTHORITATIVE for — extended by live pushes and by backfills' requested
windows, advanced past samples dropped by overwrite. Coverage is a
bounded SET of disjoint intervals, not one: a 7-day-old historical
backfill and a live push stream are disjoint spans, and rounds 5-8
kept only whichever ended later — so every cold doc of the same app
re-paid the historical HTTP fetch the moment a live push landed
(ISSUE 10 satellite: the fallback's backfill write-through must STICK).
What a set must still never imply is that the gap between two fetched
windows was empty: a query is served only when one single interval
covers it (within the staleness slack), so a window sliding into the
gap between the historical span and the live head degrades to the pull
path exactly as before. Intervals within the merge slack of each other
coalesce; past `MAX_COVER_INTERVALS` the span with the oldest head is
dropped (that authority degrades back to the pull path, never to a
wrong answer).
"""

from __future__ import annotations

import numpy as np

MIN_CAPACITY = 256
DEFAULT_MAX_POINTS = 16_384  # pow2 >= the reference 10,080-pt history

# Bound on the disjoint coverage-interval set (module docstring): one
# live span + a historical backfill span is the common case, a couple
# more absorbs racing backfills of different ranges; past it the span
# with the oldest head degrades to the pull path.
MAX_COVER_INTERVALS = 4

# fixed per-sample storage cost: int64 time + float32 value
BYTES_PER_POINT = 12


def _pow2(n: int) -> int:
    cap = 1
    while cap < n:
        cap <<= 1
    return cap


def empty_series() -> tuple[np.ndarray, np.ndarray]:
    """The ring dtypes' empty series — one definition for the package
    (shards' miss results, the source's pure-push empties)."""
    return np.zeros(0, np.int64), np.zeros(0, np.float32)


class SeriesRing:
    """One series' sample window. All methods assume the owning shard's
    lock is held (see module docstring)."""

    __slots__ = ("_times", "_values", "_start", "_count", "max_points",
                 "_cov")

    def __init__(
        self,
        capacity: int = MIN_CAPACITY,
        max_points: int = DEFAULT_MAX_POINTS,
    ):
        self.max_points = _pow2(max(int(max_points), 1))
        cap = _pow2(max(1, min(int(capacity), self.max_points)))
        self._times = np.zeros(cap, np.int64)
        self._values = np.zeros(cap, np.float32)
        self._start = 0
        self._count = 0
        # disjoint authoritative spans as [from, to] pairs, sorted by
        # `from` (disjointness makes that sorted by `to` as well, so
        # the LAST entry is always the live head span)
        self._cov: list[list[float]] = []

    # -- coverage --------------------------------------------------------

    @property
    def covered_from(self) -> float | None:
        """Start of the HEAD span (the one with the newest authority) —
        the single-interval view stats and staleness accounting keep."""
        return self._cov[-1][0] if self._cov else None

    @property
    def covered_to(self) -> float | None:
        return self._cov[-1][1] if self._cov else None

    @property
    def head_interval(self) -> tuple[float, float] | None:
        return tuple(self._cov[-1]) if self._cov else None

    def intervals(self) -> list[tuple[float, float]]:
        """Every authoritative span, oldest first (snapshot/debug)."""
        return [tuple(iv) for iv in self._cov]

    def covering(
        self, t0: float | None, step: float
    ) -> tuple[float, float] | None:
        """The best span authoritative AT `t0` (its start within one
        `step` of the window start), or the head span for unbounded
        queries; None when no span reaches back to `t0`."""
        best = None
        for iv in self._cov:
            if t0 is None or iv[0] <= t0 + step:
                if best is None or iv[1] > best[1]:
                    best = iv
        return None if best is None else tuple(best)

    def _cover(self, b0: float, b1: float, slack: float) -> None:
        """Fold the batch's authoritative window into the span set:
        spans overlapping (or within `slack` of) [b0, b1] coalesce with
        it; a disjoint window becomes its own span, bounded by
        MAX_COVER_INTERVALS (oldest-head span dropped past it)."""
        lo, hi = b0, b1
        keep = []
        for iv in self._cov:
            if iv[1] >= lo - slack and iv[0] <= hi + slack:
                lo = min(lo, iv[0])
                hi = max(hi, iv[1])
            else:
                keep.append(iv)
        keep.append([lo, hi])
        keep.sort(key=lambda iv: iv[0])
        while len(keep) > MAX_COVER_INTERVALS:
            keep.remove(min(keep, key=lambda iv: iv[1]))
        self._cov = keep

    def _clamp_coverage(self, dropped_to: float) -> None:
        """Overwrite dropped resident samples: no span may claim
        authority before the oldest RETAINED sample. (Spans are never
        clamped merely to the oldest sample — a covered range may be
        provably empty.)"""
        out = []
        for iv in self._cov:
            if iv[1] < dropped_to:
                continue  # entirely before the retained region
            out.append([max(iv[0], dropped_to), iv[1]])
        self._cov = out

    # -- introspection ---------------------------------------------------

    def __len__(self) -> int:
        return self._count

    @property
    def capacity(self) -> int:
        return len(self._times)

    @property
    def nbytes(self) -> int:
        """Allocated column bytes — what the shard budget accounts."""
        return self._times.nbytes + self._values.nbytes

    @property
    def oldest(self) -> int | None:
        if not self._count:
            return None
        return int(self._times[self._start])

    @property
    def newest(self) -> int | None:
        if not self._count:
            return None
        cap = len(self._times)
        return int(self._times[(self._start + self._count - 1) % cap])

    def _segments(self) -> tuple[np.ndarray, np.ndarray]:
        """The ring's live region as (times, values) in time order;
        zero-copy when unwrapped, one concatenate when wrapped."""
        cap = len(self._times)
        s, n = self._start, self._count
        if s + n <= cap:
            return self._times[s : s + n], self._values[s : s + n]
        head = cap - s
        return (
            np.concatenate([self._times[s:], self._times[: n - head]]),
            np.concatenate([self._values[s:], self._values[: n - head]]),
        )

    # -- mutation (shard lock held) --------------------------------------

    def append(
        self,
        times: np.ndarray,
        values: np.ndarray,
        start: float | None = None,
        end: float | None = None,
        slack: float = 0.0,
    ) -> int:
        """Insert samples; returns the number accepted. Strictly-newer
        ordered batches take the circular fast path; overlapping or
        out-of-order batches merge (sort + dedup, last write wins per
        timestamp — remote-write semantics).

        `start`/`end` are the batch's authoritative window (a backfill
        asserting "the fallback answered for exactly [start, end]");
        without them the batch covers its own sample span (a live
        push). The batch's window coalesces with any coverage span it
        overlaps or abuts within `slack` seconds; a DISJOINT batch
        becomes its own span (see module docstring — a historical
        backfill stays authoritative next to the live push stream,
        while the gap between them stays on the pull path). A batch may
        be empty when `start`/`end` are given (backfilling a
        provably-empty range)."""
        ts = np.asarray(times, np.int64)
        vs = np.asarray(values, np.float32)
        n = len(ts)
        if n != len(vs):
            raise ValueError("times/values length mismatch")
        dropped_to = None
        if n:
            ordered = bool(np.all(np.diff(ts) > 0))
            newest = self.newest
            if ordered and (newest is None or int(ts[0]) > newest):
                dropped_to = self._append_ordered(ts, vs)
            else:
                dropped_to = self._merge(ts, vs)
        # sample-derived bounds use min/max, not ts[0]/ts[-1]: an
        # out-of-order push batch must not record a collapsed window
        b0 = float(start) if start is not None else (
            float(ts.min()) if n else None
        )
        b1 = float(end) if end is not None else (
            float(ts.max()) if n else None
        )
        if b0 is None and b1 is not None:
            # empty backfill of an unbounded-start window: the fallback
            # vouched for emptiness up to `end` — record point coverage
            # at the head so the series still warms (an unbounded query
            # passes no tail requirement)
            b0 = b1
        if b0 is not None:
            b1 = b0 if b1 is None else max(b0, b1)
            self._cover(b0, b1, slack)
        if dropped_to is not None and self._cov:
            self._clamp_coverage(float(dropped_to))
        return n

    def _append_ordered(self, ts: np.ndarray, vs: np.ndarray):
        """Returns the oldest retained timestamp when samples were
        dropped (the caller clamps coverage there), else None."""
        n = len(ts)
        dropped = False
        if n >= self.max_points:
            # batch alone fills the ring: keep its newest tail
            dropped = self._count > 0 or n > self.max_points
            ts, vs = ts[-self.max_points :], vs[-self.max_points :]
            n = len(ts)
        while self._count + n > self.capacity and self.capacity < self.max_points:
            self._grow()
        cap = self.capacity
        overflow = self._count + n - cap
        if overflow > 0:  # drop oldest resident samples
            self._start = (self._start + overflow) % cap
            self._count -= overflow
            dropped = True
        pos = (self._start + self._count) % cap
        first = min(n, cap - pos)
        self._times[pos : pos + first] = ts[:first]
        self._values[pos : pos + first] = vs[:first]
        if first < n:
            self._times[: n - first] = ts[first:]
            self._values[: n - first] = vs[first:]
        self._count += n
        return self.oldest if dropped else None

    def _merge(self, ts: np.ndarray, vs: np.ndarray):
        """Returns the oldest retained timestamp when the max_points
        trim dropped samples, else None (see _append_ordered)."""
        old_t, old_v = self._segments()
        all_t = np.concatenate([old_t, ts])
        all_v = np.concatenate([old_v, vs])
        order = np.argsort(all_t, kind="stable")
        all_t = all_t[order]
        all_v = all_v[order]
        # stable sort keeps insertion order within equal timestamps, so
        # keeping the LAST of each run is last-write-wins
        keep = np.ones(len(all_t), bool)
        keep[:-1] = all_t[1:] != all_t[:-1]
        all_t = all_t[keep]
        all_v = all_v[keep]
        dropped = len(all_t) > self.max_points
        if dropped:
            all_t = all_t[-self.max_points :]
            all_v = all_v[-self.max_points :]
        cap = _pow2(max(len(all_t), MIN_CAPACITY))
        cap = min(max(cap, self.capacity), self.max_points)
        self._times = np.zeros(cap, np.int64)
        self._values = np.zeros(cap, np.float32)
        self._times[: len(all_t)] = all_t
        self._values[: len(all_v)] = all_v
        self._start = 0
        self._count = len(all_t)
        return int(all_t[0]) if dropped and len(all_t) else None

    def _grow(self) -> None:
        t, v = self._segments()
        cap = min(self.capacity * 2, self.max_points)
        self._times = np.zeros(cap, np.int64)
        self._values = np.zeros(cap, np.float32)
        self._times[: len(t)] = t
        self._values[: len(v)] = v
        self._start = 0
        self._count = len(t)

    # -- queries (shard lock held) ---------------------------------------

    def window(self, t0: float | None, t1: float | None) -> tuple[np.ndarray, np.ndarray]:
        """Copy of the samples with ``t0 <= t <= t1`` (either bound may
        be None for "unbounded"), in time order."""
        t, v = self._segments()
        lo = 0 if t0 is None else int(np.searchsorted(t, t0, side="left"))
        hi = len(t) if t1 is None else int(np.searchsorted(t, t1, side="right"))
        return t[lo:hi].copy(), v[lo:hi].copy()

    def count_window(self, t0: float | None, t1: float | None) -> int:
        """How many samples ``t0 <= t <= t1`` holds — no column copy
        (the refinement planner's coverage probe, ISSUE 10)."""
        t, _ = self._segments()
        lo = 0 if t0 is None else int(np.searchsorted(t, t0, side="left"))
        hi = len(t) if t1 is None else int(np.searchsorted(t, t1, side="right"))
        return hi - lo
