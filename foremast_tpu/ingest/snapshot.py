"""Durable ring state: per-shard column snapshots + append logs.

The ingest plane made the data plane stateful — a worker restart used
to mean a fleet-wide cold start (every series re-fetched over HTTP,
every history re-uploaded). This module makes restarts warm: under
`FOREMAST_SNAPSHOT_DIR` each ring shard gets

  * a SNAPSHOT file (``ring-<i>.snap.npz``): the shard's resident
    series as raw int64/float32 columns plus their
    ``covered_from``/``covered_to`` watermarks, written to a temp file
    and published with one atomic ``os.replace`` — a reader never sees
    a half-written snapshot under its final name;
  * an APPEND LOG (``ring-<i>.log``): every push between snapshots,
    one crc-framed record each, flushed at write time so the bytes
    survive a SIGKILL (page cache outlives the process; only power
    loss needs fsync, which judgment data does not warrant).

`RingSnapshotter.restore()` replays snapshot + log into a fresh
`RingStore` through the store's own `push` (so budget accounting,
eviction, and coverage semantics are the production ones), applies the
snapshot-age cutoff, and DEGRADES PER SERIES: a torn log tail, a
version-mismatched header, a truncated snapshot file, or one broken
series inside an otherwise healthy snapshot each discard only the
affected state — counted on the `foremast_snapshot_*` families, never
a crash. A discarded series simply cold-fits through the existing
fallback path on its next fetch.

Snapshot ordering is crash-consistent without fsync barriers: the
current log is first ROTATED aside to a fresh ``.log.old.<N>``
generation (ratcheting — an earlier crash's rotated log is never
clobbered), a new log opened, THEN the shard state captured and the
snapshot renamed into place, and only then ALL rotated generations
deleted. A crash between any two steps leaves a state where
{latest durable snapshot} + {rotated generations, oldest first} +
{live log} together hold every journaled push — restore replays them
in exactly that order, and the ring's last-write-wins merge makes
double-applied samples a no-op.
"""

from __future__ import annotations

import json
import logging
import os
import pickle
import re
import struct
import threading
import time
import zlib

import numpy as np

from foremast_tpu.ingest.shards import RingStore

log = logging.getLogger("foremast_tpu.ingest")

SNAP_VERSION = 1
_LOG_MAGIC = b"FMRL"
# magic(4) + payload_len(u32) + crc32(u32)
_LOG_HEADER = struct.Struct("<4sII")

DEFAULT_INTERVAL_SECONDS = 60.0
DEFAULT_MAX_AGE_SECONDS = 86_400.0
DEFAULT_LOG_MAX_BYTES = 64 * 1024 * 1024

# discard reasons (the `foremast_snapshot_discards{reason}` label set);
# fit-journal reasons live here too so one family covers the data plane
DISCARD_REASONS = (
    "version",        # snapshot header from a different format version
    "unreadable",     # snapshot file truncated/corrupt past np.load
    "series",         # one series' arrays inconsistent (mid-eviction
                      # capture, external corruption) — that series only
    "stale",          # covered_to older than the restore age cutoff
    "torn_log",       # append-log tail cut mid-record (crash mid-write)
    "fit_unreadable", # fit-journal snapshot unreadable
    "fit_torn",       # fit-journal log tail cut mid-record
)


def _empty_discards() -> dict:
    return dict.fromkeys(DISCARD_REASONS, 0)


# ---------------------------------------------------------------------------
# crc-framed append-log records (shared with models.cache.FitJournal)
# ---------------------------------------------------------------------------


def append_record(fh, payload: bytes) -> int:
    """Frame + append one payload; returns bytes written. The caller
    holds whatever lock serializes the file handle."""
    header = _LOG_HEADER.pack(_LOG_MAGIC, len(payload), zlib.crc32(payload))
    fh.write(header + payload)
    fh.flush()  # page cache now owns the bytes: SIGKILL-safe
    return len(header) + len(payload)


def read_record_stream(fh):
    """Yield (payload, None) per intact record from an open binary
    stream, then (None, "torn_log") once if the tail is torn — short
    header/payload, bad magic, crc mismatch. Everything BEFORE the
    first bad frame is served; nothing after it is trusted (a corrupt
    length field would desync every later frame). Shared by the
    append-log replay below and the mesh handoff's transfer decoder
    (mesh/handoff.py) — one definition of "healthy prefix"."""
    while True:
        header = fh.read(_LOG_HEADER.size)
        if not header:
            return  # clean EOF
        if len(header) < _LOG_HEADER.size:
            yield None, "torn_log"
            return
        magic, length, crc = _LOG_HEADER.unpack(header)
        if magic != _LOG_MAGIC:
            yield None, "torn_log"
            return
        payload = fh.read(length)
        if len(payload) < length or zlib.crc32(payload) != crc:
            yield None, "torn_log"
            return
        yield payload, None


def read_records(path: str):
    """`read_record_stream` over a file path (missing file = no records)."""
    try:
        fh = open(path, "rb")
    except OSError:
        return
    with fh:
        yield from read_record_stream(fh)


def rotated_logs(base_path: str) -> list[str]:
    """Every ``<base>.old.<N>`` generation, oldest first — the replay
    order that reproduces the original append order across crashes."""
    d = os.path.dirname(os.path.abspath(base_path)) or "."
    prefix = os.path.basename(base_path) + ".old."
    out = []
    try:
        names = os.listdir(d)
    except OSError:
        return []
    for name in names:
        if name.startswith(prefix):
            tail = name[len(prefix):]
            if tail.isdigit():
                out.append((int(tail), os.path.join(d, name)))
    return [p for _, p in sorted(out)]


def lock_snapshot_dir(directory: str):
    """Advisory EXCLUSIVE lock on a snapshot directory: two live
    workers appending to the same shard logs through independent
    buffered handles would interleave torn frames (and share one
    persisted mesh identity). Returns an open handle — keep it
    referenced for the process lifetime — or None when another LIVE
    process holds the directory. flock releases on process death,
    SIGKILL included, so a crashed worker's replacement acquires it
    immediately; only a genuinely concurrent second worker is
    refused."""
    import fcntl

    os.makedirs(directory, exist_ok=True)
    fh = open(os.path.join(directory, ".lock"), "a+")
    try:
        fcntl.flock(fh.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
    except OSError:
        fh.close()
        return None
    return fh


def atomic_write(path: str, data: bytes) -> None:
    """Write bytes to a temp file in the target directory, then
    os.replace into place — readers see the old file or the new one,
    never a prefix."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as fh:
            fh.write(data)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


# ---------------------------------------------------------------------------
# the snapshotter
# ---------------------------------------------------------------------------


class _ShardLog:
    """One shard's append log: a lock + a lazily opened handle."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._fh = None
        self.bytes = 0

    def append(self, payload: bytes) -> None:
        # this lock EXISTS to serialize the file handle — the held I/O
        # is the critical section's whole job, and the write is a
        # page-cache append (microseconds; module docstring)
        with self._lock:
            if self._fh is None:
                os.makedirs(
                    os.path.dirname(os.path.abspath(self.path)),
                    exist_ok=True,
                )
                # foremast: ignore[blocking-under-lock]
                self._fh = open(self.path, "ab")
                self.bytes = self._fh.tell()
            # foremast: ignore[blocking-under-lock]
            self.bytes += append_record(self._fh, payload)

    def rotate(self) -> str | None:
        """Move the live log aside to a FRESH ``.old.<N>`` generation
        and start a new log; returns the rotated path (None when there
        was nothing). Generations ratchet: an earlier crash's rotated
        log (not yet folded into a durable snapshot) must never be
        clobbered by the next rotation — restore replays every
        generation in order, and only a COMPLETED snapshot pass deletes
        them. Called only from the snapshot path; pushes landing
        mid-rotate simply go to the fresh log."""
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
            self.bytes = 0
            if not os.path.exists(self.path):
                return None
            n = 0
            for old in rotated_logs(self.path):
                n = max(n, int(old.rsplit(".", 1)[1]) + 1)
            target = f"{self.path}.old.{n}"
            # the rename must be atomic against concurrent append()
            # reopening the live log — same handle-serializing lock
            # foremast: ignore[blocking-under-lock]
            os.replace(self.path, target)
            return target

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


class RingSnapshotter:
    """Snapshot/restore + write-ahead journaling for one `RingStore`.

    Lifecycle (the order matters — see `restore`): construct against a
    FRESH store, `restore()` once, then `attach()` so live pushes
    journal; `maybe_snapshot()` from the tick loop turns the log into
    bounded-size snapshots. All files live under `directory`.
    """

    def __init__(
        self,
        store: RingStore,
        directory: str,
        interval_seconds: float = DEFAULT_INTERVAL_SECONDS,
        max_age_seconds: float = DEFAULT_MAX_AGE_SECONDS,
        log_max_bytes: int = DEFAULT_LOG_MAX_BYTES,
        clock=time.time,
    ):
        self.store = store
        self.directory = directory
        self.interval_seconds = float(interval_seconds)
        self.max_age_seconds = float(max_age_seconds)
        self.log_max_bytes = int(log_max_bytes)
        self._clock = clock
        # counters/_last_snapshot guard (held only for metadata reads/
        # writes — a scrape must never wait on snapshot file I/O) and a
        # separate pass mutex serializing whole snapshot passes
        self._lock = threading.Lock()
        self._pass_lock = threading.Lock()
        self._last_snapshot = 0.0
        n = store.shard_count
        self._logs = [
            _ShardLog(os.path.join(directory, f"ring-{i}.log"))
            for i in range(n)
        ]
        self.counters = {
            "snapshots": 0,
            "restored_series": 0,
            "restored_samples": 0,
            "discards": _empty_discards(),
        }
        self._log_warned: set[int] = set()
        os.makedirs(directory, exist_ok=True)

    @staticmethod
    def from_env(store: RingStore, directory: str, env=None) -> "RingSnapshotter":
        e = os.environ if env is None else env
        return RingSnapshotter(
            store,
            directory,
            interval_seconds=float(
                e.get("FOREMAST_SNAPSHOT_INTERVAL_SECONDS", "")
                or DEFAULT_INTERVAL_SECONDS
            ),
            max_age_seconds=float(
                e.get("FOREMAST_SNAPSHOT_MAX_AGE_SECONDS", "")
                or DEFAULT_MAX_AGE_SECONDS
            ),
            log_max_bytes=int(
                e.get("FOREMAST_SNAPSHOT_LOG_MAX_BYTES", "")
                or DEFAULT_LOG_MAX_BYTES
            ),
        )

    # -- journaling (the store's push hook) -----------------------------

    def attach(self) -> None:
        """Start journaling live pushes. Call AFTER `restore()` — the
        restore path replays through `store.push`, and journaling those
        replays would double every restart's log."""
        self.store.journal = self._journal

    def detach(self) -> None:
        if self.store.journal is self._journal:
            self.store.journal = None

    def _journal(self, shard_index, key, times, values, start, end) -> None:
        payload = pickle.dumps(
            (
                key,
                np.asarray(times, np.int64),
                np.asarray(values, np.float32),
                None if start is None else float(start),
                None if end is None else float(end),
            ),
            protocol=pickle.HIGHEST_PROTOCOL,
        )
        try:
            self._logs[shard_index].append(payload)
        except OSError as e:
            # a full/broken snapshot disk must degrade durability (the
            # next restart is colder), never the live push plane; one
            # warning per shard, not one per push
            if shard_index not in self._log_warned:
                self._log_warned.add(shard_index)
                log.warning(
                    "ring append log for shard %d unwritable (%s); "
                    "pushes continue UNJOURNALED — the next restart "
                    "cold-fits whatever the last snapshot misses",
                    shard_index, e,
                )

    # -- snapshot --------------------------------------------------------

    def _snap_path(self, i: int) -> str:
        return os.path.join(self.directory, f"ring-{i}.snap.npz")

    def snapshot(self) -> int:
        """Write every shard's resident state; returns series written.
        See the module docstring for the crash-consistent ordering."""
        written = 0
        # _pass_lock exists to serialize whole snapshot passes — file
        # I/O held under it is the design (counters/_lock stay free so
        # scrapes never wait on disk); nothing else ever contends here
        with self._pass_lock:  # one pass at a time; I/O outside _lock
            for i in range(self.store.shard_count):
                # foremast: ignore[blocking-under-lock]
                self._logs[i].rotate()
                state = self.store.shard_state(i)
                arrays: dict[str, np.ndarray] = {
                    "version": np.asarray([SNAP_VERSION], np.int64),
                }
                keys = []
                cov = np.empty((len(state), 2), np.float64)
                extras: dict[str, list] = {}
                for j, (key, t, v, cf, ct, ivs) in enumerate(state):
                    keys.append(key)
                    arrays[f"t{j}"] = t
                    arrays[f"v{j}"] = v
                    cov[j, 0] = np.nan if cf is None else cf
                    cov[j, 1] = np.nan if ct is None else ct
                    if ivs:
                        extras[str(j)] = [list(iv) for iv in ivs]
                arrays["cov"] = cov
                if extras:
                    # older disjoint coverage spans (historical
                    # backfills living next to the live push stream) —
                    # absent on pre-multi-interval snapshots, so the
                    # format stays version-1 compatible both ways
                    arrays["cove"] = np.frombuffer(
                        json.dumps(extras).encode(), np.uint8
                    )
                arrays["keys"] = np.frombuffer(
                    json.dumps(keys).encode(), np.uint8
                )
                import io

                buf = io.BytesIO()
                np.savez(buf, **arrays)
                # foremast: ignore[blocking-under-lock]
                atomic_write(self._snap_path(i), buf.getvalue())
                # the snapshot is durably in place: every rotated
                # generation it subsumes (including any left by
                # earlier crashed passes) can finally go
                for old in rotated_logs(self._logs[i].path):
                    os.unlink(old)
                written += len(state)
            with self._lock:
                self.counters["snapshots"] += 1
                self._last_snapshot = self._clock()
        return written

    def maybe_snapshot(self, now: float | None = None) -> bool:
        """Tick-cadence trigger: snapshot when the interval elapsed or
        any shard's log outgrew the replay budget."""
        now = self._clock() if now is None else now
        with self._lock:
            due = now - self._last_snapshot >= self.interval_seconds
        if not due:
            due = any(
                logf.bytes > self.log_max_bytes for logf in self._logs
            )
        if not due:
            return False
        self.snapshot()
        return True

    # -- restore ---------------------------------------------------------

    def _discard(self, reason: str, n: int = 1) -> None:
        with self._lock:
            self.counters["discards"][reason] += n

    def _disk_shard_indices(self) -> set[int]:
        """Every shard index that has state on disk (snapshot, live
        log, or rotated generation) — possibly written by a run with a
        different shard count."""
        out: set[int] = set()
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for name in names:
            m = re.match(r"ring-(\d+)\.(snap\.npz|log(\.old\.\d+)?)$", name)
            if m:
                out.add(int(m.group(1)))
        return out

    def restore(self, now: float | None = None) -> dict:
        """Replay snapshot + log into the (fresh) store. Returns the
        restore stats also kept on `self.counters`. Never raises on bad
        input files — every failure mode degrades to cold state for the
        affected series/shard and a discard counter."""
        now = self._clock() if now is None else now
        cutoff = now - self.max_age_seconds
        series = 0
        samples = 0
        # replay every shard index present ON DISK, not just the
        # store's current shard count: replay re-hashes keys through
        # store.push anyway, so files written under a different
        # FOREMAST_INGEST_SHARDS (an operator retuning across the very
        # restart durability exists for) restore fine — skipping them
        # would silently lose durable state with no discard counter
        for i in sorted(
            set(range(self.store.shard_count)) | self._disk_shard_indices()
        ):
            n_series, n_samples = self._restore_snapshot(
                self._snap_path(i), cutoff
            )
            series += n_series
            samples += n_samples
            # rotated generations first, oldest to newest (crashes
            # mid-snapshot leave them behind — possibly several), then
            # the live log; double-applied samples merge last-write-wins
            base = os.path.join(self.directory, f"ring-{i}.log")
            for path in rotated_logs(base) + [base]:
                samples += self._replay_log(path, cutoff)
        # series restored = what is RESIDENT after replay (the log can
        # create series no snapshot ever captured — a worker killed
        # before its first snapshot pass restores from log alone)
        series = max(series, self.store.stats()["series"])
        with self._lock:
            self.counters["restored_series"] = series
            self.counters["restored_samples"] = samples
            discards = dict(self.counters["discards"])
        log.info(
            "ring restore: %d series / %d samples from %s (discards: %s)",
            series,
            samples,
            self.directory,
            {k: v for k, v in discards.items() if v},
        )
        return {
            "restored_series": series,
            "restored_samples": samples,
            "discards": discards,
        }

    def _restore_snapshot(self, path: str, cutoff: float) -> tuple[int, int]:
        if not os.path.exists(path):
            return 0, 0
        try:
            with np.load(path, allow_pickle=False) as z:
                version = int(z["version"][0])
                if version != SNAP_VERSION:
                    self._discard("version")
                    log.warning(
                        "snapshot %s is version %d (want %d); discarded",
                        path, version, SNAP_VERSION,
                    )
                    return 0, 0
                keys = json.loads(bytes(z["keys"]).decode())
                cov = np.asarray(z["cov"], np.float64)
                extras: dict = {}
                if "cove" in z.files:
                    try:
                        extras = json.loads(bytes(z["cove"]).decode())
                    except Exception:  # noqa: BLE001 — optional block
                        extras = {}
                data = {}
                for j in range(len(keys)):
                    tn, vn = f"t{j}", f"v{j}"
                    if tn in z.files and vn in z.files:
                        data[j] = (z[tn], z[vn])
        except Exception as e:  # noqa: BLE001 — torn/corrupt file
            self._discard("unreadable")
            log.warning("snapshot %s unreadable (%s); discarded", path, e)
            return 0, 0
        series = 0
        samples = 0
        for j, key in enumerate(keys):
            try:
                t, v = data[j]
                t = np.asarray(t, np.int64)
                v = np.asarray(v, np.float32)
                if t.ndim != 1 or v.ndim != 1 or len(t) != len(v):
                    raise ValueError("column shape mismatch")
                if j >= len(cov):
                    raise ValueError("coverage row missing")
                cf = None if np.isnan(cov[j, 0]) else float(cov[j, 0])
                ct = None if np.isnan(cov[j, 1]) else float(cov[j, 1])
            except (KeyError, ValueError, TypeError) as e:
                # one broken series (a snapshot captured mid-eviction,
                # external corruption): cold-fit it, keep its shard
                self._discard("series")
                log.warning(
                    "snapshot %s: series %r broken (%s); discarded",
                    path, key, e,
                )
                continue
            if ct is not None and ct < cutoff:
                self._discard("stale")
                continue
            # older disjoint coverage spans re-assert through the push
            # path as empty authoritative batches, so a restored ring
            # keeps serving historical backfills without re-fetching
            # (ISSUE 10: the recovery tick's cold fits stay zero-HTTP).
            # Asserted BEFORE the sample push: when the restored ring is
            # smaller than the one snapshotted (max_points retuned down),
            # the sample push drops the oldest samples and its overwrite
            # clamp must clamp these spans too — re-asserting them after
            # would claim authority over ranges whose samples were just
            # discarded, serving truncated "full" histories instead of
            # degrading to the pull path
            for iv in extras.get(str(j), ()):
                try:
                    f0, f1 = float(iv[0]), float(iv[1])
                except (TypeError, ValueError, IndexError):
                    continue
                if f1 < cutoff:
                    continue  # aged out like any stale span
                self.store.push(
                    key, (), (), start=f0, end=f1, record_lag=False
                )
            self.store.push(
                key, t, v, start=cf, end=ct, record_lag=False
            )
            series += 1
            samples += len(t)
        return series, samples

    def _replay_log(self, path: str, cutoff: float) -> int:
        samples = 0
        for payload, reason in read_records(path):
            if reason is not None:
                self._discard(reason)
                log.warning(
                    "append log %s: torn tail; replayed the healthy "
                    "prefix only", path,
                )
                break
            try:
                key, t, v, start, end = pickle.loads(payload)
                # the age cutoff applies to the LOG too, or a worker
                # killed before its first snapshot pass would resurrect
                # week-old series the snapshot path is documented to
                # discard. A record's effective head = the newest thing
                # it vouches for (coverage end or newest sample).
                head = end
                if len(t):
                    newest = float(np.asarray(t, np.int64).max())
                    head = newest if head is None else max(head, newest)
                if head is not None and head < cutoff:
                    self._discard("stale")
                    continue
                samples += self.store.push(
                    key, t, v, start=start, end=end, record_lag=False
                )
            except Exception as e:  # noqa: BLE001 — one bad record
                self._discard("torn_log")
                log.warning(
                    "append log %s: undecodable record (%s); stopping "
                    "replay", path, e,
                )
                break
        return samples

    # -- lifecycle / observability --------------------------------------

    def close(self) -> None:
        self.detach()
        for logf in self._logs:
            logf.close()

    def stats(self) -> dict:
        """Locked copy of counters + snapshot age (scrape-thread safe —
        the collector and /debug/state both read through here)."""
        with self._lock:
            out = dict(self.counters)
            out["discards"] = dict(self.counters["discards"])
            out["age_seconds"] = (
                max(0.0, self._clock() - self._last_snapshot)
                if self._last_snapshot
                else None
            )
            return out

    def debug_state(self) -> dict:
        s = self.stats()
        return {
            "directory": self.directory,
            "interval_seconds": self.interval_seconds,
            "snapshots_written": s["snapshots"],
            "last_snapshot_age_seconds": (
                round(s["age_seconds"], 2)
                if s["age_seconds"] is not None
                else None
            ),
            "restored_series": s["restored_series"],
            "restored_samples": s["restored_samples"],
            "log_bytes": sum(lf.bytes for lf in self._logs),
            "discards": s["discards"],
        }


# ---------------------------------------------------------------------------
# Prometheus exposition
# ---------------------------------------------------------------------------


class SnapshotCollector:
    """prometheus_client custom collector over the durability plane:
    the ring snapshotter plus any fit journals (models.cache.FitJournal)
    — discards share one family so 'how much state did the restart
    lose' is a single query."""

    def __init__(self, snapshotter: RingSnapshotter | None = None, journals=()):
        self._snap = snapshotter
        self._journals = tuple(journals)

    def collect(self):
        from prometheus_client.core import (
            CounterMetricFamily,
            GaugeMetricFamily,
        )

        discards = _empty_discards()
        restored_series = 0
        restored_samples = 0
        snapshots = 0
        age = -1.0
        if self._snap is not None:
            c = self._snap.stats()
            for k, v in c["discards"].items():
                discards[k] += v
            restored_series = c["restored_series"]
            restored_samples = c["restored_samples"]
            snapshots = c["snapshots"]
            if c["age_seconds"] is not None:
                age = c["age_seconds"]
        restored_fits = 0
        for j in self._journals:
            js = j.stats()
            for k, v in js["discards"].items():
                discards[k] += v
            restored_fits += js["restored_entries"]
        fam = CounterMetricFamily(
            "foremast_snapshot_discards",
            "state discarded during snapshot restore, by reason "
            "(torn log tails, version-mismatched or unreadable "
            "snapshots, broken or age-expired series, fit-journal "
            "damage) — each degrades that state to a cold fit, never "
            "a crash",
            labels=["reason"],
        )
        for reason in DISCARD_REASONS:
            fam.add_metric([reason], discards[reason])
        yield fam
        yield GaugeMetricFamily(
            "foremast_snapshot_restored_series",
            "ring series restored by the last startup restore",
            value=restored_series,
        )
        yield GaugeMetricFamily(
            "foremast_snapshot_restored_samples",
            "ring samples restored by the last startup restore "
            "(snapshot + append-log replay)",
            value=restored_samples,
        )
        yield GaugeMetricFamily(
            "foremast_snapshot_restored_fits",
            "fitted-model cache entries restored by the last startup "
            "restore (rehydrated lazily on first claim)",
            value=restored_fits,
        )
        yield CounterMetricFamily(
            "foremast_snapshot_writes",
            "ring snapshot passes completed (all shards, atomic rename)",
            value=snapshots,
        )
        yield GaugeMetricFamily(
            "foremast_snapshot_age_seconds",
            "seconds since the last completed ring snapshot (-1 before "
            "the first)",
            value=age,
        )
