"""Ingest wire format: push payloads and query_range key resolution.

The ingest plane inverts the reference's scrape direction (SURVEY §3.2:
the brain HTTP-GETs every document's `query_range` URL from Prometheus
each tick). Pushers — a vmagent-style forwarder, a recording-rule
evaluator, or the tests' direct `RingStore.push` — send samples keyed
by the SAME series identity the documents' query strings carry, so a
warm fetch is a dictionary gather instead of an HTTP round trip.

Two codecs live here, both pure functions with no locking or I/O:

  * ``parse_push`` — the receiver's remote-write-style JSON body:
    ``{"timeseries": [...]}`` where each entry carries either Prometheus
    remote-write shaped ``labels`` + ``samples`` pairs, or the direct
    ``alias``/``times``/``values`` arrays. Timestamps are unix SECONDS
    (the judgment plane's resolution; the 60 s recording-rule step makes
    sub-second precision meaningless here).
  * ``resolve_query_range`` — a document's datasource URL → the ring
    key plus the requested (start, end, step) window. Handles both URL
    shapes the brain fetches (Prometheus ``query_range?query=...`` per
    `prometheushelper.go:12-27` and the wavefront ``&&`` encoding per
    `wavefronthelper.go:20-29`).

Series identity: ``canonical_series`` normalizes a bare PromQL selector
(`name{a="1",b="2"}`) by sorting its label matchers, so a push built
from a labels map and a query string written in any label order land on
the same ring slot. Non-selector expressions (wrapped in functions)
pass through verbatim — pushers for those use the alias form with the
exact expression text.
"""

from __future__ import annotations

import re
import urllib.parse

import numpy as np

from foremast_tpu.metrics.source import Series  # noqa: F401 — shared alias

_SELECTOR_RE = re.compile(
    r"^\s*([a-zA-Z_:][a-zA-Z0-9_:]*)\s*(?:\{(.*)\})?\s*$", re.DOTALL
)
_MATCHER_RE = re.compile(
    r'\s*([a-zA-Z_][a-zA-Z0-9_]*)\s*(=~|!=|!~|=)\s*"((?:[^"\\]|\\.)*)"\s*(?:,|$)'
)


def canonical_series(expr: str) -> str:
    """Label-order-independent form of a bare PromQL selector; any
    expression that is not a plain ``name{matchers}`` selector is
    returned stripped-but-verbatim (pushers then use the exact text)."""
    m = _SELECTOR_RE.match(expr or "")
    if m is None:
        return (expr or "").strip()
    name, body = m.group(1), m.group(2)
    if body is None or not body.strip():
        return name
    matchers = []
    pos = 0
    for mm in _MATCHER_RE.finditer(body):
        if mm.start() != pos:
            return expr.strip()  # unparsed residue: not a bare selector
        matchers.append((mm.group(1), mm.group(2), mm.group(3)))
        pos = mm.end()
    if pos != len(body):
        return expr.strip()
    matchers.sort()
    inner = ",".join(f'{k}{op}"{v}"' for k, op, v in matchers)
    return f"{name}{{{inner}}}"


def series_key(labels: dict) -> str:
    """Ring key for a labels map (`__name__` + sorted matchers) — the
    push-side mirror of `canonical_series` on the query side. Label
    values are rendered in PromQL's escaped form (backslash and quote),
    matching the escaped text a query selector carries — an unescaped
    render would let a value containing `","` inject fake matchers and
    collide with a different series' key."""
    name = str(labels.get("__name__", ""))
    rest = sorted(
        (str(k), str(v)) for k, v in labels.items() if k != "__name__"
    )
    if not rest:
        return name
    inner = ",".join(
        '{}="{}"'.format(k, v.replace("\\", "\\\\").replace('"', '\\"'))
        for k, v in rest
    )
    return f"{name}{{{inner}}}"


class WireError(ValueError):
    """Malformed push payload (the receiver answers 400 with the text)."""


def _entry_series(entry: dict) -> tuple[np.ndarray, np.ndarray]:
    if "samples" in entry:
        samples = entry["samples"]
        ts = np.asarray([s[0] for s in samples], np.int64)
        vs = np.asarray([s[1] for s in samples], np.float32)
    elif "times" in entry and "values" in entry:
        ts = np.asarray(entry["times"], np.int64)
        vs = np.asarray(entry["values"], np.float32)
    else:
        raise WireError(
            "timeseries entry needs `samples` or `times`+`values`"
        )
    if ts.ndim != 1 or vs.ndim != 1 or len(ts) != len(vs):
        raise WireError("times/values must be equal-length 1-d arrays")
    return ts, vs


def parse_push(body) -> list[tuple[str, np.ndarray, np.ndarray, float | None]]:
    """Decode one push payload into ``(key, times, values, start)``
    tuples. `start` is the entry's optional coverage watermark: a
    backfill-style push may assert "there is no data before start" so
    queries reaching back to it count as covered."""
    if not isinstance(body, dict):
        raise WireError("push body must be a JSON object")
    series = body.get("timeseries")
    if not isinstance(series, list):
        raise WireError("push body needs a `timeseries` list")
    out = []
    for entry in series:
        if not isinstance(entry, dict):
            raise WireError("timeseries entries must be objects")
        labels = entry.get("labels")
        if labels is not None:
            if isinstance(labels, list):  # proto-JSON [{name,value}] shape
                if not all(
                    isinstance(lb, dict) and "name" in lb and "value" in lb
                    for lb in labels
                ):
                    raise WireError(
                        "label list entries must be {name, value} objects"
                    )
                labels = {
                    str(lb["name"]): str(lb["value"]) for lb in labels
                }
            if not isinstance(labels, dict) or not labels.get("__name__"):
                raise WireError("labels need a `__name__`")
            key = series_key(labels)
        else:
            alias = entry.get("alias") or entry.get("series")
            if not alias:
                raise WireError(
                    "timeseries entry needs `labels` or `alias`"
                )
            key = canonical_series(str(alias))
        try:
            ts, vs = _entry_series(entry)
        except WireError:
            raise
        except (TypeError, ValueError, IndexError, KeyError) as e:
            raise WireError(f"bad samples for {key!r}: {e}") from None
        start = entry.get("start")
        if start is not None:
            try:
                start = float(start)
            except (TypeError, ValueError):
                raise WireError(
                    f"bad `start` for {key!r}: {start!r}"
                ) from None
        out.append((key, ts, vs, start))
    return out


def _qs_float(qs: dict, name: str) -> float | None:
    raw = qs.get(name, [None])[0]
    if raw in (None, ""):
        return None
    try:
        return float(raw)
    except ValueError:
        from foremast_tpu.jobs.store import parse_time

        t = parse_time(raw)  # Prometheus accepts RFC3339 too
        return t if t > 0 else None


def resolve_query_range(
    url: str,
) -> tuple[str | None, float | None, float | None, float]:
    """Document URL → ``(key, start, end, step)``; key None when the URL
    carries no recognizable query (the source then bypasses the ring).
    Both the Prometheus `query_range?query=...&start=&end=&step=` shape
    and the wavefront `<query>&&<start>&&<unit>&&<end>` shape resolve."""
    if "&&" in url and "query_range" not in url:
        parts = url.split("&&")
        if len(parts) >= 4:
            key = canonical_series(urllib.parse.unquote(parts[0]))

            def _f(raw):
                try:
                    return float(raw)
                except ValueError:
                    return None

            # the inverse of promql.wavefront_url's granularity map
            step = {"s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}.get(
                parts[2], 60.0
            )
            return key or None, _f(parts[1]), _f(parts[3]), step
        return None, None, None, 60.0
    try:
        qs = urllib.parse.parse_qs(urllib.parse.urlparse(url).query)
    except ValueError:
        return None, None, None, 60.0
    raw_q = qs.get("query", [None])[0] or qs.get("q", [None])[0]
    if not raw_q:
        return None, None, None, 60.0
    step = _qs_float(qs, "step") or 60.0
    return (
        canonical_series(raw_q),
        _qs_float(qs, "start"),
        _qs_float(qs, "end"),
        step,
    )
