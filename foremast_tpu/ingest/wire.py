"""Ingest wire format: push payloads and query_range key resolution.

The ingest plane inverts the reference's scrape direction (SURVEY §3.2:
the brain HTTP-GETs every document's `query_range` URL from Prometheus
each tick). Pushers — a vmagent-style forwarder, a recording-rule
evaluator, or the tests' direct `RingStore.push` — send samples keyed
by the SAME series identity the documents' query strings carry, so a
warm fetch is a dictionary gather instead of an HTTP round trip.

Three codecs live here, all pure functions with no locking or I/O:

  * ``parse_push`` — the receiver's remote-write-style JSON body:
    ``{"timeseries": [...]}`` where each entry carries either Prometheus
    remote-write shaped ``labels`` + ``samples`` pairs, or the direct
    ``alias``/``times``/``values`` arrays. Timestamps are unix SECONDS
    (the judgment plane's resolution; the 60 s recording-rule step makes
    sub-second precision meaningless here).
  * ``encode_frame``/``decode_frame`` — the BINARY wire codec (ISSUE
    18): a length-prefixed columnar frame in the remote-write wire
    family's shape (one framed write request, optionally
    snappy-compressed) whose decode is ``np.frombuffer`` VIEWS over the
    frame — the (int64 times, float32 values) columns land in the ring
    with zero intermediate dict/list/object materialization. Layout and
    negotiation are specified in docs/wire-protocol.md.
  * ``resolve_query_range`` — a document's datasource URL → the ring
    key plus the requested (start, end, step) window. Handles both URL
    shapes the brain fetches (Prometheus ``query_range?query=...`` per
    `prometheushelper.go:12-27` and the wavefront ``&&`` encoding per
    `wavefronthelper.go:20-29`).

A pure-python snappy block-format codec rides along
(``snappy_compress``/``snappy_decompress``): the container bakes no
snappy wheel, and gating ``Content-Encoding: snappy`` on an optional
import would make wire compatibility an install-time accident. The
decoder handles the FULL block format (literals + all three copy
element shapes — what a real remote-write pusher emits); the encoder
emits well-formed literal-only streams (framing compatibility, not
ratio — the wire-speed path is the uncompressed frame).

Series identity: ``canonical_series`` normalizes a bare PromQL selector
(`name{a="1",b="2"}`) by sorting its label matchers, so a push built
from a labels map and a query string written in any label order land on
the same ring slot. Non-selector expressions (wrapped in functions)
pass through verbatim — pushers for those use the alias form with the
exact expression text.
"""

from __future__ import annotations

import re
import urllib.parse

import numpy as np

from foremast_tpu.metrics.source import Series  # noqa: F401 — shared alias

_SELECTOR_RE = re.compile(
    r"^\s*([a-zA-Z_:][a-zA-Z0-9_:]*)\s*(?:\{(.*)\})?\s*$", re.DOTALL
)
_MATCHER_RE = re.compile(
    r'\s*([a-zA-Z_][a-zA-Z0-9_]*)\s*(=~|!=|!~|=)\s*"((?:[^"\\]|\\.)*)"\s*(?:,|$)'
)


def canonical_series(expr: str) -> str:
    """Label-order-independent form of a bare PromQL selector; any
    expression that is not a plain ``name{matchers}`` selector is
    returned stripped-but-verbatim (pushers then use the exact text)."""
    m = _SELECTOR_RE.match(expr or "")
    if m is None:
        return (expr or "").strip()
    name, body = m.group(1), m.group(2)
    if body is None or not body.strip():
        return name
    matchers = []
    pos = 0
    for mm in _MATCHER_RE.finditer(body):
        if mm.start() != pos:
            return expr.strip()  # unparsed residue: not a bare selector
        matchers.append((mm.group(1), mm.group(2), mm.group(3)))
        pos = mm.end()
    if pos != len(body):
        return expr.strip()
    matchers.sort()
    inner = ",".join(f'{k}{op}"{v}"' for k, op, v in matchers)
    return f"{name}{{{inner}}}"


def series_key(labels: dict) -> str:
    """Ring key for a labels map (`__name__` + sorted matchers) — the
    push-side mirror of `canonical_series` on the query side. Label
    values are rendered in PromQL's escaped form (backslash and quote),
    matching the escaped text a query selector carries — an unescaped
    render would let a value containing `","` inject fake matchers and
    collide with a different series' key."""
    name = str(labels.get("__name__", ""))
    rest = sorted(
        (str(k), str(v)) for k, v in labels.items() if k != "__name__"
    )
    if not rest:
        return name
    inner = ",".join(
        '{}="{}"'.format(k, v.replace("\\", "\\\\").replace('"', '\\"'))
        for k, v in rest
    )
    return f"{name}{{{inner}}}"


class WireError(ValueError):
    """Malformed push payload (the receiver answers 400 with the text)."""


def _entry_series(entry: dict) -> tuple[np.ndarray, np.ndarray]:
    if "samples" in entry:
        samples = entry["samples"]
        ts = np.asarray([s[0] for s in samples], np.int64)
        vs = np.asarray([s[1] for s in samples], np.float32)
    elif "times" in entry and "values" in entry:
        ts = np.asarray(entry["times"], np.int64)
        vs = np.asarray(entry["values"], np.float32)
    else:
        raise WireError(
            "timeseries entry needs `samples` or `times`+`values`"
        )
    if ts.ndim != 1 or vs.ndim != 1 or len(ts) != len(vs):
        raise WireError("times/values must be equal-length 1-d arrays")
    if len(vs) and not bool(np.isfinite(vs).all()):
        # same contract as the binary codec: a non-finite value is a
        # malformed push, not a storable sample (parity keeps statuses
        # byte-identical across codecs)
        raise WireError("non-finite sample value (NaN/Inf)")
    return ts, vs


def parse_push(body) -> list[tuple[str, np.ndarray, np.ndarray, float | None]]:
    """Decode one push payload into ``(key, times, values, start)``
    tuples. `start` is the entry's optional coverage watermark: a
    backfill-style push may assert "there is no data before start" so
    queries reaching back to it count as covered."""
    if not isinstance(body, dict):
        raise WireError("push body must be a JSON object")
    series = body.get("timeseries")
    if not isinstance(series, list):
        raise WireError("push body needs a `timeseries` list")
    out = []
    for entry in series:
        if not isinstance(entry, dict):
            raise WireError("timeseries entries must be objects")
        labels = entry.get("labels")
        if labels is not None:
            if isinstance(labels, list):  # proto-JSON [{name,value}] shape
                if not all(
                    isinstance(lb, dict) and "name" in lb and "value" in lb
                    for lb in labels
                ):
                    raise WireError(
                        "label list entries must be {name, value} objects"
                    )
                labels = {
                    str(lb["name"]): str(lb["value"]) for lb in labels
                }
            if not isinstance(labels, dict) or not labels.get("__name__"):
                raise WireError("labels need a `__name__`")
            key = series_key(labels)
        else:
            alias = entry.get("alias") or entry.get("series")
            if not alias:
                raise WireError(
                    "timeseries entry needs `labels` or `alias`"
                )
            key = canonical_series(str(alias))
        try:
            ts, vs = _entry_series(entry)
        except WireError:
            raise
        except (TypeError, ValueError, IndexError, KeyError) as e:
            raise WireError(f"bad samples for {key!r}: {e}") from None
        start = entry.get("start")
        if start is not None:
            try:
                start = float(start)
            except (TypeError, ValueError):
                raise WireError(
                    f"bad `start` for {key!r}: {start!r}"
                ) from None
        out.append((key, ts, vs, start))
    return out


# --------------------------------------------------------------------------
# Binary wire codec (ISSUE 18): length-prefixed columnar frame, decoded as
# np.frombuffer views — no per-sample Python objects anywhere on the path.
#
#   header (32 bytes, little-endian):
#     [0:4)   magic  b"FMW1"
#     [4]     u8  version (1)
#     [5]     u8  flags (reserved, must be 0)
#     [6:8)   u16 reserved (must be 0)
#     [8:12)  u32 n_series
#     [12:20) u64 n_samples
#     [20:24) u32 key_blob_len
#     [24:32) u64 frame_len (header + all sections; truncation/garbage check)
#   sections, contiguous from byte 32, widest-alignment-first so every
#   np.frombuffer view is naturally aligned:
#     times    int64[n_samples]      sample timestamps, concatenated per series
#     starts   float64[n_series]     coverage watermark; NaN = none
#     values   float32[n_samples]
#     counts   uint32[n_series]      samples per series (prefix-sums slice times/values)
#     key_offs uint32[n_series + 1]  byte offsets into key_blob (offs[0] == 0)
#     key_blob utf-8 bytes           canonical series keys, concatenated
#
# Contract (docs/wire-protocol.md): per-series timestamps must be
# non-decreasing — an out-of-order frame is a 400, unlike the JSON compat
# codec which merge-sorts. Values must be finite in BOTH codecs.

BINARY_CONTENT_TYPE = "application/x-foremast-remote-write"
JSON_CONTENT_TYPE = "application/json"
FRAME_MAGIC = b"FMW1"
FRAME_VERSION = 1
_HEADER = 32
# Sanity ceilings: a header declaring more than this is malformed, not big
# (the receiver's byte caps bound real frames far below these).
_MAX_SERIES = 1 << 24
_MAX_SAMPLES = 1 << 33


def encode_frame(
    entries: list[tuple[str, np.ndarray, np.ndarray, float | None]],
) -> bytes:
    """Encode ``(key, times, values, start)`` tuples (the exact shape
    ``parse_push`` returns) into one binary frame. Keys are written as
    given — callers wanting cross-codec key identity pass canonical keys."""
    n_series = len(entries)
    keys = [str(k).encode("utf-8") for k, _, _, _ in entries]
    counts = np.asarray([len(t) for _, t, _, _ in entries], np.uint32)
    n_samples = int(counts.sum())
    times = (
        np.concatenate([np.asarray(t, np.int64) for _, t, _, _ in entries])
        if n_series
        else np.empty(0, np.int64)
    )
    values = (
        np.concatenate([np.asarray(v, np.float32) for _, _, v, _ in entries])
        if n_series
        else np.empty(0, np.float32)
    )
    starts = np.asarray(
        [np.nan if s is None else float(s) for _, _, _, s in entries],
        np.float64,
    )
    key_offs = np.zeros(n_series + 1, np.uint32)
    np.cumsum([len(k) for k in keys], out=key_offs[1:])
    blob = b"".join(keys)
    frame_len = (
        _HEADER
        + times.nbytes
        + starts.nbytes
        + values.nbytes
        + counts.nbytes
        + key_offs.nbytes
        + len(blob)
    )
    header = (
        FRAME_MAGIC
        + bytes((FRAME_VERSION, 0, 0, 0))
        + n_series.to_bytes(4, "little")
        + n_samples.to_bytes(8, "little")
        + len(blob).to_bytes(4, "little")
        + frame_len.to_bytes(8, "little")
    )
    return b"".join(
        (
            header,
            times.tobytes(),
            starts.tobytes(),
            values.tobytes(),
            counts.tobytes(),
            key_offs.tobytes(),
            blob,
        )
    )


def frame_decoded_len(buf: bytes) -> int:
    """Declared total frame length from the first 32 header bytes — the
    no-buffering 413 guard reads THIS (or Content-Length) before touching
    section bytes. Raises WireError when the header itself is malformed."""
    if len(buf) < _HEADER:
        raise WireError("binary frame shorter than its 32-byte header")
    if buf[:4] != FRAME_MAGIC:
        raise WireError("bad frame magic (want FMW1)")
    if buf[4] != FRAME_VERSION:
        raise WireError(f"unsupported frame version {buf[4]}")
    if buf[5] != 0 or buf[6] != 0 or buf[7] != 0:
        raise WireError("reserved frame header bytes must be zero")
    return int.from_bytes(buf[24:32], "little")


def decode_frame(
    buf: bytes, intern: dict | None = None, canonicalize: bool = False
) -> list[tuple[str, np.ndarray, np.ndarray, float | None]]:
    """Decode one binary frame into ``(key, times, values, start)`` tuples
    whose arrays are zero-copy views over ``buf`` (the frame must outlive
    them — the receiver applies within the request, so it always does).

    ``intern`` is an optional ``bytes -> str`` cache: repeat pushers resend
    the same key set every frame, so decode amortizes utf-8 decoding (and,
    with ``canonicalize``, the `canonical_series` regex) to one dict hit
    per series. Validation is vectorized: finiteness over the whole values
    column, per-series timestamp order via one diff masked at series
    boundaries — no per-sample Python loop anywhere."""
    frame_len = frame_decoded_len(buf)
    if frame_len != len(buf):
        raise WireError(
            f"frame length mismatch: header declares {frame_len} bytes, "
            f"got {len(buf)} (truncated or trailing garbage)"
        )
    n_series = int.from_bytes(buf[8:12], "little")
    n_samples = int.from_bytes(buf[12:20], "little")
    blob_len = int.from_bytes(buf[20:24], "little")
    if n_series > _MAX_SERIES or n_samples > _MAX_SAMPLES:
        raise WireError("frame header counts out of range")
    want = (
        _HEADER
        + 8 * n_samples  # times
        + 8 * n_series  # starts
        + 4 * n_samples  # values
        + 4 * n_series  # counts
        + 4 * (n_series + 1)  # key_offs
        + blob_len
    )
    if want != frame_len:
        raise WireError(
            f"frame sections need {want} bytes but header declares {frame_len}"
        )
    off = _HEADER
    times = np.frombuffer(buf, np.int64, n_samples, off)
    off += times.nbytes
    starts = np.frombuffer(buf, np.float64, n_series, off)
    off += starts.nbytes
    values = np.frombuffer(buf, np.float32, n_samples, off)
    off += values.nbytes
    counts = np.frombuffer(buf, np.uint32, n_series, off)
    off += counts.nbytes
    key_offs = np.frombuffer(buf, np.uint32, n_series + 1, off)
    off += key_offs.nbytes
    blob = buf[off : off + blob_len]
    if int(counts.sum()) != n_samples:
        raise WireError("per-series counts do not sum to n_samples")
    if key_offs[0] != 0 or int(key_offs[-1]) != blob_len:
        raise WireError("key offsets do not span the key blob")
    if n_series and bool(np.any(np.diff(key_offs.astype(np.int64)) < 0)):
        raise WireError("key offsets must be non-decreasing")
    if n_samples and not bool(np.isfinite(values).all()):
        raise WireError("non-finite sample value (NaN/Inf) in frame")
    bounds = np.cumsum(counts.astype(np.int64))
    if n_samples > 1:
        order_ok = np.diff(times) >= 0
        # series boundaries: time may legitimately reset between series
        order_ok[bounds[:-1][bounds[:-1] < n_samples] - 1] = True
        if not bool(order_ok.all()):
            raise WireError(
                "out-of-order timestamps within a series (binary frames "
                "must be time-sorted; use the JSON codec for unsorted pushes)"
            )
    out = []
    lo = 0
    for i in range(n_series):
        hi = int(bounds[i])
        raw = blob[int(key_offs[i]) : int(key_offs[i + 1])]
        key = intern.get(raw) if intern is not None else None
        if key is None:
            try:
                key = raw.decode("utf-8")
            except UnicodeDecodeError:
                raise WireError("series key is not valid utf-8") from None
            if canonicalize:
                key = canonical_series(key)
            if intern is not None and len(intern) < 65536:
                intern[raw] = key
        s = float(starts[i])
        out.append((key, times[lo:hi], values[lo:hi], None if s != s else s))
        lo = hi
    return out


# --------------------------------------------------------------------------
# Pure-python snappy block format (https://github.com/google/snappy —
# format_description.txt). Enough for wire compatibility with real
# remote-write pushers; the uncompressed binary frame is the fast path.


def _uvarint(buf: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise WireError("truncated snappy length varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 35:
            raise WireError("snappy length varint too long")


def snappy_uncompressed_len(buf: bytes) -> int:
    """Declared uncompressed size from the stream preamble — the snappy
    bomb guard checks THIS against the decoded-bytes cap before any
    decompression work happens."""
    n, _ = _uvarint(buf, 0)
    return n


def snappy_decompress(buf: bytes, max_len: int | None = None) -> bytes:
    """Decode a snappy block-format stream. Raises WireError on any
    malformed input (bad preamble, copy before start, overrun, short
    stream) and when the declared length exceeds ``max_len``."""
    declared, pos = _uvarint(buf, 0)
    if max_len is not None and declared > max_len:
        raise WireError(
            f"snappy stream declares {declared} bytes > cap {max_len}"
        )
    out = bytearray()
    n = len(buf)
    while pos < n:
        tag = buf[pos]
        pos += 1
        kind = tag & 3
        if kind == 0:  # literal
            length = (tag >> 2) + 1
            if length > 60:
                nbytes = length - 60
                if pos + nbytes > n:
                    raise WireError("truncated snappy literal length")
                length = (
                    int.from_bytes(buf[pos : pos + nbytes], "little") + 1
                )
                pos += nbytes
            if pos + length > n:
                raise WireError("truncated snappy literal")
            out += buf[pos : pos + length]
            pos += length
        else:  # copy
            if kind == 1:
                if pos >= n:
                    raise WireError("truncated snappy copy-1")
                length = ((tag >> 2) & 0x7) + 4
                offset = ((tag >> 5) << 8) | buf[pos]
                pos += 1
            elif kind == 2:
                if pos + 2 > n:
                    raise WireError("truncated snappy copy-2")
                length = (tag >> 2) + 1
                offset = int.from_bytes(buf[pos : pos + 2], "little")
                pos += 2
            else:
                if pos + 4 > n:
                    raise WireError("truncated snappy copy-4")
                length = (tag >> 2) + 1
                offset = int.from_bytes(buf[pos : pos + 4], "little")
                pos += 4
            if offset == 0 or offset > len(out):
                raise WireError("snappy copy reaches before stream start")
            if len(out) + length > declared:
                raise WireError("snappy output overruns declared length")
            if offset >= length:
                start = len(out) - offset
                out += out[start : start + length]
            else:  # overlapping copy: byte-wise RLE semantics
                start = len(out) - offset
                for i in range(length):
                    out.append(out[start + i])
        if len(out) > declared:
            raise WireError("snappy output overruns declared length")
    if len(out) != declared:
        raise WireError(
            f"snappy stream declares {declared} bytes, decoded {len(out)}"
        )
    return bytes(out)


def snappy_compress(data: bytes) -> bytes:
    """Encode ``data`` as a valid snappy block-format stream of literals.
    No match search — the uncompressed binary frame is the wire-speed
    path; this exists so `Content-Encoding: snappy` round-trips without a
    native wheel. Any conformant decoder (including real snappy) reads it."""
    out = bytearray()
    n = len(data)
    shift = n
    while True:
        b = shift & 0x7F
        shift >>= 7
        if shift:
            out.append(b | 0x80)
        else:
            out.append(b)
            break
    pos = 0
    while pos < n:
        chunk = min(n - pos, 1 << 16)
        if chunk <= 60:
            out.append((chunk - 1) << 2)
        else:
            enc = (chunk - 1).to_bytes(4, "little").rstrip(b"\x00") or b"\x00"
            out.append((59 + len(enc)) << 2)
            out += enc
        out += data[pos : pos + chunk]
        pos += chunk
    return bytes(out)


def _qs_float(qs: dict, name: str) -> float | None:
    raw = qs.get(name, [None])[0]
    if raw in (None, ""):
        return None
    try:
        return float(raw)
    except ValueError:
        from foremast_tpu.jobs.store import parse_time

        t = parse_time(raw)  # Prometheus accepts RFC3339 too
        return t if t > 0 else None


def resolve_query_range(
    url: str,
) -> tuple[str | None, float | None, float | None, float]:
    """Document URL → ``(key, start, end, step)``; key None when the URL
    carries no recognizable query (the source then bypasses the ring).
    Both the Prometheus `query_range?query=...&start=&end=&step=` shape
    and the wavefront `<query>&&<start>&&<unit>&&<end>` shape resolve."""
    if "&&" in url and "query_range" not in url:
        parts = url.split("&&")
        if len(parts) >= 4:
            key = canonical_series(urllib.parse.unquote(parts[0]))

            def _f(raw):
                try:
                    return float(raw)
                except ValueError:
                    return None

            # the inverse of promql.wavefront_url's granularity map
            step = {"s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}.get(
                parts[2], 60.0
            )
            return key or None, _f(parts[1]), _f(parts[3]), step
        return None, None, None, 60.0
    try:
        qs = urllib.parse.parse_qs(urllib.parse.urlparse(url).query)
    except ValueError:
        return None, None, None, 60.0
    raw_q = qs.get("query", [None])[0] or qs.get("q", [None])[0]
    if not raw_q:
        return None, None, None, 60.0
    step = _qs_float(qs, "step") or 60.0
    return (
        canonical_series(raw_q),
        _qs_float(qs, "start"),
        _qs_float(qs, "end"),
        step,
    )
