"""Cold-miss subscription + backfill: how a series becomes warm.

The ingest plane is opt-in and push-driven, so the first fetch of any
series necessarily misses. The contract (ISSUE 5 tentpole): the miss is
RECORDED — the series is now "subscribed" — and the fallback fetch's
result is written straight into the ring with the query's own window
start as the coverage watermark, so the very next tick's fetch for the
same document is a resident-slice hit with zero HTTP. Pushers can read
the subscription book (receiver `/debug/state`, worker varz) to learn
which series the fleet actually wants — the push-plane analog of a
scrape config.

`SubscriptionBook` is bounded: keys arrive from document configs
(REST-supplied), the same unbounded-cardinality source the gauge-family
cap defends against, so past `cap` the oldest subscription record is
dropped (the series itself is unaffected — only the bookkeeping row).
"""

from __future__ import annotations

import threading
from collections import OrderedDict

from foremast_tpu.ingest.shards import RingStore

DEFAULT_CAP = 16_384


class SubscriptionBook:
    """Thread-safe record of (series key -> last miss reason/URL)."""

    def __init__(self, cap: int = DEFAULT_CAP):
        self.cap = cap
        self._lock = threading.Lock()
        self._subs: OrderedDict[str, dict] = OrderedDict()

    def record(self, key: str, url: str, reason: str) -> None:
        with self._lock:
            row = self._subs.get(key)
            if row is None:
                row = {"url": url, "reason": reason, "misses": 0}
                self._subs[key] = row
            row["reason"] = reason
            row["misses"] += 1
            self._subs.move_to_end(key)
            while len(self._subs) > self.cap:
                self._subs.popitem(last=False)

    def __len__(self) -> int:
        with self._lock:
            return len(self._subs)

    def snapshot(self, limit: int = 32) -> dict:
        """Bounded varz view: total + the most recent `limit` rows."""
        with self._lock:
            recent = list(self._subs.items())[-limit:]
            return {
                "total": len(self._subs),
                "recent": {k: dict(v) for k, v in recent},
            }


def backfill(
    store: RingStore,
    key: str,
    series,
    start: float | None,
    end: float | None = None,
    now: float | None = None,
) -> int:
    """Write a fallback fetch's result into the ring, stamping the
    query's window `[start, end]` as the coverage interval — the
    fallback is authoritative for exactly that range, INCLUDING its
    emptiness: a truly-empty series becomes a resident empty ring whose
    coverage serves subsequent fetches as empty hits (parity with the
    pull path) until staleness sends it back for a refresh. Backfilled
    samples never count as receiver lag (they are old by construction —
    see `RingStore.push(record_lag=...)`)."""
    times, values = series
    if start is None and end is None and not len(times):
        return 0  # nothing to store and no range to assert
    return store.push(
        key, times, values, start=start, end=end, now=now,
        record_lag=False,
    )
