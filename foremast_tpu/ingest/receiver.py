"""Remote-write receiver + Prometheus exposition of the ingest plane.

`start_ingest_server` serves the push endpoint the way
`observe.spans.start_observe_server` serves the scrape endpoint — a
daemon-threaded `ThreadingHTTPServer`, so each pusher connection gets a
handler thread and the sharded store's per-shard locks absorb the
concurrency:

    POST /api/v1/write   remote-write-style JSON (wire.parse_push);
                         200 + {"accepted_samples", "series"} on
                         success (plus a "redirects" {key: address}
                         map when a mesh router marks series another
                         member owns — samples are still accepted, so
                         the convergence window loses nothing), 400
                         with the reason on a malformed payload — one
                         bad entry rejects the batch so pushers notice
                         instead of silently losing series — and 413
                         when the body exceeds the byte cap
                         (`FOREMAST_INGEST_MAX_BODY_BYTES`)
    GET  /healthz        liveness + version
    GET  /debug/state    the store's stats (series resident, bytes,
                         evictions, hit ratio, receiver lag)

Hardening: handler threads are daemons with a per-connection socket
timeout, request bodies are size-capped BEFORE json.loads (an
oversized push answers 413 without buffering the payload), and
`stop_ingest_server` gives the worker's close path a bounded drain —
stop accepting, wait for in-flight handlers up to a deadline, then
abandon them to their daemon fate instead of wedging shutdown.

`IngestCollector` exports the same stats as the `foremast_ingest_*`
metric families (docs/observability.md) via a custom collector —
counters and gauges are materialized from `RingStore.stats()` at scrape
time, so the hot push/fetch paths never touch prometheus_client.

Reactive plane (ISSUE 12): with a `dirty` set wired
(`reactive.DirtySet`), every accepted push marks its series' route key
dirty so the worker's next micro-tick judges the affected documents.
**Arrival-clock contract:** the dirty stamp is THIS process's wall
clock taken at handler entry, BEFORE the body is read or parsed — the
RECEIVER's arrival instant — never the pusher's sample timestamps.
The push→verdict latency SLO (`foremast_verdict_latency_seconds`)
therefore measures time spent inside this system and is immune to
client clock skew: a pusher replaying old samples, or one with a fast
clock, moves its sample stamps but not the SLO.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time

from foremast_tpu.ingest.shards import RingStore
from foremast_tpu.ingest.wire import WireError, parse_push

log = logging.getLogger("foremast_tpu.ingest")

WRITE_PATH = "/api/v1/write"
# peer→peer planned-handoff endpoint (mesh/handoff.py): crc-framed
# transfer batches from a draining member or a joiner's current owners
TRANSFER_PATH = "/api/v1/transfer"
DEFAULT_MAX_BODY_BYTES = 8 * 1024 * 1024
# concurrent push handlers allowed before the receiver sheds with
# 429 + Retry-After (FOREMAST_INGEST_MAX_INFLIGHT; 0 = unbounded)
DEFAULT_MAX_INFLIGHT = 64
# a handler stuck mid-read (pusher died with the body half-sent) frees
# its thread after this instead of holding it forever
HANDLER_TIMEOUT_SECONDS = 30.0


class IngestCollector:
    """prometheus_client custom collector over `RingStore.stats()`."""

    def __init__(self, store: RingStore, book=None):
        self._store = store
        self._book = book

    def collect(self):
        from prometheus_client.core import (
            CounterMetricFamily,
            GaugeMetricFamily,
        )

        s = self._store.stats()
        fetches = CounterMetricFamily(
            "foremast_ingest_fetches",
            "ring TSDB fetch outcomes (hit=resident slice served, "
            "partial=short-history admission slice served, "
            "miss=series not resident, stale=pusher behind the window, "
            "uncovered=resident but not authoritative back to start)",
            labels=["result"],
        )
        for result, count_key in (
            ("hit", "hits"),
            ("partial", "partial"),
            ("miss", "misses"),
            ("stale", "stale"),
            ("uncovered", "uncovered"),
        ):
            fetches.add_metric([result], s[count_key])
        yield fetches
        yield CounterMetricFamily(
            "foremast_ingest_samples",
            "samples accepted by the ingest plane (receiver + direct push)",
            value=s["samples"],
        )
        yield CounterMetricFamily(
            "foremast_ingest_evictions",
            "whole series evicted under FOREMAST_INGEST_BUDGET_BYTES",
            value=s["evictions"],
        )
        yield GaugeMetricFamily(
            "foremast_ingest_series_resident",
            "series currently resident in the ring TSDB",
            value=s["series"],
        )
        yield GaugeMetricFamily(
            "foremast_ingest_bytes_resident",
            "column bytes currently allocated by resident series",
            value=s["bytes"],
        )
        lag = s.get("receiver_lag_seconds")
        yield GaugeMetricFamily(
            "foremast_ingest_receiver_lag_seconds",
            "now minus the newest sample timestamp of the latest push "
            "(-1 until the first push arrives)",
            value=-1.0 if lag is None else lag,
        )


def start_ingest_server(
    port: int,
    store: RingStore,
    host: str = "0.0.0.0",
    book=None,
    router=None,
    max_body_bytes: int | None = None,
    max_inflight: int | None = None,
    chaos=None,
    degrade_stats=None,
    handoff=None,
    dirty=None,
):
    """Serve the push plane; returns (server, thread). Port 0 binds an
    ephemeral port (tests) — read it back from server.server_address.

    `router` (mesh.routing.MeshRouter, optional): pushes for series
    another mesh member owns are accepted into the local ring (lossless
    during convergence, LRU reclaims them) AND answered with the
    owner's advertised address in the response's `redirects` map, so a
    mesh-aware pusher lands on the right shard from its next cycle.

    `max_body_bytes` caps request bodies (413 past it); None reads
    `FOREMAST_INGEST_MAX_BODY_BYTES` (default 8 MiB).

    Overload shedding (ISSUE 9): `max_inflight` bounds concurrent push
    handlers (None reads ``FOREMAST_INGEST_MAX_INFLIGHT``, default 64;
    0 disables) — past it a push is answered 429 + ``Retry-After``
    BEFORE its body is read, so a pusher flood degrades to client-side
    retry-then-buffer (`RoutingPusher` treats 429 as transient) instead
    of a handler-thread pileup. `chaos` (chaos.EdgeChaos) injects
    latency/errors at the handler seam — faults are ANSWERED as their
    HTTP status, never raised into the server loop. `degrade_stats`
    (chaos.DegradeStats) counts sheds.

    `handoff` (mesh.handoff.HandoffManager, duck-typed): mounts the
    peer→peer transfer endpoint ``POST /api/v1/transfer`` — planned
    scale events stream ring series + fit entries here (404 when no
    handoff plane is wired). The body cap and the inflight shed apply
    to transfers exactly as to pushes: senders chunk batches under the
    cap and treat 429 as transient.

    `dirty` (reactive.DirtySet, ISSUE 12): every entry a push APPLIES
    samples for marks its route key dirty, stamped with the receiver's
    arrival clock (see the module docstring's clock contract) — the
    micro-tick trigger. Re-pushes mark too: a last-write-wins revision
    of an existing timestamp is exactly the spike-correction case that
    must re-judge. Only entries the ring wholly ignored (empty sample
    arrays) mark nothing."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    if max_body_bytes is None:
        max_body_bytes = int(
            os.environ.get("FOREMAST_INGEST_MAX_BODY_BYTES", "")
            or DEFAULT_MAX_BODY_BYTES
        )
    cap = int(max_body_bytes)
    if max_inflight is None:
        max_inflight = int(
            os.environ.get("FOREMAST_INGEST_MAX_INFLIGHT", "")
            or DEFAULT_MAX_INFLIGHT
        )
    inflight_cap = int(max_inflight)
    inflight = _Inflight()

    class Handler(BaseHTTPRequestHandler):
        # a half-sent body must free its daemon thread, not hold it
        # until process exit (StreamRequestHandler applies this to the
        # connection socket)
        timeout = HANDLER_TIMEOUT_SECONDS

        def log_message(self, *a):  # push traffic must not spam stderr
            pass

        def _send(
            self,
            code: int,
            body: bytes,
            ctype: str = "application/json",
            headers: dict | None = None,
        ):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_POST(self):
            with inflight:
                self._post()

        def _post(self):
            # the arrival instant, BEFORE the body is read or parsed:
            # a near-cap batch on a slow link can spend seconds in
            # read+parse, and that is in-system time the push→verdict
            # SLO must charge for, not silently exclude
            arrived_at = time.time()
            path = self.path.split("?", 1)[0]
            if path not in (WRITE_PATH, TRANSFER_PATH):
                self._send(404, b'{"reason": "not found"}')
                return
            if path == TRANSFER_PATH and handoff is None:
                self._send(404, b'{"reason": "no handoff plane"}')
                return
            # shed BEFORE reading the body: under overload the cheapest
            # possible answer, and the pusher's buffer (not our heap)
            # holds the samples until the flood passes
            if inflight_cap and inflight.count > inflight_cap:
                if degrade_stats is not None:
                    degrade_stats.count_event("receiver", "shed")
                self._send(
                    429,
                    b'{"reason": "receiver overloaded"}',
                    headers={"Retry-After": "1"},
                )
                return
            if chaos is not None:
                fault = chaos.perturb(path, raise_faults=False)
                if fault is not None:
                    self._send(
                        fault.status,
                        json.dumps({"reason": str(fault)}).encode(),
                    )
                    return
            length = int(self.headers.get("Content-Length", "0") or 0)
            if length > cap:
                # reject BEFORE buffering: an oversized push must not
                # make this thread read (or json-parse) the whole body
                self._send(
                    413,
                    json.dumps(
                        {
                            "reason": f"body {length} bytes exceeds "
                            f"cap {cap}"
                        }
                    ).encode(),
                )
                return
            try:
                raw = self.rfile.read(length)
            except OSError:
                return  # pusher died mid-body; nothing to answer
            if path == TRANSFER_PATH:
                # crc-framed peer transfer: the handoff plane applies
                # it (damage degrades per record, never a crash) and
                # reports what landed
                try:
                    code, body = handoff.apply_transfer(raw)
                except Exception as e:  # noqa: BLE001 — answer, don't die
                    log.exception("handoff transfer application failed")
                    code, body = 500, {"reason": str(e)}
                self._send(code, json.dumps(body).encode())
                return
            try:
                payload = json.loads(raw or b"{}")
                entries = parse_push(payload)
            # TypeError/KeyError/AttributeError backstop: a payload
            # shape the codec's explicit checks missed must still be a
            # 400 to the pusher, never a dropped handler thread
            except (WireError, ValueError, TypeError, KeyError,
                    AttributeError) as e:
                self._send(
                    400, json.dumps({"reason": str(e)}).encode()
                )
                return
            accepted = 0
            redirects: dict[str, str] = {}
            # ONE arrival instant for the whole batch, taken at handler
            # entry (pre-read, pre-parse): the SLO clock starts when
            # the samples reached us, not when each ring shard finished
            # applying
            for key, ts, vs, start in entries:
                if router is not None:
                    hint = router.redirect_hint(key)
                    if hint is not None:
                        redirects[key] = hint
                n_new = store.push(key, ts, vs, start=start)
                accepted += n_new
                if dirty is not None and n_new:
                    dirty.mark_series(key, now=arrived_at)
            body = {"accepted_samples": accepted, "series": len(entries)}
            if redirects:
                body["redirects"] = redirects
            self._send(200, json.dumps(body).encode())

        def do_GET(self):
            with inflight:
                self._get()

        def _get(self):
            path = self.path.split("?", 1)[0]
            if path == "/healthz":
                from foremast_tpu import __version__

                self._send(
                    200,
                    json.dumps(
                        {"ok": True, "version": __version__}
                    ).encode(),
                )
            elif path == "/debug/state":
                state = store.stats()
                if book is not None:
                    state["subscriptions"] = book.snapshot()
                self._send(
                    200, json.dumps(state, default=str, indent=2).encode()
                )
            else:
                self._send(404, b'{"reason": "not found"}')

    srv = ThreadingHTTPServer((host, port), Handler)
    # handler threads must never block interpreter exit (the wedge a
    # mid-shutdown push used to cause), and server_close must not join
    # them — stop_ingest_server does the bounded drain instead
    srv.daemon_threads = True
    srv.block_on_close = False
    srv._foremast_inflight = inflight  # stop_ingest_server reads this
    thread = threading.Thread(
        target=srv.serve_forever, name="foremast-ingest", daemon=True
    )
    thread.start()
    log.info("ingest receiver listening on :%d%s", srv.server_address[1], WRITE_PATH)
    return srv, thread


class _Inflight:
    """Context-managed handler counter the drain path polls."""

    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0

    def __enter__(self):
        with self._lock:
            self._count += 1
        return self

    def __exit__(self, *exc):
        with self._lock:
            self._count -= 1
        return False

    @property
    def count(self) -> int:
        with self._lock:
            return self._count


def stop_ingest_server(srv, drain_seconds: float = 5.0) -> bool:
    """Graceful receiver shutdown: stop accepting, drain in-flight
    handlers up to `drain_seconds`, then abandon stragglers (they are
    daemon threads with socket timeouts — they cannot wedge the
    process). Returns True when the drain completed clean."""
    srv.shutdown()  # stop serve_forever; no new connections accepted
    srv.server_close()  # release the listen socket (port reusable now)
    inflight = getattr(srv, "_foremast_inflight", None)
    deadline = time.monotonic() + drain_seconds
    while inflight is not None and inflight.count > 0:
        if time.monotonic() >= deadline:
            log.warning(
                "ingest receiver drain timed out with %d handler(s) "
                "in flight; abandoning them (daemon threads)",
                inflight.count,
            )
            return False
        time.sleep(0.02)
    return True
