"""Remote-write receiver + Prometheus exposition of the ingest plane.

`start_ingest_server` serves the push endpoint the way
`observe.spans.start_observe_server` serves the scrape endpoint — a
daemon-threaded `ThreadingHTTPServer`, so each pusher connection gets a
handler thread and the sharded store's per-shard locks absorb the
concurrency:

    POST /api/v1/write   remote-write-style JSON (wire.parse_push);
                         200 + {"accepted_samples", "series"} on
                         success, 400 with the reason on a malformed
                         payload — one bad entry rejects the batch so
                         pushers notice instead of silently losing
                         series
    GET  /healthz        liveness + version
    GET  /debug/state    the store's stats (series resident, bytes,
                         evictions, hit ratio, receiver lag)

`IngestCollector` exports the same stats as the `foremast_ingest_*`
metric families (docs/observability.md) via a custom collector —
counters and gauges are materialized from `RingStore.stats()` at scrape
time, so the hot push/fetch paths never touch prometheus_client.
"""

from __future__ import annotations

import json
import logging
import threading

from foremast_tpu.ingest.shards import RingStore
from foremast_tpu.ingest.wire import WireError, parse_push

log = logging.getLogger("foremast_tpu.ingest")

WRITE_PATH = "/api/v1/write"


class IngestCollector:
    """prometheus_client custom collector over `RingStore.stats()`."""

    def __init__(self, store: RingStore, book=None):
        self._store = store
        self._book = book

    def collect(self):
        from prometheus_client.core import (
            CounterMetricFamily,
            GaugeMetricFamily,
        )

        s = self._store.stats()
        fetches = CounterMetricFamily(
            "foremast_ingest_fetches",
            "ring TSDB fetch outcomes (hit=resident slice served, "
            "miss=series not resident, stale=pusher behind the window, "
            "uncovered=resident but not authoritative back to start)",
            labels=["result"],
        )
        for result, count_key in (
            ("hit", "hits"),
            ("miss", "misses"),
            ("stale", "stale"),
            ("uncovered", "uncovered"),
        ):
            fetches.add_metric([result], s[count_key])
        yield fetches
        yield CounterMetricFamily(
            "foremast_ingest_samples",
            "samples accepted by the ingest plane (receiver + direct push)",
            value=s["samples"],
        )
        yield CounterMetricFamily(
            "foremast_ingest_evictions",
            "whole series evicted under FOREMAST_INGEST_BUDGET_BYTES",
            value=s["evictions"],
        )
        yield GaugeMetricFamily(
            "foremast_ingest_series_resident",
            "series currently resident in the ring TSDB",
            value=s["series"],
        )
        yield GaugeMetricFamily(
            "foremast_ingest_bytes_resident",
            "column bytes currently allocated by resident series",
            value=s["bytes"],
        )
        lag = s.get("receiver_lag_seconds")
        yield GaugeMetricFamily(
            "foremast_ingest_receiver_lag_seconds",
            "now minus the newest sample timestamp of the latest push "
            "(-1 until the first push arrives)",
            value=-1.0 if lag is None else lag,
        )


def start_ingest_server(
    port: int,
    store: RingStore,
    host: str = "0.0.0.0",
    book=None,
):
    """Serve the push plane; returns (server, thread). Port 0 binds an
    ephemeral port (tests) — read it back from server.server_address."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # push traffic must not spam stderr
            pass

        def _send(self, code: int, body: bytes, ctype: str = "application/json"):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_POST(self):
            path = self.path.split("?", 1)[0]
            if path != WRITE_PATH:
                self._send(404, b'{"reason": "not found"}')
                return
            try:
                length = int(self.headers.get("Content-Length", "0") or 0)
                payload = json.loads(self.rfile.read(length) or b"{}")
                entries = parse_push(payload)
            # TypeError/KeyError/AttributeError backstop: a payload
            # shape the codec's explicit checks missed must still be a
            # 400 to the pusher, never a dropped handler thread
            except (WireError, ValueError, TypeError, KeyError,
                    AttributeError) as e:
                self._send(
                    400, json.dumps({"reason": str(e)}).encode()
                )
                return
            accepted = 0
            for key, ts, vs, start in entries:
                accepted += store.push(key, ts, vs, start=start)
            self._send(
                200,
                json.dumps(
                    {"accepted_samples": accepted, "series": len(entries)}
                ).encode(),
            )

        def do_GET(self):
            path = self.path.split("?", 1)[0]
            if path == "/healthz":
                from foremast_tpu import __version__

                self._send(
                    200,
                    json.dumps(
                        {"ok": True, "version": __version__}
                    ).encode(),
                )
            elif path == "/debug/state":
                state = store.stats()
                if book is not None:
                    state["subscriptions"] = book.snapshot()
                self._send(
                    200, json.dumps(state, default=str, indent=2).encode()
                )
            else:
                self._send(404, b'{"reason": "not found"}')

    srv = ThreadingHTTPServer((host, port), Handler)
    thread = threading.Thread(
        target=srv.serve_forever, name="foremast-ingest", daemon=True
    )
    thread.start()
    log.info("ingest receiver listening on :%d%s", srv.server_address[1], WRITE_PATH)
    return srv, thread
