"""Remote-write receiver + Prometheus exposition of the ingest plane.

`start_ingest_server` serves the push endpoint the way
`observe.spans.start_observe_server` serves the scrape endpoint — a
daemon-threaded `ThreadingHTTPServer`, so each pusher connection gets a
handler thread and the sharded store's per-shard locks absorb the
concurrency:

    POST /api/v1/write   remote-write-style push; TWO codecs on the
                         one route, negotiated by `Content-Type`
                         (ISSUE 18):
                           - JSON (default / `application/json`):
                             wire.parse_push — the bit-compatible
                             compat codec every existing pusher keeps
                             using unchanged
                           - binary (`application/x-foremast-remote-
                             write`): wire.decode_frame — the columnar
                             frame decoded as np.frombuffer views
                             straight into the ring columns
                         plus `Content-Encoding: snappy` on either.
                         200 + {"accepted_samples", "series"} on
                         success (plus a "redirects" {key: address}
                         map when a mesh router marks series another
                         member owns — samples are still accepted, so
                         the convergence window loses nothing), 400
                         with the reason on a malformed payload — one
                         bad entry rejects the batch so pushers notice
                         instead of silently losing series — and 413
                         when the body exceeds the byte cap
                         (`FOREMAST_INGEST_MAX_BODY_BYTES`) or the
                         DECLARED decoded size exceeds
                         `FOREMAST_INGEST_MAX_DECODED_BYTES`
    GET  /healthz        liveness + version
    GET  /debug/state    the store's stats (series resident, bytes,
                         evictions, hit ratio, receiver lag) + the
                         per-codec/per-stage wire breakdown

Decode pool: handler threads do socket I/O only; decompress + decode +
apply run on `FOREMAST_INGEST_DECODE_WORKERS` pooled threads (0 =
inline), so decode CPU is bounded by the pool width however many
pusher connections pile up, and a full decode queue sheds 429 exactly
like the inflight cap. Both codecs share ONE apply path
(`RingStore.push_batch` + identical redirect/dirty/response handling),
which is what makes statuses byte-identical across codecs by
construction. Per-request stage timings (read / decompress / decode /
apply) accumulate into `WireStats`, surfaced in /debug/state and the
`foremast_ingest_stage_seconds` / `foremast_ingest_requests` families.

Hardening: handler threads are daemons with a per-connection socket
timeout, request bodies are size-capped BEFORE json.loads (an
oversized push answers 413 without buffering the payload), the binary
path additionally rejects from the DECLARED size in the snappy
preamble / frame header before reading the rest of the body or
decompressing anything (snappy bomb guard — the same no-buffering
contract), and `stop_ingest_server` gives the worker's close path a
bounded drain — stop accepting, wait for in-flight handlers AND
pooled decode jobs up to a deadline, then abandon them to their
daemon fate instead of wedging shutdown. A push that reaches the pool
after close answers 503: it is never half-appended.

`IngestCollector` exports the same stats as the `foremast_ingest_*`
metric families (docs/observability.md) via a custom collector —
counters and gauges are materialized from `RingStore.stats()` at scrape
time, so the hot push/fetch paths never touch prometheus_client.

Reactive plane (ISSUE 12): with a `dirty` set wired
(`reactive.DirtySet`), every accepted push marks its series' route key
dirty so the worker's next micro-tick judges the affected documents.
**Arrival-clock contract:** the dirty stamp is THIS process's wall
clock taken at handler entry, BEFORE the body is read or parsed — the
RECEIVER's arrival instant — never the pusher's sample timestamps.
The push→verdict latency SLO (`foremast_verdict_latency_seconds`)
therefore measures time spent inside this system and is immune to
client clock skew: a pusher replaying old samples, or one with a fast
clock, moves its sample stamps but not the SLO.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import threading
import time

from foremast_tpu.ingest.shards import RingStore
from foremast_tpu.ingest.wire import (
    BINARY_CONTENT_TYPE,
    WireError,
    decode_frame,
    frame_decoded_len,
    parse_push,
    snappy_decompress,
    snappy_uncompressed_len,
)

log = logging.getLogger("foremast_tpu.ingest")

WRITE_PATH = "/api/v1/write"
# peer→peer planned-handoff endpoint (mesh/handoff.py): crc-framed
# transfer batches from a draining member or a joiner's current owners
TRANSFER_PATH = "/api/v1/transfer"
DEFAULT_MAX_BODY_BYTES = 8 * 1024 * 1024
# decoded-size ceiling for the binary path: the snappy preamble / frame
# header DECLARES the decoded size, and anything past this cap is 413'd
# before a byte is decompressed (FOREMAST_INGEST_MAX_DECODED_BYTES)
DEFAULT_MAX_DECODED_BYTES = 32 * 1024 * 1024
# pooled decode worker threads (FOREMAST_INGEST_DECODE_WORKERS; 0 =
# decode inline on the handler thread)
DEFAULT_DECODE_WORKERS = 4
# concurrent push handlers allowed before the receiver sheds with
# 429 + Retry-After (FOREMAST_INGEST_MAX_INFLIGHT; 0 = unbounded)
DEFAULT_MAX_INFLIGHT = 64
# a handler stuck mid-read (pusher died with the body half-sent) frees
# its thread after this instead of holding it forever
HANDLER_TIMEOUT_SECONDS = 30.0

_STAGES = ("read", "decompress", "decode", "apply")


class WireStats:
    """Per-codec, per-stage wall-clock accumulation for the push path.
    One `record` per request, AFTER the shard locks are released — the
    stats lock never nests inside a store lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._codecs: dict[str, dict] = {}

    def record(
        self, codec: str, stages: dict, samples: int, ok: bool
    ) -> None:
        with self._lock:
            c = self._codecs.get(codec)
            if c is None:
                c = self._codecs[codec] = {
                    "requests": 0,
                    "rejected": 0,
                    "samples": 0,
                    "stage_seconds": dict.fromkeys(_STAGES, 0.0),
                }
            c["requests"] += 1
            if not ok:
                c["rejected"] += 1
            c["samples"] += samples
            acc = c["stage_seconds"]
            for stage, seconds in stages.items():
                acc[stage] = acc.get(stage, 0.0) + seconds

    def snapshot(self) -> dict:
        with self._lock:
            return {
                codec: {
                    "requests": c["requests"],
                    "rejected": c["rejected"],
                    "samples": c["samples"],
                    "stage_seconds": dict(c["stage_seconds"]),
                }
                for codec, c in self._codecs.items()
            }


class _PoolClosed(Exception):
    """Submitted after close — the push answers 503 (never half-applied)."""


class _PoolBusy(Exception):
    """Decode queue full — the push answers 429 (pusher retries)."""


class _DecodeJob:
    """One pooled decode+apply. State machine keeps the shutdown
    contract honest: a job is either RUN TO COMPLETION (fully applied,
    200/400 answered) or CANCELLED BEFORE STARTING (503/429) — there is
    no state where half its series landed in the shards."""

    __slots__ = ("fn", "done", "result", "_state", "_lock")
    PENDING, RUNNING, CANCELLED = 0, 1, 2

    def __init__(self, fn):
        self.fn = fn
        self.done = threading.Event()
        self.result = None
        self._state = self.PENDING
        self._lock = threading.Lock()

    def try_start(self) -> bool:
        with self._lock:
            if self._state != self.PENDING:
                return False
            self._state = self.RUNNING
            return True

    def try_cancel(self) -> bool:
        with self._lock:
            if self._state != self.PENDING:
                return False
            self._state = self.CANCELLED
            return True


class _DecodePool:
    """Bounded decode worker pool. Width bounds decode CPU (handler
    threads scale with connections; these do not), the queue bounds
    memory, and `close` is the pooled half of the shutdown drain."""

    def __init__(self, workers: int, queue_depth: int | None = None):
        self.workers = max(0, int(workers))
        self._lock = threading.Lock()
        self._closed = False
        self._pending = 0
        self._q: queue.Queue | None = None
        if self.workers:
            self._q = queue.Queue(maxsize=queue_depth or 4 * self.workers)
            for i in range(self.workers):
                threading.Thread(
                    target=self._run,
                    name=f"foremast-ingest-decode-{i}",
                    daemon=True,
                ).start()

    def _admit(self) -> None:
        with self._lock:
            if self._closed:
                raise _PoolClosed
            self._pending += 1

    def _done(self) -> None:
        with self._lock:
            self._pending -= 1

    def run(self, fn, wait_seconds: float = HANDLER_TIMEOUT_SECONDS):
        """Execute `fn` on a pool thread (inline when workers=0) and
        return its result. Raises _PoolClosed after close, _PoolBusy
        when the queue stays full or the job cannot start in time."""
        self._admit()
        if self._q is None:
            try:
                return _run_guarded(fn)
            finally:
                self._done()
        job = _DecodeJob(fn)
        try:
            self._q.put(job, timeout=0.25)
        except queue.Full:
            self._done()
            raise _PoolBusy from None
        if not job.done.wait(wait_seconds):
            if job.try_cancel():
                # never started: nothing applied, safe to shed
                self._done()
                raise _PoolBusy from None
            # already running: the apply itself is bounded, wait it out
            job.done.wait()
        self._done()
        return job.result

    def _run(self) -> None:
        while True:
            job = self._q.get()
            if job is None:
                return  # poison pill from close()
            if not job.try_start():
                job.done.set()  # cancelled while queued
                continue
            job.result = _run_guarded(job.fn)
            job.done.set()

    def close(self, deadline: float) -> bool:
        """Stop admitting, wait for pending jobs until the monotonic
        `deadline`, then poison the workers. True when fully drained."""
        with self._lock:
            self._closed = True
        clean = True
        while True:
            with self._lock:
                pending = self._pending
            if pending == 0:
                break
            if time.monotonic() >= deadline:
                log.warning(
                    "ingest decode pool drain timed out with %d job(s) "
                    "pending; abandoning them (daemon threads)",
                    pending,
                )
                clean = False
                break
            time.sleep(0.02)
        if self._q is not None:
            for _ in range(self.workers):
                try:
                    self._q.put_nowait(None)
                except queue.Full:
                    break
        return clean


def _run_guarded(fn):
    """A decode job must always produce an HTTP answer: an unexpected
    exception becomes a logged 500, never a dead handler thread."""
    try:
        return fn()
    except Exception as e:  # noqa: BLE001 — answer, don't die
        log.exception("ingest decode job failed")
        return 500, json.dumps({"reason": str(e)}).encode()


class IngestCollector:
    """prometheus_client custom collector over `RingStore.stats()`."""

    def __init__(self, store: RingStore, book=None, wire: WireStats | None = None):
        self._store = store
        self._book = book
        self._wire = wire

    def collect(self):
        from prometheus_client.core import (
            CounterMetricFamily,
            GaugeMetricFamily,
        )

        s = self._store.stats()
        fetches = CounterMetricFamily(
            "foremast_ingest_fetches",
            "ring TSDB fetch outcomes (hit=resident slice served, "
            "partial=short-history admission slice served, "
            "miss=series not resident, stale=pusher behind the window, "
            "uncovered=resident but not authoritative back to start)",
            labels=["result"],
        )
        for result, count_key in (
            ("hit", "hits"),
            ("partial", "partial"),
            ("miss", "misses"),
            ("stale", "stale"),
            ("uncovered", "uncovered"),
        ):
            fetches.add_metric([result], s[count_key])
        yield fetches
        yield CounterMetricFamily(
            "foremast_ingest_samples",
            "samples accepted by the ingest plane (receiver + direct push)",
            value=s["samples"],
        )
        yield CounterMetricFamily(
            "foremast_ingest_evictions",
            "whole series evicted under FOREMAST_INGEST_BUDGET_BYTES",
            value=s["evictions"],
        )
        yield GaugeMetricFamily(
            "foremast_ingest_series_resident",
            "series currently resident in the ring TSDB",
            value=s["series"],
        )
        yield GaugeMetricFamily(
            "foremast_ingest_bytes_resident",
            "column bytes currently allocated by resident series",
            value=s["bytes"],
        )
        lag = s.get("receiver_lag_seconds")
        yield GaugeMetricFamily(
            "foremast_ingest_receiver_lag_seconds",
            "now minus the newest sample timestamp of the latest push "
            "(-1 until the first push arrives)",
            value=-1.0 if lag is None else lag,
        )
        if self._wire is not None:
            w = self._wire.snapshot()
            requests = CounterMetricFamily(
                "foremast_ingest_requests",
                "push requests decoded by the receiver, by wire codec "
                "(json=compat codec, binary=columnar frame)",
                labels=["codec"],
            )
            stage = CounterMetricFamily(
                "foremast_ingest_stage_seconds",
                "wall-clock seconds spent per receiver pipeline stage "
                "(read / decompress / decode / apply), by wire codec",
                labels=["codec", "stage"],
            )
            for codec in sorted(w):
                requests.add_metric([codec], w[codec]["requests"])
                for st in _STAGES:
                    stage.add_metric(
                        [codec, st],
                        w[codec]["stage_seconds"].get(st, 0.0),
                    )
            yield requests
            yield stage


def start_ingest_server(
    port: int,
    store: RingStore,
    host: str = "0.0.0.0",
    book=None,
    router=None,
    max_body_bytes: int | None = None,
    max_inflight: int | None = None,
    chaos=None,
    degrade_stats=None,
    handoff=None,
    dirty=None,
    decode_workers: int | None = None,
    max_decoded_bytes: int | None = None,
    tenancy=None,
):
    """Serve the push plane; returns (server, thread). Port 0 binds an
    ephemeral port (tests) — read it back from server.server_address.

    `router` (mesh.routing.MeshRouter, optional): pushes for series
    another mesh member owns are accepted into the local ring (lossless
    during convergence, LRU reclaims them) AND answered with the
    owner's advertised address in the response's `redirects` map, so a
    mesh-aware pusher lands on the right shard from its next cycle.

    `max_body_bytes` caps request bodies (413 past it); None reads
    `FOREMAST_INGEST_MAX_BODY_BYTES` (default 8 MiB).

    Overload shedding (ISSUE 9): `max_inflight` bounds concurrent push
    handlers (None reads ``FOREMAST_INGEST_MAX_INFLIGHT``, default 64;
    0 disables) — past it a push is answered 429 + ``Retry-After``
    BEFORE its body is read, so a pusher flood degrades to client-side
    retry-then-buffer (`RoutingPusher` treats 429 as transient) instead
    of a handler-thread pileup. `chaos` (chaos.EdgeChaos) injects
    latency/errors at the handler seam — faults are ANSWERED as their
    HTTP status, never raised into the server loop. `degrade_stats`
    (chaos.DegradeStats) counts sheds.

    `handoff` (mesh.handoff.HandoffManager, duck-typed): mounts the
    peer→peer transfer endpoint ``POST /api/v1/transfer`` — planned
    scale events stream ring series + fit entries here (404 when no
    handoff plane is wired). The body cap and the inflight shed apply
    to transfers exactly as to pushes: senders chunk batches under the
    cap and treat 429 as transient.

    `dirty` (reactive.DirtySet, ISSUE 12): every entry a push APPLIES
    samples for marks its route key dirty, stamped with the receiver's
    arrival clock (see the module docstring's clock contract) — the
    micro-tick trigger. Re-pushes mark too: a last-write-wins revision
    of an existing timestamp is exactly the spike-correction case that
    must re-judge. Only entries the ring wholly ignored (empty sample
    arrays) mark nothing. The contract is codec-independent.

    `decode_workers` / `max_decoded_bytes` (ISSUE 18): pooled decode
    width (None reads ``FOREMAST_INGEST_DECODE_WORKERS``, default 4;
    0 decodes inline) and the declared-decoded-size 413 ceiling (None
    reads ``FOREMAST_INGEST_MAX_DECODED_BYTES``, default 32 MiB).

    `tenancy` (tenant.TenantRegistry, ISSUE 20; None reads the
    process-global ``FOREMAST_TENANTS`` registry): per-tenant ingest
    byte-rate envelopes enforced AFTER decode (tenant identity lives in
    the series labels) and BEFORE apply, on both codecs by construction
    — a batch whose dominant-by-bytes tenant is over its envelope is
    shed whole with 429 + a computed Retry-After charged to THAT
    tenant, while every other tenant's pushes sail through. The global
    inflight cap and decode-pool depth remain tenant-blind backstops;
    decode-pool sheds are blamed on the deepest-over-envelope tenant
    (`IngestGovernor.blame`). Unconfigured fleets shed exactly as
    before."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    if max_body_bytes is None:
        max_body_bytes = int(
            os.environ.get("FOREMAST_INGEST_MAX_BODY_BYTES", "")
            or DEFAULT_MAX_BODY_BYTES
        )
    cap = int(max_body_bytes)
    if max_inflight is None:
        max_inflight = int(
            os.environ.get("FOREMAST_INGEST_MAX_INFLIGHT", "")
            or DEFAULT_MAX_INFLIGHT
        )
    inflight_cap = int(max_inflight)
    if decode_workers is None:
        decode_workers = int(
            os.environ.get("FOREMAST_INGEST_DECODE_WORKERS", "")
            or DEFAULT_DECODE_WORKERS
        )
    if max_decoded_bytes is None:
        max_decoded_bytes = int(
            os.environ.get("FOREMAST_INGEST_MAX_DECODED_BYTES", "")
            or DEFAULT_MAX_DECODED_BYTES
        )
    decoded_cap = int(max_decoded_bytes)
    if tenancy is None:
        from foremast_tpu.tenant.registry import get_tenancy

        tenancy = get_tenancy()
    governor = None
    accounting = None
    if tenancy is not None:
        from foremast_tpu.tenant.accounting import accounting_for
        from foremast_tpu.tenant.envelopes import IngestGovernor

        accounting = accounting_for(tenancy)
        governor = IngestGovernor(tenancy)
    inflight = _Inflight()
    pool = _DecodePool(decode_workers)
    wire_stats = WireStats()
    # bytes -> canonical key str, shared across decode workers. Plain
    # dict on purpose: get/setitem are single-opcode atomic under the
    # GIL, and a racing double-insert writes the identical value.
    intern_cache: dict[bytes, str] = {}

    def decode_apply(raw, codec, snappy_enc, arrived_at, read_s):
        """The pooled stage pipeline: decompress → decode → apply, one
        codec switch and ONE shared apply path (push_batch + redirects
        + dirty marks + response shape), so the two codecs cannot
        drift apart in observable behavior. Returns (status, body) or
        (status, body, headers) — the tenant-shed 429 carries its
        computed Retry-After."""
        stages = {"read": read_s, "decompress": 0.0, "decode": 0.0,
                  "apply": 0.0}
        try:
            if snappy_enc:
                t0 = time.perf_counter()
                raw = snappy_decompress(raw, max_len=decoded_cap)
                stages["decompress"] = time.perf_counter() - t0
            t0 = time.perf_counter()
            if codec == "binary":
                entries = decode_frame(
                    raw, intern_cache, canonicalize=True
                )
            else:
                entries = parse_push(json.loads(raw or b"{}"))
            stages["decode"] = time.perf_counter() - t0
        # TypeError/KeyError/AttributeError backstop: a payload shape
        # the codec's explicit checks missed must still be a 400 to the
        # pusher, never a dropped handler thread
        except (WireError, ValueError, TypeError, KeyError,
                AttributeError) as e:
            wire_stats.record(codec, stages, samples=0, ok=False)
            return 400, json.dumps({"reason": str(e)}).encode()
        if governor is not None and entries:
            # tenant admission (ISSUE 20): post-decode because tenant
            # identity lives in the series labels, pre-apply so a shed
            # batch lands NOTHING. The whole batch is charged to its
            # dominant-by-bytes tenant and shed atomically — re-pushes
            # are idempotent at the ring, and a batching agent that
            # mixes tenants shares the dominant tenant's fate
            # (docs/operations.md "Multi-tenant QoS").
            by_tenant: dict[str, int] = {}
            total = 0
            for key, ts, vs, _start in entries:
                nb = int(getattr(ts, "nbytes", 0)) + int(
                    getattr(vs, "nbytes", 0)
                )
                t = tenancy.tenant_of_series(key)
                by_tenant[t] = by_tenant.get(t, 0) + nb
                total += nb
            dominant = max(by_tenant, key=by_tenant.get)
            retry = governor.admit(dominant, total, time.monotonic())
            if retry > 0:
                accounting.count_shed(dominant)
                if degrade_stats is not None:
                    degrade_stats.count_event("receiver", "tenant_shed")
                wire_stats.record(codec, stages, samples=0, ok=False)
                return (
                    429,
                    json.dumps(
                        {
                            "reason": "tenant over ingest envelope",
                            "tenant": dominant,
                        }
                    ).encode(),
                    {"Retry-After": str(int(retry))},
                )
        t0 = time.perf_counter()
        redirects: dict[str, str] = {}
        if router is not None:
            for key, _ts, _vs, _start in entries:
                hint = router.redirect_hint(key)
                if hint is not None:
                    redirects[key] = hint
        # striped shard-batch apply: one lock acquisition per touched
        # shard for the whole payload (binary keys are canonical out of
        # decode_frame's interning; JSON keys canonicalize in-store)
        counts = store.push_batch(entries, canonical=(codec == "binary"))
        accepted = sum(counts)
        if dirty is not None:
            # ONE arrival instant for the whole batch, taken at handler
            # entry (pre-read, pre-parse): the SLO clock starts when
            # the samples reached us, not when each ring shard finished
            # applying
            for (key, _ts, _vs, _start), n_new in zip(entries, counts):
                if n_new:
                    dirty.mark_series(key, now=arrived_at)
        stages["apply"] = time.perf_counter() - t0
        wire_stats.record(codec, stages, samples=accepted, ok=True)
        body = {"accepted_samples": accepted, "series": len(entries)}
        if redirects:
            body["redirects"] = redirects
        return 200, json.dumps(body).encode()

    class Handler(BaseHTTPRequestHandler):
        # a half-sent body must free its daemon thread, not hold it
        # until process exit (StreamRequestHandler applies this to the
        # connection socket)
        timeout = HANDLER_TIMEOUT_SECONDS

        def log_message(self, *a):  # push traffic must not spam stderr
            pass

        def _send(
            self,
            code: int,
            body: bytes,
            ctype: str = "application/json",
            headers: dict | None = None,
        ):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_POST(self):
            with inflight:
                self._post()

        def _post(self):
            # the arrival instant, BEFORE the body is read or parsed:
            # a near-cap batch on a slow link can spend seconds in
            # read+parse, and that is in-system time the push→verdict
            # SLO must charge for, not silently exclude
            arrived_at = time.time()
            path = self.path.split("?", 1)[0]
            if path not in (WRITE_PATH, TRANSFER_PATH):
                self._send(404, b'{"reason": "not found"}')
                return
            if path == TRANSFER_PATH and handoff is None:
                self._send(404, b'{"reason": "no handoff plane"}')
                return
            # shed BEFORE reading the body: under overload the cheapest
            # possible answer, and the pusher's buffer (not our heap)
            # holds the samples until the flood passes
            if inflight_cap and inflight.count > inflight_cap:
                if degrade_stats is not None:
                    degrade_stats.count_event("receiver", "shed")
                self._send(
                    429,
                    b'{"reason": "receiver overloaded"}',
                    headers={"Retry-After": "1"},
                )
                return
            if chaos is not None:
                fault = chaos.perturb(path, raise_faults=False)
                if fault is not None:
                    self._send(
                        fault.status,
                        json.dumps({"reason": str(fault)}).encode(),
                    )
                    return
            length = int(self.headers.get("Content-Length", "0") or 0)
            if length > cap:
                # reject BEFORE buffering: an oversized push must not
                # make this thread read (or json-parse) the whole body
                self._send(
                    413,
                    json.dumps(
                        {
                            "reason": f"body {length} bytes exceeds "
                            f"cap {cap}"
                        }
                    ).encode(),
                )
                return
            ctype = (
                (self.headers.get("Content-Type") or "")
                .split(";", 1)[0]
                .strip()
                .lower()
            )
            codec = "binary" if ctype == BINARY_CONTENT_TYPE else "json"
            enc = (
                (self.headers.get("Content-Encoding") or "identity")
                .strip()
                .lower()
            )
            if enc not in ("", "identity", "snappy"):
                self._send(
                    400,
                    json.dumps(
                        {
                            "reason": f"unsupported Content-Encoding "
                            f"{enc!r} (identity or snappy)"
                        }
                    ).encode(),
                )
                return
            snappy_enc = enc == "snappy"
            peek = b""
            t_read0 = time.perf_counter()
            if path == WRITE_PATH and (snappy_enc or codec == "binary"):
                # snappy bomb guard: the DECLARED decoded size lives in
                # the first bytes (snappy varint preamble / FMW1 frame
                # header) — peek it and 413 before reading the rest of
                # the body, let alone decompressing it. A malformed
                # header falls through: the decode stage owns the 400.
                try:
                    peek = self.rfile.read(min(length, 32))
                except OSError:
                    return
                declared = None
                try:
                    declared = (
                        snappy_uncompressed_len(peek)
                        if snappy_enc
                        else frame_decoded_len(peek)
                    )
                except WireError:
                    pass
                if declared is not None and declared > decoded_cap:
                    self._send(
                        413,
                        json.dumps(
                            {
                                "reason": f"declared decoded size "
                                f"{declared} bytes exceeds cap "
                                f"{decoded_cap}"
                            }
                        ).encode(),
                    )
                    return
            try:
                raw = peek + self.rfile.read(length - len(peek))
            except OSError:
                return  # pusher died mid-body; nothing to answer
            read_s = time.perf_counter() - t_read0
            if path == TRANSFER_PATH:
                # crc-framed peer transfer: the handoff plane applies
                # it (damage degrades per record, never a crash) and
                # reports what landed
                try:
                    code, body = handoff.apply_transfer(raw)
                except Exception as e:  # noqa: BLE001 — answer, don't die
                    log.exception("handoff transfer application failed")
                    code, body = 500, {"reason": str(e)}
                self._send(code, json.dumps(body).encode())
                return
            try:
                res = pool.run(
                    lambda: decode_apply(
                        raw, codec, snappy_enc, arrived_at, read_s
                    )
                )
            except _PoolClosed:
                # receiver draining: the job never started, so nothing
                # was applied — the pusher's retry lands on another
                # member (RoutingPusher treats 503 as transient)
                self._send(
                    503,
                    b'{"reason": "receiver draining"}',
                    headers={"Retry-After": "1"},
                )
                return
            except _PoolBusy:
                if degrade_stats is not None:
                    degrade_stats.count_event("receiver", "decode_shed")
                if governor is not None:
                    # pre-decode shed: no tenant can be KNOWN yet, but
                    # the deepest-over-envelope tenant is the queue
                    # pressure's overwhelmingly likely source — charge
                    # it so decode sheds stay attributed (ISSUE 20)
                    blamed = governor.blame(time.monotonic())
                    if blamed is not None:
                        accounting.count_shed(blamed)
                self._send(
                    429,
                    b'{"reason": "decode queue full"}',
                    headers={"Retry-After": "1"},
                )
                return
            code, out = res[0], res[1]
            self._send(
                code, out, headers=res[2] if len(res) > 2 else None
            )

        def do_GET(self):
            with inflight:
                self._get()

        def _get(self):
            path = self.path.split("?", 1)[0]
            if path == "/healthz":
                from foremast_tpu import __version__

                self._send(
                    200,
                    json.dumps(
                        {"ok": True, "version": __version__}
                    ).encode(),
                )
            elif path == "/debug/state":
                state = store.stats()
                state["wire"] = wire_stats.snapshot()
                if book is not None:
                    state["subscriptions"] = book.snapshot()
                if tenancy is not None:
                    from foremast_tpu.tenant.collector import debug_tenants

                    state["tenants"] = debug_tenants(
                        tenancy, accounting, governor
                    )
                self._send(
                    200, json.dumps(state, default=str, indent=2).encode()
                )
            else:
                self._send(404, b'{"reason": "not found"}')

    srv = ThreadingHTTPServer((host, port), Handler)
    # handler threads must never block interpreter exit (the wedge a
    # mid-shutdown push used to cause), and server_close must not join
    # them — stop_ingest_server does the bounded drain instead
    srv.daemon_threads = True
    srv.block_on_close = False
    srv._foremast_inflight = inflight  # stop_ingest_server reads this
    srv._foremast_decode_pool = pool  # ... and drains this
    srv._foremast_wire_stats = wire_stats  # collectors scrape this
    thread = threading.Thread(
        target=srv.serve_forever, name="foremast-ingest", daemon=True
    )
    thread.start()
    log.info("ingest receiver listening on :%d%s", srv.server_address[1], WRITE_PATH)
    return srv, thread


class _Inflight:
    """Context-managed handler counter the drain path polls."""

    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0

    def __enter__(self):
        with self._lock:
            self._count += 1
        return self

    def __exit__(self, *exc):
        with self._lock:
            self._count -= 1
        return False

    @property
    def count(self) -> int:
        with self._lock:
            return self._count


def stop_ingest_server(srv, drain_seconds: float = 5.0) -> bool:
    """Graceful receiver shutdown: stop accepting, drain in-flight
    handlers AND pooled decode jobs up to `drain_seconds`, then abandon
    stragglers (they are daemon threads with socket timeouts — they
    cannot wedge the process). The pool drain is the half the original
    drain missed (ISSUE 18 satellite): a handler can have handed its
    frame to a decode worker and be gone, so counting handlers alone
    could close with a batch mid-apply. The pool refuses new jobs the
    moment close starts (those pushes answer 503 with NOTHING applied)
    and started jobs run to completion — a push at shutdown is either
    fully applied or cleanly 503'd, never half-appended. Returns True
    when both drains completed clean."""
    srv.shutdown()  # stop serve_forever; no new connections accepted
    srv.server_close()  # release the listen socket (port reusable now)
    inflight = getattr(srv, "_foremast_inflight", None)
    deadline = time.monotonic() + drain_seconds
    clean = True
    while inflight is not None and inflight.count > 0:
        if time.monotonic() >= deadline:
            log.warning(
                "ingest receiver drain timed out with %d handler(s) "
                "in flight; abandoning them (daemon threads)",
                inflight.count,
            )
            clean = False
            break
        time.sleep(0.02)
    pool = getattr(srv, "_foremast_decode_pool", None)
    if pool is not None:
        clean = pool.close(deadline) and clean
    return clean
