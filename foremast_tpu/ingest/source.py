"""`RingSource` — the worker-facing adapter of the ingest plane.

A `MetricSource` whose warm path is a resident ring-slice gather: the
worker's slow and fast tick paths only ever see the `MetricSource`
interface, so pull/push parity is structural — the same `_fetch_tasks`
/ `_fast_tick` code runs either way, only `fetch()`'s cost changes.
`concurrent_fetch` DELEGATES to the wrapped fallback: a warm fetch is
an in-memory gather, but the miss/stale path is the fallback's real
blocking I/O — a fleet-cold first tick (or a dead-pusher tick) must
fan 16k HTTP round trips over the fetch pool, not serialize them on
the tick thread. Pure-push mode (no fallback) declares False like the
other in-memory sources.

Miss handling (see `backfill`): unresolvable URLs bypass the ring;
resolvable misses are recorded in the subscription book, served by the
wrapped fallback source (the real `PrometheusSource` in production),
and backfilled so the next tick hits. With no fallback the source is
pure-push: a miss returns the empty series and the brain yields
UNKNOWN, never a crash.
"""

from __future__ import annotations

import logging
import time

from foremast_tpu.ingest.backfill import SubscriptionBook, backfill
from foremast_tpu.ingest.ring import empty_series as _empty
from foremast_tpu.ingest.shards import RingStore
from foremast_tpu.ingest.wire import resolve_query_range
from foremast_tpu.metrics.source import MetricSource, Series

log = logging.getLogger("foremast_tpu.ingest")


class RingSource(MetricSource):
    # url -> (key, t0, t1, step) memo bound: a fleet's URL set is
    # stable (two per document per alias), so this is effectively
    # "decode each URL once per process"; the crude clear-on-overflow
    # matches the worker's admission-cache discipline
    RESOLVE_CACHE_MAX = 1_048_576

    def __init__(
        self,
        ring: RingStore,
        fallback: MetricSource | None = None,
        clock=time.time,
    ):
        self.ring = ring
        self.fallback = fallback
        self.book = SubscriptionBook()
        self._clock = clock
        # Warm fetches are the per-tick hot loop (one per window per
        # tick at fleet scale) and `resolve_query_range` — urlparse +
        # parse_qs + selector canonicalization — costs ~25-35 µs, an
        # order of magnitude more than the ring gather it guards.
        # Document URLs are immutable per doc id, so the resolution is
        # memoized: a warm fetch is a dict hit + ring slice.
        self._resolved: dict[str, tuple] = {}

    @property
    def concurrent_fetch(self) -> bool:
        # see module docstring: the miss path is the fallback's I/O
        return bool(
            self.fallback is not None
            and getattr(self.fallback, "concurrent_fetch", True)
        )

    def fetch(self, url: str) -> Series:
        resolved = self._resolved.get(url)
        if resolved is None:
            if len(self._resolved) > self.RESOLVE_CACHE_MAX:
                self._resolved.clear()  # crude bound; repopulates
            resolved = resolve_query_range(url)
            self._resolved[url] = resolved
        key, t0, t1, step = resolved
        if key is None:
            # no recognizable series identity: never warmable, straight
            # through to the wrapped source
            if self.fallback is None:
                return _empty()
            return self.fallback.fetch(url)
        now = self._clock()
        status, ts, vs = self.ring.query(key, t0, t1, now, step=step)
        if status == "hit":
            return ts, vs
        self.book.record(key, url, status)
        if self.fallback is None:
            return ts, vs  # pure-push mode: empty series => UNKNOWN
        series = self.fallback.fetch(url)
        head = now if t1 is None else min(t1, now)
        backfill(self.ring, key, series, start=t0, end=head, now=now)
        return series

    def ingest_debug_state(self) -> dict:
        """The worker `/debug/state` `ingest` section (duck-typed hook:
        `BrainWorker.debug_state` includes any source exposing this)."""
        state = self.ring.stats()
        state["subscriptions"] = self.book.snapshot()
        state["fallback"] = type(self.fallback).__name__ if self.fallback else None
        return state
