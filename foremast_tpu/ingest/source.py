"""`RingSource` — the worker-facing adapter of the ingest plane.

A `MetricSource` whose warm path is a resident ring-slice gather: the
worker's slow and fast tick paths only ever see the `MetricSource`
interface, so pull/push parity is structural — the same `_fetch_tasks`
/ `_fast_tick` code runs either way, only `fetch()`'s cost changes.
`concurrent_fetch` DELEGATES to the wrapped fallback: a warm fetch is
an in-memory gather, but the miss/stale path is the fallback's real
blocking I/O — a fleet-cold first tick (or a dead-pusher tick) must
fan 16k HTTP round trips over the fetch pool, not serialize them on
the tick thread. Pure-push mode (no fallback) declares False like the
other in-memory sources.

Miss handling (see `backfill`): unresolvable URLs bypass the ring;
resolvable misses are recorded in the subscription book, served by the
wrapped fallback source (the real `PrometheusSource` in production),
and backfilled so the next tick hits. With no fallback the source is
pure-push: a miss returns the empty series and the brain yields
UNKNOWN, never a crash.
"""

from __future__ import annotations

import logging
import os
import time

from foremast_tpu.ingest.backfill import SubscriptionBook, backfill
from foremast_tpu.ingest.ring import empty_series as _empty
from foremast_tpu.ingest.shards import RingStore
from foremast_tpu.ingest.wire import resolve_query_range
from foremast_tpu.metrics.source import MetricSource, Series

log = logging.getLogger("foremast_tpu.ingest")

# Short-history admission floor (ISSUE 10): a newcomer series whose
# live coverage span holds at least this many seconds of fresh data is
# admissible for a PROVISIONAL cold fit straight from the ring — a
# verdict-capable short history in its first tick instead of a miss.
# One day default: enough for every detector's measurability gates at
# the reference 60 s step (1,440 points >> min_historical_points and
# the LSTM 2-window floor) while staying well under the 7-day target
# the background refinement converges to.
DEFAULT_ADMIT_FLOOR_SECONDS = 86_400.0


class RingSource(MetricSource):
    # url -> (key, t0, t1, step) memo bound: a fleet's URL set is
    # stable (two per document per alias), so this is effectively
    # "decode each URL once per process"; the crude clear-on-overflow
    # matches the worker's admission-cache discipline
    RESOLVE_CACHE_MAX = 1_048_576

    def __init__(
        self,
        ring: RingStore,
        fallback: MetricSource | None = None,
        clock=time.time,
        admit_floor: float | None = None,
    ):
        self.ring = ring
        self.fallback = fallback
        self.book = SubscriptionBook()
        self._clock = clock
        if admit_floor is None:
            admit_floor = float(
                os.environ.get("FOREMAST_ADMIT_MIN_COVERAGE_SECONDS", "")
                or DEFAULT_ADMIT_FLOOR_SECONDS
            )
        # seconds of fresh coverage a series needs before a historical
        # range it cannot fully serve is admitted as a PROVISIONAL
        # short history (hist_columns); 0 disables partial admission
        self.admit_floor = float(admit_floor)
        # Warm fetches are the per-tick hot loop (one per window per
        # tick at fleet scale) and `resolve_query_range` — urlparse +
        # parse_qs + selector canonicalization — costs ~25-35 µs, an
        # order of magnitude more than the ring gather it guards.
        # Document URLs are immutable per doc id, so the resolution is
        # memoized: a warm fetch is a dict hit + ring slice.
        self._resolved: dict[str, tuple] = {}

    @property
    def concurrent_fetch(self) -> bool:
        # see module docstring: the miss path is the fallback's I/O
        return bool(
            self.fallback is not None
            and getattr(self.fallback, "concurrent_fetch", True)
        )

    def _resolve(self, url: str) -> tuple:
        resolved = self._resolved.get(url)
        if resolved is None:
            if len(self._resolved) > self.RESOLVE_CACHE_MAX:
                self._resolved.clear()  # crude bound; repopulates
            resolved = resolve_query_range(url)
            self._resolved[url] = resolved
        return resolved

    def fetch(self, url: str) -> Series:
        key, t0, t1, step = self._resolve(url)
        if key is None:
            # no recognizable series identity: never warmable, straight
            # through to the wrapped source
            if self.fallback is None:
                return _empty()
            return self.fallback.fetch(url)
        now = self._clock()
        status, ts, vs = self.ring.query(key, t0, t1, now, step=step)
        if status == "hit":
            return ts, vs
        self.book.record(key, url, status)
        if self.fallback is None:
            return ts, vs  # pure-push mode: empty series => UNKNOWN
        series = self.fallback.fetch(url)
        head = now if t1 is None else min(t1, now)
        backfill(self.ring, key, series, start=t0, end=head, now=now)
        return series

    # -- ring-resident historical reads (ISSUE 10 tentpole) ---------------

    def hist_columns(self, url: str, now: float | None = None):
        """Serve a historical range straight from the ring's resident
        columns — the worker's cold-fit read path (jobs/worker.py
        `_fetch_hist`), which bypasses its host-side `_hist_cache`
        entirely when the ring can serve (no double-buffering, no HTTP,
        no JSON reassembly: the slice IS the stored column).

        Returns (status, times, values, (cov_from, cov_to), (t0, t1))
        with status "full" (the ring covers the whole requested range)
        or "partial" (short-history admission: the live span holds >=
        `admit_floor` seconds — a PROVISIONAL fit, refined in the
        background as coverage grows). None when the ring cannot serve:
        the caller falls back to `fetch()`, whose fallback result
        backfills the ring write-through so the NEXT cold fit of the
        same series is resident.

        Partial admission is PURE-PUSH only: with a fallback
        configured, an uncovered window start must keep degrading to
        the fallback — Prometheus may well hold the full 7-day history
        the ring lost (restart without a snapshot, eviction), and a
        partial fit would silently replace it with the short slice
        forever. A genuinely-new app costs one fallback round trip
        (short real history, backfilled with full-window authority) and
        is resident from then on; only a fleet with NO pull path needs
        the ring's own short-history admission."""
        key, t0, t1, step = self._resolve(url)
        if key is None:
            return None
        now = self._clock() if now is None else now
        status, ts, vs, cov = self.ring.hist_query(
            key, t0, t1, now, step=step,
            admit_floor=(
                self.admit_floor if self.fallback is None else 0.0
            ),
        )
        if status in ("full", "partial"):
            return status, ts, vs, cov, (t0, t1)
        # no book.record here: every unservable hist read falls through
        # to fetch(), which records the subscription (and the fetch
        # counters) for the SAME lookup — recording twice would double
        # the miss counts every fallback-path cold fit
        return None

    def hist_coverage(self, url: str, now: float | None = None):
        """Counter-free coverage probe for one historical URL: (state,
        points_in_window, (cov_from, cov_to), (t0, t1)) — the
        refinement planner's pacing read (no column copies, no LRU
        touch). state None when the ring cannot serve the series."""
        key, t0, t1, step = self._resolve(url)
        if key is None:
            return None
        now = self._clock() if now is None else now
        state, n, cov = self.ring.coverage(key, t0, t1, now, step=step)
        return state, n, cov, (t0, t1)

    def ingest_debug_state(self) -> dict:
        """The worker `/debug/state` `ingest` section (duck-typed hook:
        `BrainWorker.debug_state` includes any source exposing this)."""
        state = self.ring.stats()
        state["subscriptions"] = self.book.snapshot()
        state["fallback"] = type(self.fallback).__name__ if self.fallback else None
        state["admit_floor_seconds"] = self.admit_floor
        return state
