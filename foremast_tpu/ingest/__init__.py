"""Push-based metric ingest plane (ISSUE 5).

The scrape-vs-remote-write inversion: instead of the worker HTTP-GETing
every document's `query_range` URL from Prometheus each tick (the
reference brain's shape, SURVEY §3.2 — and ~half of a cold tick's wall
clock, BENCHMARKS.md round 6), pushers remote-write samples INTO the
worker's resident ring TSDB and a warm fetch becomes an in-memory
columnar gather — the same shape as serving an inference stack from a
resident feature store instead of a remote database.

Modules:
    wire      push payload codecs (JSON compat + FMW1 binary columnar
              frame + pure-python snappy) + query_range key resolution
    ring      per-series pow2 (int64, float32) ring buffers
    shards    sharded, byte-budgeted, LRU-evicting RingStore
    backfill  cold-miss subscriptions + fallback-result backfill
    source    RingSource(MetricSource) — what the worker mounts
    receiver  HTTP push endpoint + foremast_ingest_* exposition
    snapshot  durable shard snapshots + append logs (warm restarts)

Opt-in via `FOREMAST_INGEST=1` (docs/operations.md "Ingest plane");
durability via `FOREMAST_SNAPSHOT_DIR` (docs/operations.md "Restarts
and upgrades").
"""

from foremast_tpu.ingest.backfill import SubscriptionBook, backfill
from foremast_tpu.ingest.receiver import (
    IngestCollector,
    WireStats,
    start_ingest_server,
    stop_ingest_server,
)
from foremast_tpu.ingest.ring import SeriesRing
from foremast_tpu.ingest.shards import RingShard, RingStore
from foremast_tpu.ingest.snapshot import (
    RingSnapshotter,
    SnapshotCollector,
    lock_snapshot_dir,
)
from foremast_tpu.ingest.source import RingSource
from foremast_tpu.ingest.wire import (
    BINARY_CONTENT_TYPE,
    WireError,
    canonical_series,
    decode_frame,
    encode_frame,
    parse_push,
    resolve_query_range,
    series_key,
    snappy_compress,
    snappy_decompress,
)

__all__ = [
    "BINARY_CONTENT_TYPE",
    "IngestCollector",
    "RingShard",
    "RingSnapshotter",
    "RingSource",
    "RingStore",
    "SeriesRing",
    "SnapshotCollector",
    "SubscriptionBook",
    "WireError",
    "WireStats",
    "backfill",
    "canonical_series",
    "decode_frame",
    "encode_frame",
    "lock_snapshot_dir",
    "parse_push",
    "resolve_query_range",
    "series_key",
    "snappy_compress",
    "snappy_decompress",
    "start_ingest_server",
    "stop_ingest_server",
]
