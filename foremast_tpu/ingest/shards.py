"""Sharded, byte-budgeted resident-series store: the ring TSDB proper.

`RingStore` spreads series over N `RingShard`s by key hash (crc32 —
deterministic across processes, unlike Python's randomized `hash`).
Each shard owns one `threading.Lock` guarding its series map, its LRU
order, and its byte account, so the receiver's push threads, the
worker's tick-thread fetches, and the varz scrape handler contend on
1/N of the keyspace instead of one global lock — the same reasoning as
the per-thread Sessions in `PrometheusSource`.

Budget + eviction: `FOREMAST_INGEST_BUDGET_BYTES` divides evenly across
shards; when a push overflows a shard's slice, least-recently-USED
series (queries refresh recency, not just pushes) are dropped whole —
an evicted-but-subscribed series re-warms through the source's
cold-miss fallback on its next fetch, so eviction degrades to the pull
path rather than to wrong answers. A shard never evicts its last
resident series: one series larger than the slice must not thrash.

Staleness: a query whose window reaches `min(end, now)` is only a hit
when the newest resident sample is within `stale_seconds` of it — a
pusher that died must degrade to the pull path, not freeze every
verdict at its last pushed value.
"""

from __future__ import annotations

import os
import threading
import time
import zlib
from collections import OrderedDict

import numpy as np

from foremast_tpu.ingest.ring import (
    DEFAULT_MAX_POINTS,
    SeriesRing,
    empty_series as _empty,
)
from foremast_tpu.ingest.wire import canonical_series

DEFAULT_BUDGET_BYTES = 268_435_456  # 256 MB
DEFAULT_SHARDS = 8
DEFAULT_STALE_SECONDS = 300.0

_COUNT_KEYS = ("hits", "misses", "stale", "uncovered", "samples", "evictions")


class RingShard:
    """One lock's worth of series. All state behind `_lock`; the
    SeriesRing objects inside are only touched while holding it."""

    def __init__(self, budget_bytes: int, max_points: int):
        self.budget_bytes = int(budget_bytes)
        self.max_points = int(max_points)
        self._lock = threading.Lock()
        self._series: OrderedDict[str, SeriesRing] = OrderedDict()
        self._bytes = 0
        self._counts = dict.fromkeys(_COUNT_KEYS, 0)

    def push(
        self,
        key: str,
        times,
        values,
        start: float | None = None,
        end: float | None = None,
        slack: float = 0.0,
        journal=None,
    ) -> int:
        """`journal` (optional, the snapshot plane's append-log hook) is
        invoked INSIDE the lock, after a successful apply: replayed log
        order must equal apply order, or a restore could resurrect the
        stale side of two same-timestamp revisions that raced on the
        receiver's handler threads. The cost is a page-cache write +
        flush inside the lock hold — microseconds, and only when
        durability is mounted."""
        with self._lock:
            ring = self._series.get(key)
            prev = 0
            if ring is None:
                ring = SeriesRing(max_points=self.max_points)
                self._series[key] = ring
            else:
                prev = ring.nbytes
            n = ring.append(times, values, start=start, end=end, slack=slack)
            self._bytes += ring.nbytes - prev
            self._series.move_to_end(key)
            self._counts["samples"] += n
            while self._bytes > self.budget_bytes and len(self._series) > 1:
                _, old = self._series.popitem(last=False)
                self._bytes -= old.nbytes
                self._counts["evictions"] += 1
            if journal is not None and (
                n or start is not None or end is not None
            ):
                # empty backfills still carry an authority claim worth
                # persisting; pure no-op pushes do not. DELIBERATELY
                # under the shard lock (PR-7 replay-order contract, see
                # the docstring above): journaling outside it let two
                # racing same-timestamp revisions restore stale.
                journal(key, times, values, start, end)  # foremast: ignore[blocking-under-lock]
            return n

    def query(
        self,
        key: str,
        t0: float | None,
        t1: float | None,
        now: float,
        step: float,
        stale_seconds: float,
    ) -> tuple[str, np.ndarray, np.ndarray]:
        """(status, times, values); status "hit" | "miss" (not resident)
        | "uncovered" (the window reaches outside the ring's contiguous
        authoritative interval — including the gap between two disjoint
        fetched windows) | "stale" (coverage head too far behind the
        window head: pusher dead or backfill aged out)."""
        with self._lock:
            ring = self._series.get(key)
            if ring is None:
                self._counts["misses"] += 1
                return ("miss",) + _empty()
            self._series.move_to_end(key)  # queries refresh LRU recency
            if ring.covered_from is None or ring.covered_to is None or (
                t0 is not None and ring.covered_from > t0 + step
            ):
                self._counts["uncovered"] += 1
                return ("uncovered",) + _empty()
            head = now if t1 is None else min(t1, now)
            if ring.covered_to < head - stale_seconds or (
                # a window starting past the coverage head has ZERO
                # overlap with what the ring can vouch for — an "empty
                # hit" there would hide samples the pull path has
                t0 is not None
                and ring.covered_to < t0 - step
            ):
                self._counts["stale"] += 1
                return ("stale",) + _empty()
            self._counts["hits"] += 1
            return ("hit",) + ring.window(t0, t1)

    def evict_unowned(self, owns) -> int:
        """Drop every resident series the predicate disowns — the mesh
        rebalance hook (a healed ring moved these keys to another
        member; keeping their columns would spend this worker's budget
        on series it will never be asked for again)."""
        with self._lock:
            doomed = [k for k in self._series if not owns(k)]
            for k in doomed:
                old = self._series.pop(k)
                self._bytes -= old.nbytes
                self._counts["evictions"] += 1
            return len(doomed)

    def snapshot_state(self) -> list[tuple]:
        """Consistent copy of every resident series for the snapshot
        writer: (key, times, values, covered_from, covered_to), columns
        copied under the shard lock so a concurrent push can never
        interleave half a mutation into the on-disk state."""
        with self._lock:
            out = []
            for key, ring in self._series.items():
                t, v = ring.window(None, None)  # ordered copies
                out.append(
                    (key, t, v, ring.covered_from, ring.covered_to)
                )
            return out

    def stats(self) -> dict:
        with self._lock:
            return {
                "series": len(self._series),
                "bytes": self._bytes,
                **self._counts,
            }


class RingStore:
    """The sharded ring TSDB: push/query/stats over canonical series
    keys (`wire.canonical_series` — push and query sides agree on label
    order by construction)."""

    def __init__(
        self,
        budget_bytes: int = DEFAULT_BUDGET_BYTES,
        shards: int = DEFAULT_SHARDS,
        stale_seconds: float = DEFAULT_STALE_SECONDS,
        max_points: int = DEFAULT_MAX_POINTS,
    ):
        shards = max(1, int(shards))
        self.budget_bytes = int(budget_bytes)
        self.stale_seconds = float(stale_seconds)
        self.max_points = int(max_points)
        self._shards = tuple(
            RingShard(max(self.budget_bytes // shards, 1), self.max_points)
            for _ in range(shards)
        )
        self._lock = threading.Lock()
        self._lag = {"receiver_lag_seconds": None, "last_push_at": None}
        # optional push write-through hook (ingest.snapshot attaches the
        # append-log writer here): called AFTER a successful apply with
        # (shard_index, canonical key, times, values, start, end),
        # UNDER the owning shard's lock so replay order equals apply
        # order (see RingShard.push).
        self.journal = None

    @staticmethod
    def from_env(env=None) -> "RingStore":
        e = os.environ if env is None else env
        return RingStore(
            budget_bytes=int(
                e.get("FOREMAST_INGEST_BUDGET_BYTES", "")
                or DEFAULT_BUDGET_BYTES
            ),
            shards=int(e.get("FOREMAST_INGEST_SHARDS", "") or DEFAULT_SHARDS),
            stale_seconds=float(
                e.get("FOREMAST_INGEST_STALE_SECONDS", "")
                or DEFAULT_STALE_SECONDS
            ),
            max_points=int(
                e.get("FOREMAST_INGEST_MAX_POINTS", "") or DEFAULT_MAX_POINTS
            ),
        )

    def _shard_index(self, key: str) -> int:
        """THE key→shard mapping — the journal hook pairs snapshot
        files with logs by this index, so there must be exactly one
        definition of it."""
        return zlib.crc32(key.encode()) % len(self._shards)

    def _shard(self, key: str) -> RingShard:
        return self._shards[self._shard_index(key)]

    @property
    def shard_count(self) -> int:
        return len(self._shards)

    def shard_state(self, index: int) -> list[tuple]:
        """One shard's resident series as consistent column copies —
        the snapshot writer's read path (ingest/snapshot.py)."""
        return self._shards[index].snapshot_state()

    def push(
        self,
        alias: str,
        times,
        values,
        start: float | None = None,
        end: float | None = None,
        now: float | None = None,
        record_lag: bool = True,
    ) -> int:
        """Direct push API (the receiver, backfill, and tests all land
        here). `alias` is the series identity — a bare selector in any
        label order, or an opaque expression string. `start`/`end`
        bound the batch's authoritative window (backfill); plain pushes
        cover their own sample span, with gaps up to the staleness
        cutoff treated as contiguous. `record_lag=False` keeps a
        backfill of old history from reporting as receiver lag."""
        key = canonical_series(alias)
        idx = self._shard_index(key)
        journal = self.journal
        n = self._shards[idx].push(
            key, times, values, start=start, end=end,
            slack=self.stale_seconds,
            journal=(
                None
                if journal is None
                else lambda k, t, v, s, e: journal(idx, k, t, v, s, e)
            ),
        )
        if n and record_lag:
            now = time.time() if now is None else now
            newest = float(np.max(np.asarray(times, np.int64)))
            with self._lock:
                self._lag["receiver_lag_seconds"] = max(0.0, now - newest)
                self._lag["last_push_at"] = now
        return n

    def query(
        self,
        key: str,
        t0: float | None,
        t1: float | None,
        now: float,
        step: float = 60.0,
    ) -> tuple[str, np.ndarray, np.ndarray]:
        return self._shard(key).query(
            key, t0, t1, now, step, self.stale_seconds
        )

    def evict_unowned(self, owns) -> int:
        """Drop resident series `owns(key)` rejects (mesh rebalance);
        returns how many were evicted across all shards."""
        return sum(s.evict_unowned(owns) for s in self._shards)

    def stats(self) -> dict:
        out = {"series": 0, "bytes": 0}
        out.update(dict.fromkeys(_COUNT_KEYS, 0))
        for shard in self._shards:
            for k, v in shard.stats().items():
                out[k] += v
        out["shards"] = len(self._shards)
        out["budget_bytes"] = self.budget_bytes
        looked = (
            out["hits"] + out["misses"] + out["stale"] + out["uncovered"]
        )
        out["hit_ratio"] = round(out["hits"] / looked, 4) if looked else None
        with self._lock:
            out.update(self._lag)
        return out
