"""Sharded, byte-budgeted resident-series store: the ring TSDB proper.

`RingStore` spreads series over N `RingShard`s by key hash (crc32 —
deterministic across processes, unlike Python's randomized `hash`).
Each shard owns one `threading.Lock` guarding its series map, its LRU
order, and its byte account, so the receiver's push threads, the
worker's tick-thread fetches, and the varz scrape handler contend on
1/N of the keyspace instead of one global lock — the same reasoning as
the per-thread Sessions in `PrometheusSource`.

Budget + eviction: `FOREMAST_INGEST_BUDGET_BYTES` divides evenly across
shards; when a push overflows a shard's slice, least-recently-USED
series (queries refresh recency, not just pushes) are dropped whole —
an evicted-but-subscribed series re-warms through the source's
cold-miss fallback on its next fetch, so eviction degrades to the pull
path rather than to wrong answers. A shard never evicts its last
resident series: one series larger than the slice must not thrash.

Staleness: a query whose window reaches `min(end, now)` is only a hit
when the newest resident sample is within `stale_seconds` of it — a
pusher that died must degrade to the pull path, not freeze every
verdict at its last pushed value.
"""

from __future__ import annotations

import os
import threading
import time
import zlib
from collections import OrderedDict

import numpy as np

from foremast_tpu.ingest.ring import (
    DEFAULT_MAX_POINTS,
    SeriesRing,
    empty_series as _empty,
)
from foremast_tpu.ingest.wire import canonical_series

DEFAULT_BUDGET_BYTES = 268_435_456  # 256 MB
DEFAULT_SHARDS = 8
DEFAULT_STALE_SECONDS = 300.0

_COUNT_KEYS = (
    "hits", "partial", "misses", "stale", "uncovered", "samples",
    "evictions",
)


class _RingTenancy:
    """One store's tenant view (ISSUE 20): the registry resolves a
    pushed key's tenant (OUTSIDE any shard lock — the registry's cache
    lock is a peer leaf), per-tenant ring-bytes envelopes divide evenly
    across shards exactly like the global budget, and residency deltas
    + eviction charges flush into the shared `TenantAccounting` ledger
    AFTER the shard lock releases."""

    def __init__(self, registry, shards: int):
        from foremast_tpu.tenant.accounting import accounting_for

        self.registry = registry
        self.accounting = accounting_for(registry)
        self.shards = max(1, int(shards))

    def tenant_of(self, key: str) -> str:
        return self.registry.tenant_of_series(key)

    def shard_envelope(self, tenant: str) -> int:
        """The tenant's per-shard byte envelope slice; 0 = no envelope.
        Lock-free (spec lookup reads an immutable dict), so it is safe
        under a shard lock."""
        rb = self.registry.spec(tenant).ring_bytes
        return max(rb // self.shards, 1) if rb > 0 else 0

    def flush(self, byte_deltas: dict, evictions: dict) -> None:
        for tenant, delta in byte_deltas.items():
            self.accounting.add_ring_bytes(tenant, delta)
        for tenant, n in evictions.items():
            self.accounting.count_eviction(tenant, n)


def _serving_span(ring, t0, t1, now, step, stale_seconds):
    """THE serve rule, shared by query/hist_query/coverage (one
    definition or the refinement planner's view of servability drifts
    from what the read paths actually serve): the best span covering
    the window start, IF it is fresh enough for the window head —
    (span, head, covering) where span is None when nothing serves and
    covering is the raw covering interval regardless (so callers can
    split uncovered from stale without re-walking the span list).
    Caller holds the shard lock."""
    iv = ring.covering(t0, step)
    head = now if t1 is None else min(t1, now)
    if iv is not None and iv[1] >= head - stale_seconds and not (
        # a window starting past the span head has ZERO overlap with
        # what the ring can vouch for — an "empty hit" there would
        # hide samples the pull path has
        t0 is not None
        and iv[1] < t0 - step
    ):
        return iv, head, iv
    return None, head, iv


class RingShard:
    """One lock's worth of series. All state behind `_lock`; the
    SeriesRing objects inside are only touched while holding it."""

    def __init__(self, budget_bytes: int, max_points: int, tenancy=None):
        self.budget_bytes = int(budget_bytes)
        self.max_points = int(max_points)
        self._lock = threading.Lock()
        self._series: OrderedDict[str, SeriesRing] = OrderedDict()
        self._bytes = 0
        self._counts = dict.fromkeys(_COUNT_KEYS, 0)
        # tenant QoS view (ISSUE 20); None = untenanted, every path
        # below keeps its zero-cost check
        self._tenancy = tenancy
        self._t_bytes: dict[str, int] = {}  # tenant -> resident bytes
        self._t_of: dict[str, str] = {}  # resident key -> tenant

    def push(
        self,
        key: str,
        times,
        values,
        start: float | None = None,
        end: float | None = None,
        slack: float = 0.0,
        journal=None,
    ) -> int:
        """`journal` (optional, the snapshot plane's append-log hook) is
        invoked INSIDE the lock, after a successful apply: replayed log
        order must equal apply order, or a restore could resurrect the
        stale side of two same-timestamp revisions that raced on the
        receiver's handler threads. The cost is a page-cache write +
        flush inside the lock hold — microseconds, and only when
        durability is mounted."""
        return self.push_many(
            [(key, times, values, start, end)], slack=slack, journal=journal
        )[0]

    def push_many(
        self,
        items: list[tuple[str, np.ndarray, np.ndarray, float | None, float | None]],
        slack: float = 0.0,
        journal=None,
    ) -> list[int]:
        """Striped batch append (ISSUE 18): apply every ``(key, times,
        values, start, end)`` in ``items`` under ONE lock acquisition
        instead of one per series — the wire decode path groups a whole
        frame by shard and lands each group in a single hold (`push` is
        the one-item case). Returns per-item accepted counts, aligned
        with ``items``. Budget eviction runs per item (identical
        semantics to N push() calls); the journal hook fires per item
        IN APPLY ORDER, still under the lock (the PR-7 replay-order
        contract is per-apply, not per-acquisition)."""
        out = []
        ten = self._tenancy
        # tenant resolution OUTSIDE the shard lock (the registry's
        # cache lock is a peer leaf, never nested under a shard's);
        # residency deltas + eviction charges accumulate here and
        # flush into the shared ledger after the lock releases
        tenants = (
            [ten.tenant_of(key) for key, _, _, _, _ in items]
            if ten is not None
            else None
        )
        byte_deltas: dict[str, int] = {}
        evict_charges: dict[str, int] = {}
        with self._lock:
            for j, (key, times, values, start, end) in enumerate(items):
                ring = self._series.get(key)
                prev = 0
                if ring is None:
                    ring = SeriesRing(max_points=self.max_points)
                    self._series[key] = ring
                else:
                    prev = ring.nbytes
                n = ring.append(
                    times, values, start=start, end=end, slack=slack
                )
                self._bytes += ring.nbytes - prev
                self._series.move_to_end(key)
                self._counts["samples"] += n
                if ten is not None:
                    t = tenants[j]
                    self._t_of[key] = t
                    delta = ring.nbytes - prev
                    if delta:
                        self._t_bytes[t] = self._t_bytes.get(t, 0) + delta
                        byte_deltas[t] = byte_deltas.get(t, 0) + delta
                    # per-tenant envelope (ISSUE 20): a tenant past its
                    # ring-bytes slice loses its OWN least-recently-used
                    # series first, charged to itself — never another
                    # tenant's residency, and never the series just
                    # pushed (one series larger than the envelope must
                    # not thrash, same rule as the global budget)
                    env = ten.shard_envelope(t)
                    if env:
                        self._evict_tenant(
                            t, env, key, byte_deltas, evict_charges
                        )
                while (
                    self._bytes > self.budget_bytes and len(self._series) > 1
                ):
                    if ten is not None:
                        # global overflow: prefer an over-envelope
                        # tenant's series, charge the pusher causing
                        # the pressure
                        self._evict_global(
                            tenants[j], byte_deltas, evict_charges
                        )
                    else:
                        _, old = self._series.popitem(last=False)
                        self._bytes -= old.nbytes
                        self._counts["evictions"] += 1
                if journal is not None and (
                    n or start is not None or end is not None
                ):
                    # empty backfills still carry an authority claim
                    # worth persisting; pure no-op pushes do not.
                    # DELIBERATELY under the shard lock (PR-7
                    # replay-order contract, see the docstring above):
                    # journaling outside it let two racing
                    # same-timestamp revisions restore stale.
                    journal(key, times, values, start, end)  # foremast: ignore[blocking-under-lock]
                out.append(n)
        if ten is not None and (byte_deltas or evict_charges):
            ten.flush(byte_deltas, evict_charges)
        return out

    # -- tenant-aware eviction (ISSUE 20) -------------------------------
    # These helpers run ONLY from put()'s `with self._lock:` block —
    # the lock is not reentrant, so they cannot retake it. Each guarded
    # access carries the lock-discipline suppression; the contract is
    # the single call site, not a lock-free fast path.

    def _pop_series(self, key: str, byte_deltas: dict) -> None:
        # foremast: ignore[lock-discipline] — caller (put) holds _lock
        old = self._series.pop(key)
        # foremast: ignore[lock-discipline] — caller (put) holds _lock
        self._bytes -= old.nbytes
        # foremast: ignore[lock-discipline] — caller (put) holds _lock
        self._counts["evictions"] += 1
        # foremast: ignore[lock-discipline] — caller (put) holds _lock
        t = self._t_of.pop(key, None)
        if t is not None:
            # foremast: ignore[lock-discipline] — caller holds _lock
            left = self._t_bytes.get(t, 0) - old.nbytes
            if left > 0:
                # foremast: ignore[lock-discipline] — caller holds _lock
                self._t_bytes[t] = left
            else:
                # foremast: ignore[lock-discipline] — caller holds _lock
                self._t_bytes.pop(t, None)
            byte_deltas[t] = byte_deltas.get(t, 0) - old.nbytes

    def _evict_tenant(
        self,
        tenant: str,
        envelope: int,
        pushed_key: str,
        byte_deltas: dict,
        evict_charges: dict,
    ) -> None:
        while (
            # foremast: ignore[lock-discipline] — caller holds _lock
            self._t_bytes.get(tenant, 0) > envelope
            # foremast: ignore[lock-discipline] — caller holds _lock
            and len(self._series) > 1
        ):
            victim = next(
                (
                    k
                    # foremast: ignore[lock-discipline] — caller holds _lock
                    for k in self._series
                    # foremast: ignore[lock-discipline] — caller holds _lock
                    if k != pushed_key and self._t_of.get(k) == tenant
                ),
                None,
            )
            if victim is None:
                break
            self._pop_series(victim, byte_deltas)
            evict_charges[tenant] = evict_charges.get(tenant, 0) + 1

    def _evict_global(
        self, causer: str, byte_deltas: dict, evict_charges: dict
    ) -> None:
        victim = None
        # foremast: ignore[lock-discipline] — caller (put) holds _lock
        for k in self._series:
            # foremast: ignore[lock-discipline] — caller holds _lock
            t = self._t_of.get(k)
            if t is None:
                continue
            env = self._tenancy.shard_envelope(t)
            # foremast: ignore[lock-discipline] — caller holds _lock
            if env and self._t_bytes.get(t, 0) > env:
                victim = k
                break
        if victim is None:
            # foremast: ignore[lock-discipline] — caller holds _lock
            victim = next(iter(self._series))
        self._pop_series(victim, byte_deltas)
        evict_charges[causer] = evict_charges.get(causer, 0) + 1

    def query(
        self,
        key: str,
        t0: float | None,
        t1: float | None,
        now: float,
        step: float,
        stale_seconds: float,
    ) -> tuple[str, np.ndarray, np.ndarray]:
        """(status, times, values); status "hit" | "miss" (not resident)
        | "uncovered" (no single authoritative span reaches the window
        start — including the gap between two disjoint fetched windows)
        | "stale" (the serving span's head too far behind the window
        head: pusher dead or backfill aged out). A window is only ever
        served out of ONE coverage span (ring.SeriesRing.covering), so
        disjoint backfills never imply the gap between them was empty."""
        with self._lock:
            ring = self._series.get(key)
            if ring is None:
                self._counts["misses"] += 1
                return ("miss",) + _empty()
            self._series.move_to_end(key)  # queries refresh LRU recency
            iv, _head, cov = _serving_span(
                ring, t0, t1, now, step, stale_seconds
            )
            if iv is not None:
                self._counts["hits"] += 1
                return ("hit",) + ring.window(t0, t1)
            if cov is None:
                self._counts["uncovered"] += 1
                return ("uncovered",) + _empty()
            self._counts["stale"] += 1
            return ("stale",) + _empty()

    def hist_query(
        self,
        key: str,
        t0: float | None,
        t1: float | None,
        now: float,
        step: float,
        stale_seconds: float,
        admit_floor: float,
    ) -> tuple:
        """Historical-range read with short-history admission (ISSUE 10
        tentpole): (status, times, values, (cov_from, cov_to) | None).

        "full" is exactly `query`'s hit — one span covers the window.
        "partial" serves the LIVE span's slice of the window when the
        span cannot reach back to `t0` but holds at least `admit_floor`
        seconds of fresh coverage: a newcomer's 1-2 pushed days become
        a verdict-capable short-history fit instead of a miss that the
        fallback (which has no more data for a true newcomer either)
        or pure-push UNKNOWN would be. The partial slice is clamped to
        the span — never a silently truncated view of a covered range.

        Only SERVED outcomes (full/partial) bump the fetch counters:
        every unservable hist read falls straight through to `fetch()`,
        which counts the same lookup — counting here too would double
        every fallback-path miss in foremast_ingest_fetches and the
        hit_ratio denominator."""
        with self._lock:
            ring = self._series.get(key)
            if ring is None:
                return ("miss",) + _empty() + (None,)
            self._series.move_to_end(key)
            iv, head, cov = _serving_span(
                ring, t0, t1, now, step, stale_seconds
            )
            if iv is not None:
                self._counts["hits"] += 1
                return ("full",) + ring.window(t0, t1) + (iv,)
            hd = ring.head_interval
            if (
                admit_floor > 0
                and hd is not None
                and hd[1] >= head - stale_seconds
                and (t0 is None or hd[0] > t0 + step)
                and min(head, hd[1]) - hd[0] >= admit_floor
            ):
                self._counts["partial"] += 1
                return ("partial",) + ring.window(hd[0], t1) + (hd,)
            if cov is None:
                return ("uncovered",) + _empty() + (None,)
            return ("stale",) + _empty() + (None,)

    def coverage(
        self,
        key: str,
        t0: float | None,
        t1: float | None,
        now: float,
        step: float,
        stale_seconds: float,
    ) -> tuple:
        """(state, points_in_window, (cov_from, cov_to)) without column
        copies and without touching LRU order or the serve counters —
        the refinement planner's pacing probe (worker._refine_provisional
        runs it per provisional fit per idle tick). state "full" |
        "partial" (live span short of t0) | None (not resident / dead
        pusher)."""
        with self._lock:
            ring = self._series.get(key)
            if ring is None:
                return None, 0, None
            iv, head, _cov = _serving_span(
                ring, t0, t1, now, step, stale_seconds
            )
            if iv is not None:
                return "full", ring.count_window(t0, t1), iv
            hd = ring.head_interval
            if hd is not None and hd[1] >= head - stale_seconds:
                lo = hd[0] if t0 is None else max(t0, hd[0])
                return "partial", ring.count_window(lo, t1), hd
            return None, 0, None

    def evict_unowned(self, owns) -> int:
        """Drop every resident series the predicate disowns — the mesh
        rebalance hook (a healed ring moved these keys to another
        member; keeping their columns would spend this worker's budget
        on series it will never be asked for again)."""
        byte_deltas: dict[str, int] = {}
        with self._lock:
            doomed = [k for k in self._series if not owns(k)]
            for k in doomed:
                if self._tenancy is not None:
                    # residency moves, but rebalance is nobody's QoS
                    # pressure: no tenant eviction charge
                    self._pop_series(k, byte_deltas)
                else:
                    old = self._series.pop(k)
                    self._bytes -= old.nbytes
                    self._counts["evictions"] += 1
        if self._tenancy is not None and byte_deltas:
            self._tenancy.flush(byte_deltas, {})
        return len(doomed)

    def snapshot_state(self) -> list[tuple]:
        """Consistent copy of every resident series for the snapshot
        writer: (key, times, values, covered_from, covered_to, extras)
        — the head coverage span plus any OLDER disjoint spans (a
        restored ring must keep serving historical backfills, ISSUE 10
        satellite). Columns copied under the shard lock so a concurrent
        push can never interleave half a mutation into the on-disk
        state."""
        with self._lock:
            out = []
            for key, ring in self._series.items():
                t, v = ring.window(None, None)  # ordered copies
                ivs = ring.intervals()
                out.append(
                    (
                        key,
                        t,
                        v,
                        ring.covered_from,
                        ring.covered_to,
                        ivs[:-1],  # all but the head span
                    )
                )
            return out

    def stats(self) -> dict:
        with self._lock:
            return {
                "series": len(self._series),
                "bytes": self._bytes,
                **self._counts,
            }


class RingStore:
    """The sharded ring TSDB: push/query/stats over canonical series
    keys (`wire.canonical_series` — push and query sides agree on label
    order by construction)."""

    def __init__(
        self,
        budget_bytes: int = DEFAULT_BUDGET_BYTES,
        shards: int = DEFAULT_SHARDS,
        stale_seconds: float = DEFAULT_STALE_SECONDS,
        max_points: int = DEFAULT_MAX_POINTS,
        tenancy=None,
    ):
        shards = max(1, int(shards))
        self.budget_bytes = int(budget_bytes)
        self.stale_seconds = float(stale_seconds)
        self.max_points = int(max_points)
        # tenant QoS plane (ISSUE 20): a TenantRegistry activates
        # per-tenant ring-bytes envelopes + eviction attribution; None
        # keeps the untenanted eviction loop byte-identical
        self.tenancy = (
            _RingTenancy(tenancy, shards) if tenancy is not None else None
        )
        self._shards = tuple(
            RingShard(
                max(self.budget_bytes // shards, 1),
                self.max_points,
                tenancy=self.tenancy,
            )
            for _ in range(shards)
        )
        self._lock = threading.Lock()
        self._lag = {"receiver_lag_seconds": None, "last_push_at": None}
        # optional push write-through hook (ingest.snapshot attaches the
        # append-log writer here): called AFTER a successful apply with
        # (shard_index, canonical key, times, values, start, end),
        # UNDER the owning shard's lock so replay order equals apply
        # order (see RingShard.push).
        self.journal = None

    @staticmethod
    def from_env(env=None) -> "RingStore":
        from foremast_tpu.tenant.registry import get_tenancy

        e = os.environ if env is None else env
        return RingStore(
            budget_bytes=int(
                e.get("FOREMAST_INGEST_BUDGET_BYTES", "")
                or DEFAULT_BUDGET_BYTES
            ),
            shards=int(e.get("FOREMAST_INGEST_SHARDS", "") or DEFAULT_SHARDS),
            stale_seconds=float(
                e.get("FOREMAST_INGEST_STALE_SECONDS", "")
                or DEFAULT_STALE_SECONDS
            ),
            max_points=int(
                e.get("FOREMAST_INGEST_MAX_POINTS", "") or DEFAULT_MAX_POINTS
            ),
            tenancy=get_tenancy(),
        )

    def _shard_index(self, key: str) -> int:
        """THE key→shard mapping — the journal hook pairs snapshot
        files with logs by this index, so there must be exactly one
        definition of it."""
        return zlib.crc32(key.encode()) % len(self._shards)

    def _shard(self, key: str) -> RingShard:
        return self._shards[self._shard_index(key)]

    @property
    def shard_count(self) -> int:
        return len(self._shards)

    def shard_state(self, index: int) -> list[tuple]:
        """One shard's resident series as consistent column copies —
        the snapshot writer's read path (ingest/snapshot.py)."""
        return self._shards[index].snapshot_state()

    def push(
        self,
        alias: str,
        times,
        values,
        start: float | None = None,
        end: float | None = None,
        now: float | None = None,
        record_lag: bool = True,
    ) -> int:
        """Direct push API (the receiver, backfill, and tests all land
        here). `alias` is the series identity — a bare selector in any
        label order, or an opaque expression string. `start`/`end`
        bound the batch's authoritative window (backfill); plain pushes
        cover their own sample span, with gaps up to the staleness
        cutoff treated as contiguous. `record_lag=False` keeps a
        backfill of old history from reporting as receiver lag."""
        key = canonical_series(alias)
        idx = self._shard_index(key)
        journal = self.journal
        n = self._shards[idx].push(
            key, times, values, start=start, end=end,
            slack=self.stale_seconds,
            journal=(
                None
                if journal is None
                else lambda k, t, v, s, e: journal(idx, k, t, v, s, e)
            ),
        )
        if n and record_lag:
            now = time.time() if now is None else now
            newest = float(np.max(np.asarray(times, np.int64)))
            with self._lock:
                self._lag["receiver_lag_seconds"] = max(0.0, now - newest)
                self._lag["last_push_at"] = now
        return n

    def push_batch(
        self,
        entries: list[tuple[str, np.ndarray, np.ndarray, float | None]],
        now: float | None = None,
        record_lag: bool = True,
        canonical: bool = False,
    ) -> list[int]:
        """Batch push for decoded wire payloads: ``(key, times, values,
        start)`` tuples (exactly what ``wire.parse_push`` and
        ``wire.decode_frame`` return) are grouped by shard and applied
        with ONE lock acquisition per touched shard (`RingShard.
        push_many`) — a 4k-series frame takes ~`shards` acquisitions
        instead of 4k. Returns per-entry accepted counts aligned with
        ``entries``. ``canonical=True`` skips `canonical_series` (the
        binary codec's keys are canonical by contract; the JSON path
        passes False). One lag sample is recorded for the whole batch."""
        journal = self.journal
        keys = (
            [k for k, _, _, _ in entries]
            if canonical
            else [canonical_series(k) for k, _, _, _ in entries]
        )
        by_shard: dict[int, list[int]] = {}
        for i, key in enumerate(keys):
            by_shard.setdefault(self._shard_index(key), []).append(i)
        counts = [0] * len(entries)
        newest = None
        for idx in sorted(by_shard):
            order = by_shard[idx]
            got = self._shards[idx].push_many(
                [
                    (keys[i], entries[i][1], entries[i][2], entries[i][3], None)
                    for i in order
                ],
                slack=self.stale_seconds,
                journal=(
                    None
                    if journal is None
                    else lambda k, t, v, s, e, _i=idx: journal(_i, k, t, v, s, e)
                ),
            )
            for i, n in zip(order, got):
                counts[i] = n
                if n:
                    m = float(np.max(np.asarray(entries[i][1], np.int64)))
                    newest = m if newest is None else max(newest, m)
        if newest is not None and record_lag:
            now = time.time() if now is None else now
            with self._lock:
                self._lag["receiver_lag_seconds"] = max(0.0, now - newest)
                self._lag["last_push_at"] = now
        return counts

    def query(
        self,
        key: str,
        t0: float | None,
        t1: float | None,
        now: float,
        step: float = 60.0,
    ) -> tuple[str, np.ndarray, np.ndarray]:
        return self._shard(key).query(
            key, t0, t1, now, step, self.stale_seconds
        )

    def hist_query(
        self,
        key: str,
        t0: float | None,
        t1: float | None,
        now: float,
        step: float = 60.0,
        admit_floor: float = 0.0,
    ) -> tuple:
        """Historical-range read with short-history admission — see
        `RingShard.hist_query`."""
        return self._shard(key).hist_query(
            key, t0, t1, now, step, self.stale_seconds, admit_floor
        )

    def coverage(
        self,
        key: str,
        t0: float | None,
        t1: float | None,
        now: float,
        step: float = 60.0,
    ) -> tuple:
        """Counter-free coverage probe — see `RingShard.coverage`."""
        return self._shard(key).coverage(
            key, t0, t1, now, step, self.stale_seconds
        )

    def evict_unowned(self, owns) -> int:
        """Drop resident series `owns(key)` rejects (mesh rebalance);
        returns how many were evicted across all shards."""
        return sum(s.evict_unowned(owns) for s in self._shards)

    def stats(self) -> dict:
        out = {"series": 0, "bytes": 0}
        out.update(dict.fromkeys(_COUNT_KEYS, 0))
        for shard in self._shards:
            for k, v in shard.stats().items():
                out[k] += v
        out["shards"] = len(self._shards)
        out["budget_bytes"] = self.budget_bytes
        looked = (
            out["hits"] + out["partial"] + out["misses"] + out["stale"]
            + out["uncovered"]
        )
        out["hit_ratio"] = round(out["hits"] / looked, 4) if looked else None
        with self._lock:
            out.update(self._lag)
        return out
