"""`python -m foremast_tpu.demo` — run the instrumented demo workload."""

from foremast_tpu.demo.app import main

if __name__ == "__main__":
    main()
