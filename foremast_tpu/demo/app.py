"""Instrumented demo app + error generators.

Parity with `examples/spring-boot-demo/`:

* ``/error5xx`` throws -> 500 (`controller/QueueController.java:29-32`);
  ``/error4xx`` -> 404; ``/`` -> 200.
* ``ErrorGenerator`` issues error requests at a fixed rate (the
  ``-DerrorType=5xx -Dfrequency=6`` fault injector,
  `error/ErrorGenerator.java:19-28`).
* ``FileErrorGenerator`` replays a CSV trace of per-minute error rates
  (`error/FileErrorGenerator.java:27-37` with the data1/data2 traces) —
  the deterministic canary workload behind the golden-trace tests.

Generators drive the WSGI app in-process through ``DemoClient`` (no
sockets needed); `python -m foremast_tpu.demo` serves it for a live
cluster demo.
"""

from __future__ import annotations

import io
import threading
import time
from typing import Callable

from foremast_tpu.instrument import HttpMetrics, K8sMetricsConfig, wsgi_middleware


def make_demo_app(metrics: HttpMetrics | None = None):
    """(wsgi_app, metrics): routes /, /error4xx, /error5xx, plus the
    starter's /metrics, /actuator/prometheus, /k8s-metrics/* endpoints."""
    metrics = metrics or HttpMetrics(
        K8sMetricsConfig(
            common_tags={"app": "spring-boot-demo"},
            initialize_for_statuses=(404, 500),
        )
    )

    def app(environ, start_response):
        path = environ.get("PATH_INFO", "/")
        if path == "/error5xx":
            # the reference endpoint throws; WSGI surfaces it as a 500
            start_response("500 Internal Server Error", [("Content-Type", "text/plain")])
            return [b"boom"]
        if path == "/error4xx":
            start_response("404 Not Found", [("Content-Type", "text/plain")])
            return [b"nope"]
        start_response("200 OK", [("Content-Type", "text/plain")])
        return [b"ok"]

    return wsgi_middleware(app, metrics), metrics


class DemoClient:
    """Minimal in-process WSGI client (request-driver for the generators)."""

    def __init__(self, wsgi_app: Callable) -> None:
        self.app = wsgi_app

    def get(self, path: str, headers: dict[str, str] | None = None) -> tuple[int, bytes]:
        environ = {
            "REQUEST_METHOD": "GET",
            "PATH_INFO": path,
            "SERVER_NAME": "demo",
            "SERVER_PORT": "80",
            "wsgi.input": io.BytesIO(),
            "wsgi.errors": io.StringIO(),
            "wsgi.url_scheme": "http",
        }
        for k, v in (headers or {}).items():
            environ["HTTP_" + k.upper().replace("-", "_")] = v
        captured: dict[str, str] = {}

        def start_response(status, _headers, exc_info=None):
            captured["status"] = status

        body = b"".join(self.app(environ, start_response))
        return int(captured["status"].split(" ", 1)[0]), body


class ErrorGenerator:
    """Fixed-rate fault injector (`ErrorGenerator.java:19-28`):
    ``frequency`` error requests per second of ``error_type`` 4xx|5xx."""

    def __init__(
        self,
        client: DemoClient,
        error_type: str = "5xx",
        frequency: float = 6.0,
        sleep: Callable[[float], None] = time.sleep,
    ) -> None:
        self.client = client
        self.path = "/error5xx" if error_type == "5xx" else "/error4xx"
        self.frequency = frequency
        self.sleep = sleep
        self._stop = threading.Event()

    def run_for(self, seconds: float) -> int:
        """Blocking run; returns the number of requests issued."""
        n = 0
        deadline = time.monotonic() + seconds
        period = 1.0 / self.frequency if self.frequency > 0 else seconds
        while time.monotonic() < deadline and not self._stop.is_set():
            self.client.get(self.path)
            n += 1
            self.sleep(period)
        return n

    def burst(self, count: int) -> None:
        """Issue `count` error requests immediately (test-friendly)."""
        for _ in range(count):
            self.client.get(self.path)

    def stop(self) -> None:
        self._stop.set()


class FileErrorGenerator:
    """CSV-trace replayer (`FileErrorGenerator.java:27-37`).

    Each trace line ``YYYY-MM-DD HH:MM:SS,rate`` maps to one replay step
    issuing ``round(rate)`` error requests — the per-minute error counts
    that produce the data1/data2 canary shapes in Prometheus.
    """

    def __init__(
        self, client: DemoClient, path: str, error_type: str = "5xx"
    ) -> None:
        self.gen = ErrorGenerator(client, error_type=error_type, frequency=0)
        self.path = path

    def rates(self) -> list[float]:
        out = []
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if line:
                    out.append(float(line.split(",")[1]))
        return out

    def replay(self, step_sleep: Callable[[float], None] | None = None) -> int:
        """Replay the whole trace; returns total requests issued."""
        total = 0
        for rate in self.rates():
            n = round(rate)
            self.gen.burst(n)
            total += n
            if step_sleep:
                step_sleep(60.0)
        return total


def main(argv: list[str] | None = None) -> None:  # pragma: no cover - manual entry
    """Serve the demo app, optionally with a fault injector.

    Flag parity with the reference demo's JVM flags
    (`examples/demo/*/demo_v2.yaml`): `-DerrorType=5xx -Dfrequency=6`
    becomes `--error-type 5xx --frequency 6`; `-Dfilename=data2.txt`
    becomes `--trace <csv>`.
    """
    import argparse
    from wsgiref.simple_server import make_server

    ap = argparse.ArgumentParser(description="instrumented demo workload")
    ap.add_argument("--port", type=int, default=8080)
    ap.add_argument(
        "--error-type", choices=["none", "4xx", "5xx"], default="none",
        help="background error generator target",
    )
    ap.add_argument(
        "--frequency", type=float, default=6.0, help="error requests/sec"
    )
    ap.add_argument(
        "--trace", default=None,
        help="CSV trace to replay instead of the fixed-rate generator",
    )
    args = ap.parse_args(argv)

    app, _metrics = make_demo_app()
    if args.trace:
        gen = FileErrorGenerator(DemoClient(app), args.trace)
        threading.Thread(target=gen.replay, daemon=True).start()
    elif args.error_type != "none":
        gen = ErrorGenerator(
            DemoClient(app),
            error_type=args.error_type,
            frequency=args.frequency,
        )
        threading.Thread(
            target=gen.run_for, args=(float("inf"),), daemon=True
        ).start()
    print(f"demo app on :{args.port} (/, /error4xx, /error5xx, /metrics)")
    make_server("0.0.0.0", args.port, app).serve_forever()


if __name__ == "__main__":  # pragma: no cover
    main()
