"""Demo workload — the instrumented example app + fault injectors.

Python equivalent of `examples/spring-boot-demo/` (SURVEY.md section 2.6):
a small WSGI app wired through the instrumentation starter, with an error
endpoint, a rate-based error generator, and a CSV-trace replayer — the
fault injectors that drive the end-to-end demo/runbook.
"""

from foremast_tpu.demo.app import (
    DemoClient,
    ErrorGenerator,
    FileErrorGenerator,
    make_demo_app,
)

__all__ = ["DemoClient", "ErrorGenerator", "FileErrorGenerator", "make_demo_app"]
