"""Prometheus gauge export — the brain's signature observability feature.

The reference brain re-publishes its model outputs as first-class
Prometheus series scraped from :8000/metrics
(`deploy/foremast/3_brain/foremast-brain.yaml:87-122`):
`foremastbrain:<metric>_upper`, `_lower`, `_anomaly` with
`exported_namespace`/`app` labels (`foremast-browser/src/config/metrics.js:15-23`)
— model internals become dashboards and alert-rule inputs
(`types.go:190-191`). Same here, via prometheus_client.

Gauge naming contract (`metrics.js:15-23`): the gauge is named after the
BASE SERIES of the job's historical query — the reference browser charts
`foremastbrain:namespace_app_per_pod:<metric>_{upper,lower,anomaly}`.
prometheus_client forbids ':' in exposition names (it is the PromQL
recording-rule separator), so the worker exports the sanitized form
`foremastbrain_namespace_app_per_pod_<metric>_<suffix>` and the generated
recording rules (`metrics.rules.brain_rules()`, rendered into
`deploy/foremast/2_watch/metrics-rules.yaml` and the standalone stack's
native rule file) republish every family under the exact reference
spelling, so reference-compatible dashboards and alert rules see data
unchanged.
"""

from __future__ import annotations

import logging
import os
import re
import threading

log = logging.getLogger("foremast_tpu.gauges")

_SANITIZE = re.compile(r"[^a-zA-Z0-9_]")

# Gauge-family cap default: each family is 3 Gauge collectors held
# forever in the registry, and metric names arrive from job configs
# (REST-supplied), so an unbounded set is a memory + scrape-size leak
# an adversarial or merely churning client can drive.
DEFAULT_MAX_FAMILIES = 512


def _san(name: str) -> str:
    return _SANITIZE.sub("_", name)


class BrainGauges:
    """Lazily-created per-metric gauge triplets with a bounded family set.

    The bound is real (it was only a docstring promise before this):
    at most `max_families` distinct metric families are ever created
    (env `FOREMAST_MAX_GAUGE_FAMILIES`, default 512). Past the cap,
    publishes for NEW metric names are dropped — counted on
    `foremastbrain_gauge_families_dropped_total` and warned once —
    while every already-created family keeps updating normally.
    """

    def __init__(
        self,
        registry=None,
        namespace: str = "foremastbrain",
        max_families: int | None = None,
    ):
        from prometheus_client import REGISTRY, Gauge

        from foremast_tpu.observe.spans import counter

        self._Gauge = Gauge
        self.registry = registry if registry is not None else REGISTRY
        self.ns = namespace
        self._fams: dict[str, tuple] = {}
        self._lock = threading.Lock()
        self.max_families = (
            max_families
            if max_families is not None
            else int(
                os.environ.get("FOREMAST_MAX_GAUGE_FAMILIES", "")
                or DEFAULT_MAX_FAMILIES
            )
        )
        # shared-family helper, not a bare Counter: a second BrainGauges
        # on the same registry must reuse the family, not explode on
        # prometheus_client's duplicate-registration check
        self.dropped = counter(
            f"{self.ns}_gauge_families_dropped_total",
            "distinct metric families dropped because the gauge-family "
            "cap was hit",
            registry=self.registry,
        )
        # counted once per distinct family, not per publish — the name
        # says "families dropped" and a per-publish count would read as
        # thousands shed when exactly one metric is over the cap. The
        # dedup set is itself bounded: names arrive from REST-supplied
        # job configs (the very leak the family cap defends against), so
        # past the tracking bound the counter saturates instead of the
        # set growing forever.
        self._dropped_names: set[str] = set()
        self._dropped_track_limit = max(4 * self.max_families, 1024)
        self._cap_warned = False

    def _family(self, metric: str):
        key = _san(metric)
        with self._lock:
            fam = self._fams.get(key)
            if fam is not None:
                return fam
            if len(self._fams) >= self.max_families:
                if (
                    key not in self._dropped_names
                    and len(self._dropped_names) < self._dropped_track_limit
                ):
                    self._dropped_names.add(key)
                    self.dropped.inc()
                if not self._cap_warned:
                    self._cap_warned = True
                    log.warning(
                        "gauge-family cap (%d) hit; dropping new metric "
                        "families from exposition (first dropped: %r) — "
                        "raise FOREMAST_MAX_GAUGE_FAMILIES if the fleet "
                        "legitimately carries more distinct series",
                        self.max_families,
                        metric,
                    )
                return None
            mk = lambda suffix, doc: self._Gauge(
                f"{self.ns}_{key}_{suffix}",
                doc,
                ["exported_namespace", "app"],
                registry=self.registry,
            )
            fam = (
                mk("upper", f"model upper bound for {metric}"),
                mk("lower", f"model lower bound for {metric}"),
                mk("anomaly", f"last anomalous value for {metric}"),
            )
            self._fams[key] = fam
            return fam

    def publish(
        self,
        metric: str,
        namespace: str,
        app: str,
        upper: float,
        lower: float,
        anomaly_value: float | None = None,
    ) -> None:
        fam = self._family(metric)
        if fam is None:  # over the family cap; counted in self.dropped
            return
        up, lo, an = fam
        labels = dict(exported_namespace=namespace, app=app)
        up.labels(**labels).set(upper)
        lo.labels(**labels).set(lower)
        if anomaly_value is not None:
            an.labels(**labels).set(anomaly_value)


# base series name of a BARE-selector PromQL query, e.g.
# `query=namespace_app_per_pod:http_server_requests_latency{...}`. The
# lookahead rejects wrapped expressions (`query=sum(rate(...))` must NOT
# name a gauge "sum" — such jobs fall back to the alias), and the
# leading anchor requires a real parameter boundary (a REST-supplied URL
# with `subquery=foo` must not derive a gauge name from it).
_SERIES_RE = re.compile(r"(?:^|[?&])query=([a-zA-Z_:][a-zA-Z0-9_:]*)(?=\{|&|$)")


def _series_names(config: str) -> dict[str, str]:
    """alias -> base series name from a job config string's queries.

    Uses the canonical config-string codec (`metrics.promql.decode_config`
    — the same strings the brain fetches) and extracts the series from
    each URL; aliases whose query is not a bare selector are omitted, and
    so are aliases whose queries resolve to the SAME base series (two
    colliding aliases publishing one gauge family would silently
    last-write-win each other's verdicts) — in both cases the caller
    falls back to the alias-named gauge."""
    import urllib.parse

    from foremast_tpu.metrics.promql import decode_config

    out: dict[str, str] = {}
    for alias, url in decode_config(config or "").items():
        m = _SERIES_RE.search(urllib.parse.unquote(url))
        if m:
            out[alias] = m.group(1)
    counts: dict[str, int] = {}
    for series in out.values():
        counts[series] = counts.get(series, 0) + 1
    return {a: s for a, s in out.items() if counts[s] == 1}


def make_verdict_hook(gauges: BrainGauges, namespace: str | None = None):
    """BrainWorker.on_verdict adapter: publish the latest band edge and
    anomalous value per metric after each judgment.

    Gauge names follow the reference contract
    (`foremast-browser/src/config/metrics.js:15-23`): the gauge is named
    after the BASE SERIES of the job's historical query — e.g.
    `foremastbrain:namespace_app_per_pod:http_server_requests_latency_upper`
    (exported with '_' for ':'; the generated recording rules restore the
    colon spelling) — NOT after the job's short alias, so the UI, Grafana
    dashboard, and alert rules can all address the band without knowing
    per-app alias conventions. Jobs whose queries carry no parsable series
    name (arbitrary REST clients) fall back to the alias.

    The `exported_namespace` label is derived per-document from the job's
    PromQL selector (`namespace="..."` inside currentConfig) so the gauge
    lands next to the base series it models; the static `namespace`
    argument (default: NAMESPACE env, then "default") is only the
    fallback for jobs whose queries carry no namespace selector."""
    import os
    import urllib.parse

    default_ns = namespace or os.environ.get("NAMESPACE", "default")
    ns_re = re.compile(r'namespace="([^"]+)"')

    def hook(doc, verdicts):
        m = ns_re.search(urllib.parse.unquote(doc.current_config or ""))
        namespace = m.group(1) if m else default_ns
        # historical queries always use the per-app family the browser
        # charts (metricsquery.go:73-78); fall back to the current config
        names = _series_names(doc.historical_config) or _series_names(
            doc.current_config
        )
        for v in verdicts:
            if len(v.upper) == 0:
                continue
            gauges.publish(
                metric=names.get(v.alias, v.alias),
                namespace=namespace,
                app=doc.app_name,
                upper=float(v.upper[-1]),
                lower=float(v.lower[-1]),
                anomaly_value=(
                    float(v.anomaly_pairs[-1]) if v.anomaly_pairs else None
                ),
            )

    return hook


class WorkerMetrics:
    """Engine self-telemetry counters (alongside the foremastbrain gauges):

        foremast_worker_jobs_total{status}   — documents finalized/updated
        foremast_worker_windows_total        — metric windows judged
        foremast_worker_tick_seconds         — claim-fetch-judge-write time
        foremast_worker_arena_{hits,misses,evictions}_total — device
            state-arena traffic: a rising miss/eviction rate under a
            stable fleet means claim churn is re-paying state scatters
            (the cost VERDICT r3 flagged as silent)
        foremast_cold_hist_reads_total{source} — cold-fit history
            serving source; `http` climbing on a ring-covered fleet
            means the ring lost authority over historical ranges
        foremast_refine_docs_total{result} / foremast_provisional_fits
            — background refinement of short-history admissions
        foremast_verdict_latency_seconds{path} — the reactive plane's
            SLO: push receive-instant (receiver clock) → verdict
            write, by judging path (micro / sweep)
        foremast_microtick_docs_total — documents judged by
            ingest-triggered micro-ticks

    The reference exposes only model outputs; the engine's own throughput
    is this framework's headline property, so it is first-class here.
    """

    def __init__(self, registry=None):
        from prometheus_client import REGISTRY, Counter, Gauge, Histogram

        reg = registry if registry is not None else REGISTRY
        # exposed so late-bound custom collectors (tenant attribution)
        # can join this worker's exposition registry
        self.registry = reg
        self.jobs = Counter(
            "foremast_worker_jobs_total",
            "documents processed, by resulting status",
            ["status"],
            registry=reg,
        )
        self.windows = Counter(
            "foremast_worker_windows_total",
            "metric windows judged",
            registry=reg,
        )
        self.tick_seconds = Histogram(
            "foremast_worker_tick_seconds",
            "duration of one claim-fetch-judge-write cycle",
            registry=reg,
        )
        self.arena = Counter(
            "foremast_worker_arena_events_total",
            "device state-arena row events (hit=gathered warm, "
            "miss=scattered, eviction=row recycled under pressure, "
            "shard_move=row re-homed when its batch shard changed)",
            ["event"],
            registry=reg,
        )
        # per-kind columnar-path doc counts (ISSUE 4): joint kinds
        # (bivariate/lstm) > 0 is the observable proof that multi-alias
        # docs ride the fast tick instead of the per-task object path
        self.fast_docs = Counter(
            "foremast_worker_fast_docs_total",
            "documents scored on the columnar fast path, by model kind "
            "(univariate / bivariate / lstm)",
            ["kind"],
            registry=reg,
        )
        self._arena_last = {
            "hits": 0,
            "misses": 0,
            "evictions": 0,
            "shard_moves": 0,
            "fallbacks": 0,
        }
        # chunk-pipeline occupancy (jobs/pipeline.py), by path: the
        # "slow" path is the cold chunk pipeline (PR 3), the "warm"
        # path is the sliced sweep's claim-pool pipeline (ISSUE 15) —
        # warm-tick host stalls were invisible before the label. The
        # idle counter answers "how long did the judge stage sit
        # waiting on its inputs", the gauges snapshot the latest run.
        self.pipeline_idle = Counter(
            "foremast_worker_pipeline_idle_seconds_total",
            "seconds the judge stage (the device) sat stalled waiting "
            "for a chunk's inputs, by pipeline path (slow = cold chunk "
            "pipeline, warm = sliced-sweep pipeline)",
            ["path"],
            registry=reg,
        )
        self.pipeline_overlap = Gauge(
            "foremast_worker_pipeline_overlap_ratio",
            "latest tick per path: fraction of stage-busy seconds "
            "hidden by fetch/judge/write overlap (0 = serial, ~0.67 = "
            "perfect three-stage overlap)",
            ["path"],
            registry=reg,
        )
        self.pipeline_queue = Gauge(
            "foremast_worker_pipeline_write_queue_peak",
            "latest tick per path: peak depth of the verdict "
            "write-back queue",
            ["path"],
            registry=reg,
        )
        # sliced, preemptible sweeps (ISSUE 15)
        self.sweep_slices = Counter(
            "foremast_sweep_slices_total",
            "bounded slices executed by sliced sweeps "
            "(FOREMAST_SWEEP_SLICE_DOCS)",
            registry=reg,
        )
        self.sweep_preempt = Counter(
            "foremast_sweep_preempt_events_total",
            "slice-boundary preemption outcomes (promoted = pooled "
            "docs pulled into the next slice, inflight_requeued = "
            "arrival retried behind an in-flight slice, microtick = "
            "nested micro-tick ran between slices)",
            ["action"],
            registry=reg,
        )
        # ring-first cold path (ISSUE 10): where each cold fit's
        # historical range was served from, refinement outcomes, and
        # the provisional-fit backlog — the Prometheus twins of the
        # /debug/state `cold_start` section
        self.cold_hist = Counter(
            "foremast_cold_hist_reads_total",
            "historical-range reads on the cold-fit path, by serving "
            "source (ring_full / ring_partial / http / cache / "
            "unserved)",
            ["source"],
            registry=reg,
        )
        self.refine_docs = Counter(
            "foremast_refine_docs_total",
            "background-refinement outcomes for provisional "
            "short-history fits (refit / finalized / settled)",
            ["result"],
            registry=reg,
        )
        self.provisional = Gauge(
            "foremast_provisional_fits",
            "provisional (short-history) fits awaiting background "
            "refinement",
            registry=reg,
        )
        # reactive plane (ISSUE 12): the push→verdict SLO histogram —
        # receiver arrival stamp (the RECEIVER's clock, immune to
        # pusher clock skew) to verdict write, labeled by the tick
        # path that wrote it (micro = ingest-triggered micro-tick,
        # sweep = full tick catch-all) — plus the micro-tick doc count.
        # `tenant` (ISSUE 20) is bounded-cardinality: configured
        # tenants + up to FOREMAST_TENANT_LABEL_MAX observed label
        # values, everything past the cap folded into `other`;
        # untenanted workers export one constant `default` series per
        # path (worker._observe_verdicts owns the folding)
        self.verdict_latency = Histogram(
            "foremast_verdict_latency_seconds",
            "push receive-instant to verdict write, by judging path "
            "(micro = ingest-triggered micro-tick, sweep = full tick) "
            "and tenant (bounded by FOREMAST_TENANT_LABEL_MAX + the "
            "`other` overflow bucket)",
            ["path", "tenant"],
            buckets=(
                0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 5.0, 15.0, 60.0, 300.0,
            ),
            registry=reg,
        )
        self.microtick_docs = Counter(
            "foremast_microtick_docs_total",
            "documents judged by ingest-triggered micro-ticks",
            registry=reg,
        )
        # device mesh (ISSUE 13, FOREMAST_DEVICE_MESH): the Prometheus
        # twins of the /debug/state `device_mesh` section — mesh width,
        # batch rows split real/pad (pad fraction = pad / (real+pad);
        # the <2% overhead bar at fleet shapes), arena HBM (per-device
        # bytes x device count — shard-sum under the default sharded
        # layout, ISSUE 19), and the H2D-place / host-gather roofline
        # legs
        self.mesh_devices = Gauge(
            "foremast_device_mesh_devices",
            "devices in the judge's (data x model) mesh (1 family "
            "absent = single-device judge)",
            registry=reg,
        )
        self.mesh_rows = Counter(
            "foremast_device_mesh_rows_total",
            "columnar batch rows dispatched over the mesh, real vs "
            "padding (bucket + data-axis rounding)",
            ["kind"],
            registry=reg,
        )
        self.mesh_arena_bytes = Gauge(
            "foremast_device_mesh_arena_bytes",
            "replicated state-arena HBM across the mesh (one replica's "
            "bytes x device count)",
            registry=reg,
        )
        self.mesh_transfer_seconds = Counter(
            "foremast_device_mesh_transfer_seconds_total",
            "host<->device transfer wall-clock on the sharded judge, "
            "by leg (h2d = NamedSharding placement, gather = sharded-"
            "result fetch incl. the deferred device execution it waits "
            "on)",
            ["leg"],
            registry=reg,
        )
        self.mesh_transfer_bytes = Counter(
            "foremast_device_mesh_transfer_bytes_total",
            "bytes moved by the sharded judge's host<->device legs",
            ["leg"],
            registry=reg,
        )
        self._mesh_last = {
            "rows_real": 0, "rows_pad": 0,
            "h2d_s": 0.0, "h2d_b": 0,
            "gather_s": 0.0, "gather_b": 0,
        }

    def observe_pipeline(self, stats, path: str = "slow") -> None:
        """Feed one ChunkPipeline run's stats (jobs/pipeline.py
        PipelineStats) — path "slow" for the cold chunk pipeline,
        "warm" for the sliced sweep's."""
        self.pipeline_idle.labels(path=path).inc(
            max(0.0, stats.judge_stall_seconds)
        )
        self.pipeline_overlap.labels(path=path).set(stats.overlap_ratio())
        self.pipeline_queue.labels(path=path).set(stats.write_queue_peak)

    def observe_sweep(self, stats, counters: dict) -> None:
        """Feed one sliced sweep's pipeline stats + preemption
        counters (BrainWorker._sweep_sliced)."""
        if stats is not None:
            self.observe_pipeline(stats, path="warm")
        self.sweep_slices.inc(counters.get("slices", 0))
        for action, key in (
            ("promoted", "promoted"),
            ("inflight_requeued", "inflight_requeued"),
            ("microtick", "preempt_microticks"),
        ):
            n = counters.get(key, 0)
            if n:
                self.sweep_preempt.labels(action=action).inc(n)

    def observe_doc(self, status: str, n_windows: int) -> None:
        self.jobs.labels(status=status).inc()
        self.windows.inc(n_windows)

    def observe_device_mesh(self, state: dict) -> None:
        """Feed the worker's cumulative device_mesh varz section
        (BrainWorker._device_mesh_state); deltas keep the Prometheus
        counters monotone, same discipline as observe_arena — negative
        deltas (a new judge) clamp to zero."""
        self.mesh_devices.set(state.get("devices", 1))
        self.mesh_arena_bytes.set(
            state.get("arena_total_device_bytes", 0)
        )
        last = self._mesh_last
        pad = state.get("pad_rows_total", 0)
        real = state.get("batch_rows_total", 0) - pad
        cur = {
            "rows_real": real,
            "rows_pad": pad,
            "h2d_s": state.get("place_seconds", 0.0),
            "h2d_b": state.get("place_bytes", 0),
            "gather_s": state.get("fetch_seconds", 0.0),
            "gather_b": state.get("fetch_bytes", 0),
        }
        sinks = {
            "rows_real": (self.mesh_rows, {"kind": "real"}),
            "rows_pad": (self.mesh_rows, {"kind": "pad"}),
            "h2d_s": (self.mesh_transfer_seconds, {"leg": "h2d"}),
            "h2d_b": (self.mesh_transfer_bytes, {"leg": "h2d"}),
            "gather_s": (self.mesh_transfer_seconds, {"leg": "gather"}),
            "gather_b": (self.mesh_transfer_bytes, {"leg": "gather"}),
        }
        for k, (family, labels) in sinks.items():
            delta = cur[k] - last[k]
            if delta > 0:
                family.labels(**labels).inc(delta)
            last[k] = cur[k]

    def observe_arena(self, counters: dict) -> None:
        """Feed cumulative judge.device_state_counters(); deltas are
        exported so the Prometheus counters stay monotone. The source is
        itself monotone across arena rebuilds (retired arenas fold into
        HealthJudge._counters_base), so no re-baseline heuristic is
        needed — a negative delta can only mean a new judge instance and
        is clamped to zero rather than guessed at."""
        for event in (
            "hits",
            "misses",
            "evictions",
            "shard_moves",
            "fallbacks",
        ):
            cur = counters.get(event, 0)
            delta = cur - self._arena_last[event]
            if delta > 0:
                self.arena.labels(event=event).inc(delta)
            self._arena_last[event] = cur


def start_metrics_server(port: int = 8000, registry=None):
    """Serve /metrics on :8000 (the reference brain's scrape port)."""
    from prometheus_client import REGISTRY, start_http_server

    return start_http_server(port, registry=registry or REGISTRY)
