"""Observability: gauge export, structured logs, profiler hooks."""

from foremast_tpu.observe.gauges import (
    BrainGauges,
    make_verdict_hook,
    start_metrics_server,
)
from foremast_tpu.observe.logs import JsonFormatter, ctx_log, setup_logging
from foremast_tpu.observe.profile import annotate, trace_scoring

__all__ = [
    "BrainGauges",
    "make_verdict_hook",
    "start_metrics_server",
    "JsonFormatter",
    "ctx_log",
    "setup_logging",
    "annotate",
    "trace_scoring",
]
