"""Observability: spans, gauge export, structured logs, profiler hooks."""

from foremast_tpu.observe.gauges import (
    BrainGauges,
    WorkerMetrics,
    make_verdict_hook,
    start_metrics_server,
)
from foremast_tpu.observe.logs import JsonFormatter, ctx_log, setup_logging
from foremast_tpu.observe.profile import annotate, trace_scoring
from foremast_tpu.observe.spans import (
    Span,
    SpanRing,
    Tracer,
    counter,
    current_span,
    span,
    start_observe_server,
)

__all__ = [
    "BrainGauges",
    "WorkerMetrics",
    "make_verdict_hook",
    "start_metrics_server",
    "JsonFormatter",
    "ctx_log",
    "setup_logging",
    "annotate",
    "trace_scoring",
    "Span",
    "SpanRing",
    "Tracer",
    "counter",
    "current_span",
    "span",
    "start_observe_server",
]
