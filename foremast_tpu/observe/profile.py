"""Profiling hooks around the scoring loop (SURVEY.md section 5: the reference
has none; the new build adds jax.profiler traces + optional Perfetto dumps)."""

from __future__ import annotations

import contextlib
import os


@contextlib.contextmanager
def trace_scoring(out_dir: str | None = None, create_perfetto_link: bool = False):
    """Wrap a scoring region in a jax.profiler trace when FOREMAST_PROFILE
    (or an explicit out_dir) is set; no-op otherwise."""
    import jax

    target = out_dir or os.environ.get("FOREMAST_PROFILE")
    if not target:
        yield
        return
    jax.profiler.start_trace(target, create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str):
    """Named sub-region (shows up in the trace timeline)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield
