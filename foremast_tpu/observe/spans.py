"""Dapper-style span pipeline: one correlation ID per judgment, end to end.

The reference brain's only observability is its re-published output
gauges (SURVEY §2.3) — when a judgment is late there is no way to see
*where* the tick spent its time. This module threads a trace through
service → store → worker tick stages → engine → controller and exports
it three ways:

  * Prometheus ``foremast_tick_stage_seconds{stage=...}`` histograms —
    per-stage latency attribution for every tick (always on when a
    Tracer is wired; the per-span cost is one perf_counter pair and a
    histogram observe);
  * a bounded ring buffer of Chrome-trace events, dumped as JSONL that
    Perfetto loads directly — gated by ``FOREMAST_TRACE_DIR`` (or an
    explicit ``trace_dir``), so the deployed default pays nothing for
    the buffer;
  * trace/span IDs injected into the JSON log records
    (``observe.logs.JsonFormatter``) so logs, metrics and traces all
    correlate on one ID.

Design: a single contextvar carries ``(tracer, active_span)``. Library
code (store, engine, arena) calls the module-level :func:`span` helper,
which attaches a child span to whatever tracer the caller's tick opened
— or no-ops when none is active. Only the process entry points (worker
loop, service app, controller) hold a Tracer instance, so the engine
never needs plumbing and un-instrumented callers pay one contextvar
read per call site.

Host spans around device work pass ``device=True``, which additionally
wraps the region in ``jax.profiler.TraceAnnotation`` — with
``FOREMAST_PROFILE`` set, host spans and XLA device traces land on one
Perfetto timeline.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import json
import logging
import os
import threading
import time
import uuid
import weakref

log = logging.getLogger("foremast_tpu.observe.spans")

# (tracer, span) of the innermost open span. One var, not two: the
# module-level span() helper must attach children to the SAME tracer
# that opened the enclosing root, never to some other instance.
_ACTIVE: contextvars.ContextVar = contextvars.ContextVar(
    "foremast_active_span", default=None
)

# Stage-histogram buckets: warm columnar stages sit in the 100 us - 10 ms
# band while a fleet-cold fit runs tens of seconds; the default
# prometheus buckets would collapse the warm path into one bucket.
STAGE_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

# The canonical tick stages (docs/observability.md): claim → metric_fetch
# → fit → arena_assemble → score → decode → decide → write_back. Kept
# here so the metrics lint and the docs can't drift from the emitters.
TICK_STAGES = (
    "claim",
    "metric_fetch",
    "fit",
    "arena_assemble",
    "score",
    "decode",
    "decide",
    "write_back",
)


# epoch offset of the monotonic clock, taken once at import
_CLOCK_ANCHOR = time.time() - time.perf_counter()


def new_trace_id() -> str:
    """Mint a correlation ID in the span-pipeline format. Public so
    callers that stamp IDs without an active span (the service's
    tracing-off path) stay format-compatible with span-derived ones."""
    return uuid.uuid4().hex[:16]


_new_id = new_trace_id


class Span:
    """One timed region. Completed spans are exported as Chrome trace
    events (phase "X": complete event with ts+dur in microseconds)."""

    __slots__ = (
        "trace_id",
        "span_id",
        "parent_id",
        "name",
        "stage",
        "attrs",
        "ts",
        "duration",
        "_t0",
    )

    def __init__(self, name, trace_id, parent_id, stage=None, attrs=None):
        self.name = name
        self.trace_id = trace_id
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.stage = stage
        self.attrs = attrs or {}
        self.duration = 0.0
        self._t0 = time.perf_counter()
        # wall-clock ts derived from ONE anchor + the monotonic clock:
        # if NTP steps the wall clock mid-tick, per-span time.time()
        # would shift later spans past/before their parent on the
        # Perfetto timeline while durations stay monotonic
        self.ts = _CLOCK_ANCHOR + self._t0

    def to_event(self) -> dict:
        args = {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
        }
        if self.stage:
            args["stage"] = self.stage
        args.update(self.attrs)
        return {
            "name": self.name,
            "cat": "foremast",
            "ph": "X",
            "ts": round(self.ts * 1e6, 1),
            "dur": round(self.duration * 1e6, 1),
            "pid": os.getpid(),
            # Perfetto wants a numeric tid; mask to keep it in range
            "tid": threading.get_ident() & 0x7FFFFFFF,
            "args": args,
        }


class SpanRing:
    """Thread-safe bounded buffer of completed-span trace events.

    A deque(maxlen=N) ring: the newest `capacity` spans win, older ones
    fall off — a long-lived worker keeps the recent past resident for a
    /debug dump without unbounded growth. `total` counts everything ever
    added so a dump can report how much history scrolled away.
    """

    def __init__(self, capacity: int = 8192):
        self.capacity = capacity
        self._events: collections.deque = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self.total = 0

    def add(self, event: dict) -> None:
        with self._lock:
            self._events.append(event)
            self.total += 1

    def snapshot(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def dump_jsonl(self, path: str) -> int:
        """Write one Chrome trace event per line (Perfetto's JSON
        importer accepts newline-delimited events); returns #events.
        Written to a sibling temp file and renamed, so a reader never
        loads a half-written dump."""
        events = self.snapshot()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            for e in events:
                f.write(json.dumps(e) + "\n")
        os.replace(tmp, path)
        return len(events)


@contextlib.contextmanager
def _null_span():
    yield None


class Tracer:
    """Per-process span factory + exporters.

    One Tracer per entry point (worker / service / controller). Opening
    a span publishes it as the context's active span, so nested
    module-level :func:`span` calls — engine, arena, store — parent to
    it automatically and share its trace ID.
    """

    # flush the ring to disk at most this often (root-span exits only)
    AUTOFLUSH_SECONDS = 10.0

    def __init__(
        self,
        service: str = "foremast",
        registry=None,
        trace_dir: str | None = None,
        buffer_size: int = 8192,
        histogram: bool = True,
    ):
        self.service = service
        self.trace_dir = (
            trace_dir
            if trace_dir is not None
            else (os.environ.get("FOREMAST_TRACE_DIR") or None)
        )
        self.ring = SpanRing(buffer_size) if self.trace_dir else None
        # stage -> seconds within the latest root span (tick/poll/
        # request); reset when a new root opens so the /debug/state
        # breakdown never mixes stages from different ticks
        self.last_stage_seconds: dict[str, float] = {}
        self._hist = None
        if histogram:
            from prometheus_client import Histogram

            # shared per (registry, name): two Tracers over one registry
            # (service app recreated, worker+controller embedded) must
            # reuse the family, not collide on prometheus_client's
            # duplicate-registration check
            self._hist = _shared_family(
                registry,
                "foremast_tick_stage_seconds",
                lambda reg: Histogram(
                    "foremast_tick_stage_seconds",
                    "duration of one judgment-tick stage",
                    ["stage"],
                    registry=reg,
                    buckets=STAGE_BUCKETS,
                ),
            )
        self._last_flush = time.monotonic()
        self._flush_lock = threading.Lock()
        self._flush_active = False
        self._flush_warned = False
        # serializes dump_jsonl between explicit flush() callers and the
        # background autoflush thread (both write the same target path)
        self._io_lock = threading.Lock()

    # -- span creation ---------------------------------------------------

    @contextlib.contextmanager
    def span(
        self,
        name: str,
        stage: str | None = None,
        trace_id: str | None = None,
        device: bool = False,
        **attrs,
    ):
        """Open a span. Child of the context's active span unless an
        explicit `trace_id` is given (adopting a correlation ID carried
        by a request/document starts a fresh root under that ID).
        `device=True` wraps the region in jax.profiler.TraceAnnotation
        so it shows on the XLA timeline too."""
        parent = current_span()
        if trace_id is not None:
            s = Span(name, trace_id, "", stage=stage, attrs=attrs)
        elif parent is not None:
            s = Span(
                name, parent.trace_id, parent.span_id, stage=stage, attrs=attrs
            )
        else:
            s = Span(name, _new_id(), "", stage=stage, attrs=attrs)
        if parent is None:
            # fresh root: restart the stage breakdown (atomic swap, so a
            # concurrent /debug/state read sees old-or-new, never a mix)
            self.last_stage_seconds = {}
        token = _ACTIVE.set((self, s))
        dev_cm = _null_span()
        if device:
            try:
                import jax

                dev_cm = jax.profiler.TraceAnnotation(name)
            except Exception:  # noqa: BLE001 - tracing must never break scoring
                pass
        try:
            with dev_cm:
                yield s
        finally:
            s.duration = time.perf_counter() - s._t0
            _ACTIVE.reset(token)
            self._finish(s, root=parent is None)

    def _finish(self, s: Span, root: bool) -> None:
        if s.stage is not None:
            # accumulate: a tick may open several spans per stage (chunked
            # fetch/decide/write-back, per-bucket score) and the breakdown
            # must attribute ALL of that stage's time, not the last chunk's
            self.last_stage_seconds[s.stage] = (
                self.last_stage_seconds.get(s.stage, 0.0) + s.duration
            )
            if self._hist is not None:
                self._hist.labels(stage=s.stage).observe(s.duration)
        if self.ring is not None:
            self.ring.add(s.to_event())
            if root:
                self._autoflush()

    def _autoflush(self) -> None:
        """Flush on a daemon thread: root-span exit runs on whatever
        thread (or event loop) closed the span, and serializing the
        whole ring there would stall it. At most one background flush
        at a time; a flush in flight just defers to the next root.
        The elapsed-time check sits under the lock too — an unlocked
        read of `_last_flush` raced concurrent root exits into
        duplicate flush threads (lock-discipline finding)."""
        with self._flush_lock:
            if self._flush_active:
                return
            if (
                time.monotonic() - self._last_flush
                < self.AUTOFLUSH_SECONDS
            ):
                return
            self._flush_active = True
            # stamp inside the lock so concurrent root exits don't pile
            # up more flush threads before the first one finishes
            self._last_flush = time.monotonic()

        def _run():
            try:
                self.flush()
            except Exception as e:  # noqa: BLE001 - tracing must never break serving
                # warn ONCE: an unwritable FOREMAST_TRACE_DIR otherwise
                # fails every 10 s with zero signal until shutdown
                if not self._flush_warned:
                    self._flush_warned = True
                    log.warning(
                        "trace flush to %s failed (%s); dumps disabled "
                        "until the path is writable",
                        self.trace_path(),
                        e,
                    )
            finally:
                with self._flush_lock:
                    self._flush_active = False

        threading.Thread(
            target=_run, name="foremast-trace-flush", daemon=True
        ).start()

    # -- export ----------------------------------------------------------

    def trace_path(self) -> str | None:
        if not self.trace_dir:
            return None
        return os.path.join(
            self.trace_dir,
            f"foremast-{self.service}-{os.getpid()}.trace.jsonl",
        )

    def flush(self, path: str | None = None) -> str | None:
        """Dump the ring buffer as Perfetto-loadable JSONL; returns the
        path written, or None when the buffer is disabled. Serialized
        against the background autoflush — both write the same target,
        and two unguarded writers would truncate each other's temp
        file."""
        if self.ring is None:
            return None
        target = path or self.trace_path()
        with self._io_lock:
            os.makedirs(os.path.dirname(target) or ".", exist_ok=True)
            # _io_lock exists to serialize exactly this dump between
            # explicit flush() callers and the autoflush daemon — two
            # unguarded writers would truncate each other's temp file
            # foremast: ignore[blocking-under-lock]
            self.ring.dump_jsonl(target)
        # `_last_flush` is _flush_lock state (the autoflush scheduler's
        # elapsed check reads it there); stamping it under _io_lock
        # raced the two critical sections against each other
        # (thread-escape mixed-guard finding)
        with self._flush_lock:
            self._last_flush = time.monotonic()
        return target

    def debug_state(self) -> dict:
        return {
            "service": self.service,
            "trace_dir": self.trace_dir,
            "buffer_spans": len(self.ring) if self.ring is not None else 0,
            "spans_total": self.ring.total if self.ring is not None else 0,
            "last_stage_seconds": dict(self.last_stage_seconds),
        }


# ---------------------------------------------------------------------------
# ambient helpers — what library code uses
# ---------------------------------------------------------------------------


def current_span() -> Span | None:
    """The innermost open span of this context (None outside any)."""
    active = _ACTIVE.get()
    return active[1] if active is not None else None


def span(name: str, stage: str | None = None, device: bool = False, **attrs):
    """Child span on the caller's ambient tracer, or a no-op when no
    tracer opened a span in this context — library code (store, engine,
    arena) instruments unconditionally and costs one contextvar read
    when tracing is off."""
    active = _ACTIVE.get()
    if active is None:
        if device:
            try:
                import jax

                return jax.profiler.TraceAnnotation(name)
            except Exception:  # noqa: BLE001
                return _null_span()
        return _null_span()
    return active[0].span(name, stage=stage, device=device, **attrs)


def inherit_span(fn):
    """Wrap `fn` so it runs under the submitting thread's ambient span.
    ThreadPoolExecutor workers start with an empty context, so without
    this their log records lose the tick's trace_id/span_id — exactly
    the fetch-failure logs the correlation exists to join. A single
    shared `Context.run` cannot be entered concurrently, so only the
    active-span var is re-seated (and reset) per call."""
    active = _ACTIVE.get()

    def wrapped(*args, **kwargs):
        token = _ACTIVE.set(active)
        try:
            return fn(*args, **kwargs)
        finally:
            _ACTIVE.reset(token)

    return wrapped


# ---------------------------------------------------------------------------
# shared metric families (service requests, controller transitions,
# stage histograms, gauge-drop counters)
# ---------------------------------------------------------------------------

# one collector object per (registry, name): several make_app()/Tracer/
# controller instances over one registry must share the family, not
# collide on prometheus_client's duplicate-registration error
_FAMILIES: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_FAMILY_LOCK = threading.Lock()


def _shared_family(registry, name: str, make):
    from prometheus_client import REGISTRY

    reg = registry if registry is not None else REGISTRY
    with _FAMILY_LOCK:
        per = _FAMILIES.get(reg)
        if per is None:
            per = {}
            _FAMILIES[reg] = per
        fam = per.get(name)
        if fam is None:
            fam = make(reg)
            per[name] = fam
        return fam


def counter(name: str, documentation: str, labels=(), registry=None):
    from prometheus_client import Counter

    return _shared_family(
        registry,
        name,
        lambda reg: Counter(name, documentation, list(labels), registry=reg),
    )


# ---------------------------------------------------------------------------
# /metrics + /healthz + /debug/state exposition server (worker side)
# ---------------------------------------------------------------------------


def start_observe_server(
    port: int,
    registry=None,
    state_fn=None,
    host: str = "0.0.0.0",
    max_port_tries: int = 1,
):
    """Serve /metrics (Prometheus exposition), /healthz, and
    /debug/state (JSON varz from `state_fn`) on one port — the worker's
    :8000 scrape endpoint, extended. Returns (server, thread); the
    thread is a daemon, same lifecycle as prometheus_client's
    start_http_server.

    `max_port_tries` > 1 auto-increments past ports already bound (up
    to port+tries-1): two workers on one host — the mesh's normal
    topology — must not kill each other over :8000. Read the ACTUAL
    port back from server.server_address (the mesh publishes it in the
    member record)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from prometheus_client import CONTENT_TYPE_LATEST, REGISTRY, generate_latest

    reg = registry if registry is not None else REGISTRY

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # metrics scrapes must not spam stderr
            pass

        def _send(self, code: int, body: bytes, ctype: str) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            path = self.path.split("?", 1)[0]
            if path == "/metrics":
                self._send(200, generate_latest(reg), CONTENT_TYPE_LATEST)
            elif path == "/healthz":
                from foremast_tpu import __version__

                body = json.dumps({"ok": True, "version": __version__})
                self._send(200, body.encode(), "application/json")
            elif path == "/debug/state":
                try:
                    state = state_fn() if state_fn is not None else {}
                    code = 200
                except Exception as e:  # noqa: BLE001 - varz must not 500-loop
                    state, code = {"error": str(e)}, 500
                body = json.dumps(state, default=str, indent=2)
                self._send(code, body.encode(), "application/json")
            else:
                self._send(404, b'{"reason": "not found"}', "application/json")

    import errno

    srv = None
    last_err: OSError | None = None
    # port 0 is the OS's ephemeral pick — auto-increment is meaningless
    tries = 1 if port == 0 else max(1, int(max_port_tries))
    for i in range(tries):
        try:
            srv = ThreadingHTTPServer((host, port + i), Handler)
            break
        except OSError as e:
            # only a BUSY port is worth walking past: privilege or
            # address errors repeat identically on port+1 and the
            # configured port must stay in the error the operator sees
            if e.errno != errno.EADDRINUSE:
                raise
            last_err = e
    if srv is None:
        raise last_err
    if port and srv.server_address[1] != port:
        logging.getLogger("foremast_tpu.observe").info(
            "observe port %d busy; serving /metrics + /debug/state on "
            ":%d instead", port, srv.server_address[1],
        )
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    return srv, thread
