"""Structured leveled logging for all components.

The reference scatters glog V-levels (barrelman), gin logs (service), and
an unused leveled-logger scaffold (`foremast-service/pkg/common/logger.go`);
here one JSON-lines logger serves every component.
"""

from __future__ import annotations

import json
import logging
import sys
import time

from foremast_tpu.observe.spans import current_span


class JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": round(time.time(), 3),
            "level": record.levelname.lower(),
            "logger": record.name,
            "msg": record.getMessage(),
        }
        # correlate with the span pipeline: any record emitted inside an
        # open span carries its trace/span IDs, so logs, metrics and the
        # Perfetto dump all join on one ID (observe/spans.py)
        sp = current_span()
        if sp is not None:
            out["trace_id"] = sp.trace_id
            out["span_id"] = sp.span_id
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        extra = getattr(record, "ctx", None)
        if extra:
            out.update(extra)
        return json.dumps(out)


def setup_logging(level: int = logging.INFO, stream=None) -> None:
    handler = logging.StreamHandler(stream or sys.stderr)
    handler.setFormatter(JsonFormatter())
    root = logging.getLogger("foremast_tpu")
    root.handlers[:] = [handler]
    root.setLevel(level)
    root.propagate = False


def ctx_log(logger: logging.Logger, level: int, msg: str, **ctx) -> None:
    logger.log(level, msg, extra={"ctx": ctx})
