"""Metric naming-convention lint — dashboard-contract enforcement.

Every family this framework exports must match
``foremast(brain)?_[a-z0-9_]+`` (the two prefixes the deployed
dashboards, recording rules and alert rules key on), and the core
families must carry exactly their documented label sets
(docs/observability.md). A future PR renaming a family or adding a
label silently breaks every dashboard built on it; ``make metrics-lint``
and the tier-1 test in tests/test_observe.py make that a build failure
instead.

Usage:
    lint_registry(registry) -> list of violation strings (empty = clean)
    python -m foremast_tpu.observe.metrics_lint   # lints the default set
"""

from __future__ import annotations

import re

NAME_RE = re.compile(r"^foremast(brain)?_[a-z0-9_]+$")
LABEL_RE = re.compile(r"^[a-z][a-z0-9_]*$")

# prometheus_client-internal sample labels that are not family labels
_SYNTHETIC_LABELS = frozenset({"le", "quantile"})

# family name (as collected — counters are collected WITHOUT the _total
# suffix) -> exact allowed label set. Families not listed here only need
# the name/label regexes.
ALLOWED_LABELS: dict[str, frozenset[str]] = {
    "foremast_tick_stage_seconds": frozenset({"stage"}),
    "foremast_worker_jobs": frozenset({"status"}),
    "foremast_worker_windows": frozenset(),
    "foremast_worker_tick_seconds": frozenset(),
    "foremast_worker_arena_events": frozenset({"event"}),
    "foremast_worker_fast_docs": frozenset({"kind"}),
    # chunk-pipeline occupancy (observe/gauges.py WorkerMetrics), by
    # path since ISSUE 15: "slow" = the cold chunk pipeline (PR 3),
    # "warm" = the sliced sweep's claim-pool pipeline
    "foremast_worker_pipeline_idle_seconds": frozenset({"path"}),
    "foremast_worker_pipeline_overlap_ratio": frozenset({"path"}),
    "foremast_worker_pipeline_write_queue_peak": frozenset({"path"}),
    # sliced, preemptible sweeps (ISSUE 15, observe/gauges.py
    # WorkerMetrics)
    "foremast_sweep_slices": frozenset(),
    "foremast_sweep_preempt_events": frozenset({"action"}),
    # ring-first cold start + background refinement (ISSUE 10,
    # observe/gauges.py WorkerMetrics)
    "foremast_cold_hist_reads": frozenset({"source"}),
    "foremast_refine_docs": frozenset({"result"}),
    "foremast_provisional_fits": frozenset(),
    # reactive plane (ISSUE 12): push→verdict SLO + micro-tick traffic
    # (worker: observe/gauges.py; dirty set: reactive/dirty.py
    # ReactiveCollector; watch stream: reactive/watchstream.py)
    "foremast_verdict_latency_seconds": frozenset({"path", "tenant"}),
    # device mesh (ISSUE 13, observe/gauges.py WorkerMetrics)
    "foremast_device_mesh_devices": frozenset(),
    "foremast_device_mesh_rows": frozenset({"kind"}),
    "foremast_device_mesh_arena_bytes": frozenset(),
    "foremast_device_mesh_transfer_seconds": frozenset({"leg"}),
    "foremast_device_mesh_transfer_bytes": frozenset({"leg"}),
    "foremast_microtick_docs": frozenset(),
    "foremast_microtick_dirty_events": frozenset({"event"}),
    "foremast_microtick_dirty_pending": frozenset(),
    "foremast_watch_stream_events": frozenset({"type"}),
    "foremast_watch_stream_restarts": frozenset({"reason"}),
    "foremast_service_requests": frozenset({"route", "code"}),
    "foremast_controller_transitions": frozenset({"phase"}),
    "foremastbrain_gauge_families_dropped": frozenset(),
    # ingest plane (foremast_tpu/ingest/receiver.py IngestCollector)
    "foremast_ingest_fetches": frozenset({"result"}),
    "foremast_ingest_samples": frozenset(),
    "foremast_ingest_evictions": frozenset(),
    "foremast_ingest_series_resident": frozenset(),
    "foremast_ingest_bytes_resident": frozenset(),
    "foremast_ingest_receiver_lag_seconds": frozenset(),
    "foremast_ingest_requests": frozenset({"codec"}),
    "foremast_ingest_stage_seconds": frozenset({"codec", "stage"}),
    # worker mesh (foremast_tpu/mesh/node.py MeshCollector)
    "foremast_mesh_members": frozenset({"state"}),
    "foremast_mesh_rebalances": frozenset(),
    "foremast_mesh_redirect_hints": frozenset(),
    "foremast_mesh_claim_docs": frozenset({"result"}),
    # planned handoff (ISSUE 11, foremast_tpu/mesh/node.py MeshCollector)
    "foremast_handoff_state": frozenset({"kind", "direction"}),
    "foremast_handoff_transfers": frozenset({"role", "result"}),
    # chaos plane + degradation (foremast_tpu/chaos/collector.py)
    "foremast_chaos_injections": frozenset({"edge", "kind"}),
    "foremast_breaker_state": frozenset({"edge"}),
    "foremast_breaker_transitions": frozenset({"edge", "state"}),
    "foremast_breaker_short_circuits": frozenset({"edge"}),
    "foremast_degraded_docs": frozenset({"reason"}),
    "foremast_degraded_events": frozenset({"edge", "action"}),
    # multi-tenant QoS plane (ISSUE 20, foremast_tpu/tenant/collector.py
    # TenantCollector) — `tenant` is bounded-cardinality: configured
    # tenants + up to FOREMAST_TENANT_LABEL_MAX observed values, the
    # rest folded into `other`
    "foremast_tenant_shed": frozenset({"tenant"}),
    "foremast_tenant_evictions": frozenset({"tenant"}),
    "foremast_tenant_claims": frozenset({"tenant"}),
    "foremast_tenant_ring_bytes": frozenset({"tenant"}),
    # durable data plane (foremast_tpu/ingest/snapshot.py SnapshotCollector)
    "foremast_snapshot_discards": frozenset({"reason"}),
    "foremast_snapshot_restored_series": frozenset(),
    "foremast_snapshot_restored_samples": frozenset(),
    "foremast_snapshot_restored_fits": frozenset(),
    "foremast_snapshot_writes": frozenset(),
    "foremast_snapshot_age_seconds": frozenset(),
}

# one-line operator meaning per family — the source the generated
# "family index" table in docs/observability.md renders from (rule
# `metrics-contract`: every constructed family must appear in
# ALLOWED_LABELS AND here, and the committed table must match; the
# three sources can no longer drift). Keys match ALLOWED_LABELS
# (collected names: counters WITHOUT their `_total` suffix).
FAMILY_DOCS: dict[str, str] = {
    "foremast_tick_stage_seconds": (
        "histogram of one judgment-tick stage (worker stages: claim … "
        "write_back; controller stages: poll … pause)"
    ),
    "foremast_worker_jobs": "documents finalized, by resulting status",
    "foremast_worker_windows": "metric windows judged",
    "foremast_worker_tick_seconds": (
        "histogram of the whole claim-fetch-judge-write cycle"
    ),
    "foremast_worker_arena_events": (
        "device state-arena traffic (hits/misses/evictions/"
        "shard_moves/fallbacks)"
    ),
    "foremast_worker_fast_docs": (
        "documents scored on the columnar fast path, by model kind "
        "(univariate/bivariate/lstm, plus `baseline` — the canary "
        "bucket: baseline-carrying univariate docs judged through the "
        "pairwise-active columnar program)"
    ),
    "foremast_worker_pipeline_idle_seconds": (
        "seconds the judge stage sat stalled waiting on a chunk's "
        "inputs, by path (slow = cold chunk pipeline, warm = "
        "sliced-sweep pipeline)"
    ),
    "foremast_worker_pipeline_overlap_ratio": (
        "latest tick per path: fraction of stage-busy seconds hidden "
        "by fetch/judge/write overlap"
    ),
    "foremast_worker_pipeline_write_queue_peak": (
        "latest tick per path: peak verdict write-back queue depth"
    ),
    "foremast_sweep_slices": (
        "bounded slices executed by sliced sweeps "
        "(FOREMAST_SWEEP_SLICE_DOCS, ISSUE 15)"
    ),
    "foremast_sweep_preempt_events": (
        "slice-boundary preemption outcomes (promoted / "
        "inflight_requeued / microtick)"
    ),
    "foremast_cold_hist_reads": (
        "historical-range reads on the cold-fit path, by serving "
        "source (ring_full/ring_partial/http/cache/unserved)"
    ),
    "foremast_refine_docs": (
        "background-refinement outcomes for provisional short-history "
        "fits (refit/finalized/settled)"
    ),
    "foremast_provisional_fits": (
        "provisional (short-history) fits awaiting background "
        "refinement"
    ),
    "foremast_verdict_latency_seconds": (
        "push receive-instant (receiver clock) to verdict write, by "
        "judging path (micro/sweep) and tenant (bounded by "
        "FOREMAST_TENANT_LABEL_MAX + the `other` overflow bucket) — "
        "the reactive plane's SLO"
    ),
    "foremast_microtick_docs": (
        "documents judged by ingest-triggered micro-ticks"
    ),
    "foremast_device_mesh_devices": (
        "devices in the judge's (data x model) mesh"
    ),
    "foremast_device_mesh_rows": (
        "columnar batch rows dispatched over the mesh, real vs pad "
        "(bucket + data-axis rounding)"
    ),
    "foremast_device_mesh_arena_bytes": (
        "state-arena HBM: per-device bytes x device count (shard-sum "
        "under the default sharded layout; the replication tax with "
        "FOREMAST_ARENA_SHARDED=0)"
    ),
    "foremast_device_mesh_transfer_seconds": (
        "sharded-judge host<->device wall-clock by leg (h2d placement "
        "/ sharded-result gather)"
    ),
    "foremast_device_mesh_transfer_bytes": (
        "bytes moved by the sharded judge's host<->device legs"
    ),
    "foremast_microtick_dirty_events": (
        "dirty-set traffic (marked/coalesced/dropped/foreign/"
        "requeued/unattributed/promoted/inflight_requeued)"
    ),
    "foremast_microtick_dirty_pending": (
        "route keys currently pending in the dirty set"
    ),
    "foremast_watch_stream_events": (
        "deployment watch-stream events dispatched, by type"
    ),
    "foremast_watch_stream_restarts": (
        "watch-stream reconnects (gone/stall/error/end)"
    ),
    "foremast_service_requests": (
        "gateway requests by route pattern and status code"
    ),
    "foremast_controller_transitions": (
        "DeploymentMonitor phase transitions observed by the poller"
    ),
    "foremastbrain_gauge_families_dropped": (
        "distinct metric families dropped past the gauge-family cap"
    ),
    "foremast_ingest_fetches": (
        "ring TSDB fetch outcomes (hit/miss/stale/uncovered)"
    ),
    "foremast_ingest_samples": (
        "samples accepted by the ingest plane (receiver + direct push)"
    ),
    "foremast_ingest_evictions": (
        "whole series evicted under FOREMAST_INGEST_BUDGET_BYTES"
    ),
    "foremast_ingest_series_resident": (
        "series currently resident in the ring TSDB"
    ),
    "foremast_ingest_bytes_resident": (
        "column bytes currently allocated by resident series"
    ),
    "foremast_ingest_receiver_lag_seconds": (
        "now minus the newest sample timestamp of the latest push"
    ),
    "foremast_ingest_requests": (
        "push requests decoded by the receiver, by wire codec "
        "(json=compat codec, binary=columnar frame)"
    ),
    "foremast_ingest_stage_seconds": (
        "wall-clock seconds per receiver pipeline stage "
        "(read/decompress/decode/apply), by wire codec"
    ),
    "foremast_mesh_members": (
        "live mesh members (fresh leases, including this worker), by "
        "lifecycle state (active/draining/joining)"
    ),
    "foremast_mesh_rebalances": (
        "hash-ring swaps after membership changes"
    ),
    "foremast_handoff_state": (
        "ring series and fit entries moved by planned handoff, by "
        "payload kind and direction"
    ),
    "foremast_handoff_transfers": (
        "planned-handoff transfer outcomes by role (send/receive); "
        "failed/torn/rejected degrade to cold refits, never a wedge"
    ),
    "foremast_mesh_redirect_hints": (
        "receiver responses pointing a pusher at a series' owner"
    ),
    "foremast_mesh_claim_docs": (
        "documents seen by the partition claim filter (owned/skipped)"
    ),
    "foremast_chaos_injections": (
        "faults injected by the active FOREMAST_CHAOS_PLAN, by "
        "dependency edge and fault kind"
    ),
    "foremast_breaker_state": (
        "circuit-breaker state per dependency edge "
        "(0=closed, 1=half-open, 2=open)"
    ),
    "foremast_breaker_transitions": (
        "circuit-breaker state transitions, by edge and target state"
    ),
    "foremast_breaker_short_circuits": (
        "calls rejected without touching the dependency (breaker open)"
    ),
    "foremast_degraded_docs": (
        "documents handled by degradation machinery (released "
        "un-judged, buffered/replayed/dropped write-backs), by reason"
    ),
    "foremast_degraded_events": (
        "non-per-document degradation events (claim errors survived, "
        "receiver sheds, replay flushes), by edge and action"
    ),
    "foremast_tenant_shed": (
        "pushes shed by per-tenant ingest admission (429 + "
        "Retry-After), charged to the flooding tenant; label bounded "
        "by FOREMAST_TENANT_LABEL_MAX + the `other` overflow bucket"
    ),
    "foremast_tenant_evictions": (
        "ring series / arena rows evicted under a tenant's budget "
        "envelope, charged to the tenant causing the pressure; label "
        "bounded by FOREMAST_TENANT_LABEL_MAX + `other`"
    ),
    "foremast_tenant_claims": (
        "documents claimed for judgment, by owning tenant; label "
        "bounded by FOREMAST_TENANT_LABEL_MAX + `other`"
    ),
    "foremast_tenant_ring_bytes": (
        "ring TSDB column bytes currently resident, by owning tenant; "
        "label bounded by FOREMAST_TENANT_LABEL_MAX + `other`"
    ),
    "foremast_snapshot_discards": (
        "state discarded during snapshot restore, by reason"
    ),
    "foremast_snapshot_restored_series": (
        "ring series restored by the last startup restore"
    ),
    "foremast_snapshot_restored_samples": (
        "ring samples restored by the last startup restore"
    ),
    "foremast_snapshot_restored_fits": (
        "fit-cache entries restored (lazily rehydrated on first claim)"
    ),
    "foremast_snapshot_writes": (
        "ring snapshot passes completed (all shards, atomic rename)"
    ),
    "foremast_snapshot_age_seconds": (
        "seconds since the last completed ring snapshot"
    ),
}


def lint_registry(registry) -> list[str]:
    """Walk a CollectorRegistry and return naming/label violations."""
    problems: list[str] = []
    for family in registry.collect():
        name = family.name
        if not NAME_RE.match(name):
            problems.append(
                f"family {name!r} does not match foremast(brain)?_[a-z0-9_]+"
            )
        labels: set[str] = set()
        for sample in family.samples:
            labels.update(sample.labels)
        labels -= _SYNTHETIC_LABELS
        allowed = ALLOWED_LABELS.get(name)
        if allowed is not None:
            if labels - allowed:
                problems.append(
                    f"family {name!r} carries undocumented labels "
                    f"{sorted(labels - allowed)} (allowed: {sorted(allowed)})"
                )
        else:
            for lb in labels:
                if not LABEL_RE.match(lb):
                    problems.append(
                        f"family {name!r} label {lb!r} does not match "
                        "[a-z][a-z0-9_]*"
                    )
    return problems


def default_registry_families():
    """Instantiate every standard family on a fresh registry — the set a
    deployed worker+service+controller exports — and exercise each once
    so every label combination appears in the exposition."""
    from prometheus_client import CollectorRegistry

    from foremast_tpu.observe.gauges import BrainGauges, WorkerMetrics
    from foremast_tpu.observe.spans import Tracer, counter

    registry = CollectorRegistry()
    gauges = BrainGauges(registry=registry)
    gauges.publish("error5xx", "ns", "app", upper=1.0, lower=0.0, anomaly_value=2.0)
    metrics = WorkerMetrics(registry=registry)
    metrics.observe_doc("completed_health", 1)
    metrics.observe_arena({"hits": 1, "misses": 1, "evictions": 0, "fallbacks": 0})
    metrics.tick_seconds.observe(0.01)
    for kind in ("univariate", "bivariate", "lstm"):
        metrics.fast_docs.labels(kind=kind).inc()
    for path in ("micro", "sweep"):
        metrics.verdict_latency.labels(path=path, tenant="default").observe(
            0.1
        )
    metrics.microtick_docs.inc()
    for path in ("slow", "warm"):
        metrics.pipeline_idle.labels(path=path).inc(0.0)
        metrics.pipeline_overlap.labels(path=path).set(0.0)
        metrics.pipeline_queue.labels(path=path).set(0)
    metrics.sweep_slices.inc()
    for action in ("promoted", "inflight_requeued", "microtick"):
        metrics.sweep_preempt.labels(action=action).inc()
    tracer = Tracer(service="lint", registry=registry, trace_dir=None)
    from foremast_tpu.observe.spans import TICK_STAGES

    for stage in TICK_STAGES:
        with tracer.span(f"lint.{stage}", stage=stage):
            pass
    counter(
        "foremast_service_requests_total",
        "service requests by route and status code",
        ("route", "code"),
        registry,
    ).labels(route="/healthz", code="200").inc()
    counter(
        "foremast_controller_transitions_total",
        "monitor phase transitions observed by the controller",
        ("phase",),
        registry,
    ).labels(phase="Healthy").inc()
    # ingest plane: exercise every outcome so each label value appears
    from foremast_tpu.ingest import IngestCollector, RingStore, WireStats

    ring = RingStore(budget_bytes=1 << 20, shards=1)
    ring.push("lint_series", [60, 120], [1.0, 2.0], start=0.0, now=180.0)
    ring.query("lint_series", 0.0, 120.0, now=180.0)  # hit
    ring.query("lint_absent", 0.0, 120.0, now=180.0)  # miss
    wire = WireStats()  # both codecs, every stage label
    for codec in ("json", "binary"):
        wire.record(
            codec,
            {"read": 0.001, "decompress": 0.0, "decode": 0.002,
             "apply": 0.001},
            samples=2,
            ok=True,
        )
    registry.register(IngestCollector(ring, wire=wire))
    # worker mesh: a one-member node with both claim outcomes exercised
    from foremast_tpu.jobs.models import Document
    from foremast_tpu.jobs.store import InMemoryStore
    from foremast_tpu.mesh import MeshCollector, MeshNode, Membership, MeshRouter

    membership = Membership(InMemoryStore(), "lint-worker", lease_seconds=60)
    node = MeshNode(membership, MeshRouter(membership))
    node.start()
    node.claim_filter(Document(id="lint-doc", app_name="lint-app"))
    node.claim_counts["skipped"] += 1  # both label values must appear
    registry.register(MeshCollector(node))
    # chaos plane: a plan with one fired rule, a breaker walked through
    # its states, and one counter of each degradation family
    from foremast_tpu.chaos import ChaosCollector, Degradation, FaultPlan

    plan = FaultPlan(
        rules=({"edge": "lint", "error_rate": 1.0},), seed=1
    ).activate()
    try:
        plan.edge("lint").perturb("lint-op")
    except ConnectionError:
        pass
    degrade = Degradation(chaos_plan=plan)
    br = degrade.breakers.get("lint")
    for _ in range(br.failure_threshold):
        br.record_failure()
    degrade.stats.count_docs("deadline_released")
    degrade.stats.count_event("receiver", "shed")
    registry.register(ChaosCollector(degrade))
    # reactive plane: dirty-set traffic + the watch-stream families
    from foremast_tpu.reactive import (
        DirtySet,
        ReactiveCollector,
        WatchStreamMetrics,
    )

    dirty = DirtySet(max_keys=2)
    dirty.mark_series('up{app="lint"}')
    dirty.mark_series('up{app="lint"}')  # coalesced
    dirty.mark("lint-requeue", 1.0, requeue=True)
    dirty.mark("lint-extra")  # overflows max_keys=2: dropped
    dirty.count("unattributed")
    registry.register(ReactiveCollector(dirty))
    # multi-tenant QoS plane: a two-tenant registry with one nonzero
    # sample per family so every foremast_tenant_* series is exported
    from foremast_tpu.tenant import (
        TenantAccounting,
        TenantCollector,
        TenantRegistry,
        TenantSpec,
    )

    tenancy = TenantRegistry(
        {
            "default": TenantSpec(name="default"),
            "lint": TenantSpec(name="lint", weight=2.0),
        }
    )
    acct = TenantAccounting(tenancy)
    acct.count_shed("lint")
    acct.count_eviction("lint")
    acct.count_claims("default")
    acct.add_ring_bytes("lint", 1024)
    registry.register(TenantCollector(acct))
    ws = WatchStreamMetrics(registry=registry)
    for etype in ("added", "modified", "deleted", "error"):
        ws.events.labels(type=etype).inc()
    for reason in ("gone", "stall", "error", "end"):
        ws.restarts.labels(reason=reason).inc()
    return registry


def main() -> int:
    problems = lint_registry(default_registry_families())
    if problems:
        for p in problems:
            print(f"metrics-lint: {p}")
        return 1
    print("metrics-lint: all exported families conform")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
