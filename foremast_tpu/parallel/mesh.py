"""Device-mesh construction and sharding helpers.

The reference's only "parallelism" is N shared-nothing brain pods polling a
queue (SURVEY.md section 2.8). The TPU-native replacement is a 2-D
`jax.sharding.Mesh`:

  * `data`  — the (service x metric) batch axis: pure DP over ICI; the
    scoring program partitions with zero collectives (embarrassingly
    parallel windows), matching "batched scoring: one jitted program
    scoring 100k windows as array dims in HBM";
  * `model` — tensor-parallel axis for the learned detectors (LSTM gate
    dimension) and the sequence-parallel axis for long-window scans.

Works identically on real TPU slices and on virtual CPU devices
(`xla_force_host_platform_device_count`), which is how multi-chip tests and
the driver's `dryrun_multichip` run without hardware.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(
    n_data: int | None = None,
    n_model: int = 1,
    devices: list | None = None,
) -> Mesh:
    """Build a (data, model) mesh over the available devices.

    Defaults to all devices on the data axis (the scoring engine's natural
    layout: DP over windows). `n_data=None` derives it from the device
    count / n_model.
    """
    devs = devices if devices is not None else jax.devices()
    if n_data is None:
        n_data = len(devs) // n_model
    need = n_data * n_model
    if need > len(devs):
        raise ValueError(f"mesh {n_data}x{n_model} needs {need} devices, have {len(devs)}")
    grid = np.asarray(devs[:need]).reshape(n_data, n_model)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def data_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """Shard the leading (batch) axis over `data`, replicate the rest."""
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * (ndim - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_leading(tree, mesh: Mesh):
    """device_put every array in a pytree with its leading axis on `data`."""
    return jax.tree.map(
        lambda a: jax.device_put(a, data_sharding(mesh, np.ndim(a))), tree
    )


def pad_to_multiple(n: int, k: int) -> int:
    """Smallest multiple of k >= n (batch padding for even sharding)."""
    return ((n + k - 1) // k) * k
