"""Device-mesh construction and sharding helpers.

The reference's only "parallelism" is N shared-nothing brain pods polling a
queue (SURVEY.md section 2.8). The TPU-native replacement is a 2-D
`jax.sharding.Mesh`:

  * `data`  — the (service x metric) batch axis: pure DP over ICI; the
    scoring program partitions with zero collectives (embarrassingly
    parallel windows), matching "batched scoring: one jitted program
    scoring 100k windows as array dims in HBM";
  * `model` — tensor-parallel axis for the learned detectors (LSTM gate
    dimension) and the sequence-parallel axis for long-window scans.

Works identically on real TPU slices and on virtual CPU devices
(`xla_force_host_platform_device_count`), which is how multi-chip tests and
the driver's `dryrun_multichip` run without hardware.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(
    n_data: int | None = None,
    n_model: int = 1,
    devices: list | None = None,
) -> Mesh:
    """Build a (data, model) mesh over the available devices.

    Defaults to all devices on the data axis (the scoring engine's natural
    layout: DP over windows). `n_data=None` derives it from the device
    count / n_model.
    """
    devs = devices if devices is not None else jax.devices()
    if n_data is None:
        n_data = len(devs) // n_model
    need = n_data * n_model
    if need > len(devs):
        raise ValueError(f"mesh {n_data}x{n_model} needs {need} devices, have {len(devs)}")
    grid = np.asarray(devs[:need]).reshape(n_data, n_model)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def data_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """Shard the leading (batch) axis over `data`, replicate the rest."""
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * (ndim - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_leading(tree, mesh: Mesh):
    """device_put every array in a pytree with its leading axis on `data`."""
    return jax.tree.map(
        lambda a: jax.device_put(a, data_sharding(mesh, np.ndim(a))), tree
    )


def pad_to_multiple(n: int, k: int) -> int:
    """Smallest multiple of k >= n (batch padding for even sharding)."""
    return ((n + k - 1) // k) * k


# ---------------------------------------------------------------------------
# Multi-host (the reference's NCCL/MPI-equivalent layer, SURVEY.md §2.8:
# its distribution is shared-nothing pods over HTTP/ES; ours is XLA
# collectives over ICI within a slice and DCN across slices)
# ---------------------------------------------------------------------------


def _distributed_initialized() -> bool:
    """`jax.distributed.is_initialized()` with a 0.4.x fallback (the
    accessor only gained the public spelling in later jax; on 0.4.x the
    global client being set IS the initialized marker)."""
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None:
        return bool(is_init())
    from jax._src import distributed as _dist

    return _dist.global_state.client is not None


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Initialize jax.distributed for multi-host meshes.

    No-op (returns False) when single-process: explicit args win, then the
    standard cluster envs (JAX_COORDINATOR_ADDRESS / NUM_PROCESSES /
    PROCESS_ID, or a TPU pod's metadata which jax auto-detects). Safe to
    call twice. After this, `jax.devices()` is global and `make_mesh`
    spans all hosts.
    """
    import os

    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    env_np = os.environ.get("JAX_NUM_PROCESSES")
    num_processes = num_processes if num_processes is not None else (
        int(env_np) if env_np else None
    )
    env_pid = os.environ.get("JAX_PROCESS_ID")
    process_id = process_id if process_id is not None else (
        int(env_pid) if env_pid else None
    )
    if coordinator_address is None and num_processes is None:
        return False  # single-host: nothing to coordinate
    if _distributed_initialized():
        # idempotent: a prior initialize (ours, the runtime's TPU-pod
        # auto-init, or an embedding application's) wins. Re-calling
        # jax.distributed.initialize here would raise the generic
        # "must be called before any JAX calls" error, not a clean
        # already-initialized signal.
        return True
    # CPU multi-process needs an explicit collectives backend on older
    # jax (0.4.x): without gloo, cross-process programs raise
    # "Multiprocess computations aren't implemented on the CPU backend".
    # Set unconditionally BEFORE backends initialize (probing the
    # backend here would itself initialize it); the option only affects
    # the CPU client and disappears once the default grows collectives.
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # noqa: BLE001 — newer jax handles this itself
        pass
    # a connect or barrier failure surfaces to the caller — swallowing it
    # would leave this process on a local-only "global" mesh while its
    # peers hang at the init barrier
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def make_global_mesh(n_model: int = 1) -> Mesh:
    """A (data, model) mesh over ALL hosts' devices.

    Axis order puts `data` outermost so the batch axis crosses DCN (pure
    DP needs no inter-chip traffic there — each host scores its slice and
    only verdict gathers cross hosts) while `model` stays inside a host's
    ICI domain where tensor-parallel collectives are cheap. This is the
    scaling-book recipe: collectives ride ICI, DCN only sees the
    embarrassingly-parallel axis.
    """
    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    if n_model > 1:
        local = [d for d in devs if d.process_index == devs[0].process_index]
        # groups of n_model consecutive devices form the model axis
        # (row-major reshape), so each host's device count must divide
        # cleanly or a group would straddle hosts and its collectives
        # would ride DCN
        if n_model > len(local) or len(local) % n_model != 0:
            raise ValueError(
                f"model axis {n_model} must evenly divide the {len(local)} "
                "devices of a single host — tensor parallelism must stay "
                "inside ICI"
            )
    return make_mesh(n_model=n_model, devices=devs)
