"""Device-mesh construction and sharding helpers.

The reference's only "parallelism" is N shared-nothing brain pods polling a
queue (SURVEY.md section 2.8). The TPU-native replacement is a 2-D
`jax.sharding.Mesh`:

  * `data`  — the (service x metric) batch axis: pure DP over ICI; the
    scoring program partitions with zero collectives (embarrassingly
    parallel windows), matching "batched scoring: one jitted program
    scoring 100k windows as array dims in HBM";
  * `model` — tensor-parallel axis for the learned detectors (LSTM gate
    dimension) and the sequence-parallel axis for long-window scans.

Works identically on real TPU slices and on virtual CPU devices
(`xla_force_host_platform_device_count`), which is how multi-chip tests and
the driver's `dryrun_multichip` run without hardware.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(
    n_data: int | None = None,
    n_model: int = 1,
    devices: list | None = None,
) -> Mesh:
    """Build a (data, model) mesh over the available devices.

    Defaults to all devices on the data axis (the scoring engine's natural
    layout: DP over windows). `n_data=None` derives it from the device
    count / n_model.
    """
    devs = devices if devices is not None else jax.devices()
    if n_data is None:
        n_data = len(devs) // n_model
    need = n_data * n_model
    if need > len(devs):
        raise ValueError(f"mesh {n_data}x{n_model} needs {need} devices, have {len(devs)}")
    grid = np.asarray(devs[:need]).reshape(n_data, n_model)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def data_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """Shard the leading (batch) axis over `data`, replicate the rest."""
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * (ndim - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_leading(tree, mesh: Mesh):
    """device_put every array in a pytree with its leading axis on `data`."""
    return jax.tree.map(
        lambda a: jax.device_put(a, data_sharding(mesh, np.ndim(a))), tree
    )


def pad_to_multiple(n: int, k: int) -> int:
    """Smallest multiple of k >= n (batch padding for even sharding)."""
    return ((n + k - 1) // k) * k


def shard_rows_take(tree, rows, mesh: Mesh):
    """Device-local row gather from data-axis-sharded arena state
    (ISSUE 19). `tree` leaves are [capacity, ...] arrays block-sharded
    over DATA_AXIS (capacity = n_data * cap_s); `rows` [B] holds LOCAL
    (per-shard) row indices with its leading axis sharded over the same
    data blocks — the arena's block placement rule guarantees position
    i's row lives in the device holding batch position i. Expressed as a
    shard_map (composes inside jit) so each device takes rows from its
    OWN capacity block and XLA can never insert a collective for the
    gather: a plain global `jnp.take` on a sharded operand is free to
    all-gather it, which is exactly the cross-chip leg the sharded
    arena exists to delete."""
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map

    spec = jax.tree.map(
        lambda a: P(DATA_AXIS, *([None] * (np.ndim(a) - 1))), tree
    )
    return shard_map(
        lambda rs, t: jax.tree.map(lambda a: jnp.take(a, rs, axis=0), t),
        mesh=mesh,
        in_specs=(P(DATA_AXIS), spec),
        out_specs=spec,
        check_rep=False,
    )(rows, tree)


# ---------------------------------------------------------------------------
# Worker device mesh (ISSUE 13): every BrainWorker's judge runs over a
# local device mesh by default — FOREMAST_DEVICE_MESH selects the shape.
# ---------------------------------------------------------------------------


def device_mesh_spec(env: dict | None = None) -> tuple[int | None, int] | None:
    """Parse `FOREMAST_DEVICE_MESH` (+ `FOREMAST_DEVICE_MESH_MODEL`).

    Returns (n_data, n_model) for `make_mesh`, or None when the device
    mesh is disabled. Accepted spellings:

      * unset / "auto" — all local devices on the data axis (n_data=None
        derives it from the device count; on a stock CPU host that is a
        1-device mesh, i.e. the identity);
      * "0" / "off"    — disabled: no mesh placement at all (the
        pre-ISSUE-13 single-device behavior);
      * "N"            — N devices on the data axis;
      * "NxM"          — explicit (data, model) grid (the axis override;
        `FOREMAST_DEVICE_MESH_MODEL` sets M for the other spellings).

    Malformed values warn and fall back to "auto" — a templated env must
    never kill worker startup (the FOREMAST_MICROTICK_* precedent)."""
    import logging
    import os

    e = os.environ if env is None else env
    raw = (e.get("FOREMAST_DEVICE_MESH") or "auto").strip().lower()
    n_model = 1
    raw_model = (e.get("FOREMAST_DEVICE_MESH_MODEL") or "").strip()
    if raw_model:
        try:
            n_model = max(1, int(raw_model))
        except ValueError:
            logging.getLogger("foremast_tpu.mesh").warning(
                "FOREMAST_DEVICE_MESH_MODEL=%r unparseable; using 1",
                raw_model,
            )
    if raw in ("0", "off", "none", "disabled"):
        return None
    if raw in ("auto", ""):
        return (None, n_model)
    try:
        if "x" in raw:
            d, _, m = raw.partition("x")
            di, mi = int(d), int(m)
            # zero on either axis means OFF, matching the bare "0"
            # spelling — a templated "{data}x{model}" with data=0 must
            # disable, not clamp up to a 1-wide axis
            if di <= 0 or mi <= 0:
                return None
            return (di, mi)
        return (max(1, int(raw)), n_model)
    except ValueError:
        logging.getLogger("foremast_tpu.mesh").warning(
            "FOREMAST_DEVICE_MESH=%r unparseable; using 'auto'", raw
        )
        return (None, n_model)


def worker_device_mesh(env: dict | None = None) -> Mesh | None:
    """The mesh a BrainWorker's judge should span, from the env.

    None means disabled (plain single-device judge). A resolved
    1-device mesh is returned as None too: `device_put` with a 1-device
    NamedSharding is semantically the identity, so the worker skips the
    ShardedJudge wrapper entirely rather than paying hook overhead for
    placement that changes nothing.

    Multi-controller processes always get None: a pod's judge must span
    the GLOBAL mesh (cli --sharded builds it explicitly before the
    worker exists) — an env-resolved LOCAL mesh on each process would
    hand one SPMD program differently-placed operands per host."""
    spec = device_mesh_spec(env)
    if spec is None:
        return None
    if jax.process_count() > 1:
        return None
    n_devs = len(jax.devices())
    n_data, n_model = spec
    if n_data is None:
        n_data = max(1, n_devs // n_model)
    if n_data * n_model > n_devs:
        # infeasible grid (a fleet-templated knob on a smaller host):
        # warn and fall back to the all-local auto mesh — the same
        # never-kill-startup contract as the spec parser above
        import logging

        logging.getLogger("foremast_tpu.mesh").warning(
            "FOREMAST_DEVICE_MESH %dx%d needs %d devices, have %d; "
            "falling back to the all-local auto mesh",
            n_data, n_model, n_data * n_model, n_devs,
        )
        n_data, n_model = n_devs, 1
    if n_data * n_model <= 1:
        return None
    return make_mesh(n_data=n_data, n_model=n_model)


def assert_partitioned(arr, n_data: int) -> None:
    """In-run proof the leading batch axis is actually partitioned: every
    addressable shard must hold exactly rows/n_data rows (ISSUE 13
    acceptance — 'sharding is placement' is only true if the placement
    happened; a silently-replicated batch would still be correct and
    ~n_data times slower, which is exactly the failure mode an assert
    exists for). O(#local devices) host work per call, no data read."""
    rows = arr.shape[0]
    if rows % n_data != 0:
        raise AssertionError(
            f"batch rows {rows} not a multiple of the data axis {n_data}"
        )
    shards = arr.addressable_shards
    want = rows // n_data
    # Iterates addressable_shards and reads shard SHAPES only ("no
    # data read" is this assert's contract).
    # foremast: ignore[device-flow]
    got = sorted(s.data.shape[0] for s in shards)
    n_local = len(shards)
    if any(g != want for g in got):
        raise AssertionError(
            f"batch leading axis not partitioned over the mesh: "
            f"{n_local} local shards of rows {got[:4]}..., want "
            f"{want} (= {rows}/{n_data}) each"
        )


# ---------------------------------------------------------------------------
# Multi-host (the reference's NCCL/MPI-equivalent layer, SURVEY.md §2.8:
# its distribution is shared-nothing pods over HTTP/ES; ours is XLA
# collectives over ICI within a slice and DCN across slices)
# ---------------------------------------------------------------------------


def _distributed_initialized() -> bool:
    """`jax.distributed.is_initialized()` with a 0.4.x fallback (the
    accessor only gained the public spelling in later jax; on 0.4.x the
    global client being set IS the initialized marker)."""
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None:
        return bool(is_init())
    from jax._src import distributed as _dist

    return _dist.global_state.client is not None


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Initialize jax.distributed for multi-host meshes.

    No-op (returns False) when single-process: explicit args win, then the
    standard cluster envs (JAX_COORDINATOR_ADDRESS / NUM_PROCESSES /
    PROCESS_ID, or a TPU pod's metadata which jax auto-detects). Safe to
    call twice. After this, `jax.devices()` is global and `make_mesh`
    spans all hosts.
    """
    import os

    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    env_np = os.environ.get("JAX_NUM_PROCESSES")
    num_processes = num_processes if num_processes is not None else (
        int(env_np) if env_np else None
    )
    env_pid = os.environ.get("JAX_PROCESS_ID")
    process_id = process_id if process_id is not None else (
        int(env_pid) if env_pid else None
    )
    if coordinator_address is None and num_processes is None:
        return False  # single-host: nothing to coordinate
    if _distributed_initialized():
        # idempotent: a prior initialize (ours, the runtime's TPU-pod
        # auto-init, or an embedding application's) wins. Re-calling
        # jax.distributed.initialize here would raise the generic
        # "must be called before any JAX calls" error, not a clean
        # already-initialized signal.
        return True
    # CPU multi-process needs an explicit collectives backend on older
    # jax (0.4.x): without gloo, cross-process programs raise
    # "Multiprocess computations aren't implemented on the CPU backend".
    # Set unconditionally BEFORE backends initialize (probing the
    # backend here would itself initialize it); the option only affects
    # the CPU client and disappears once the default grows collectives.
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # noqa: BLE001 — newer jax handles this itself
        pass
    # a connect or barrier failure surfaces to the caller — swallowing it
    # would leave this process on a local-only "global" mesh while its
    # peers hang at the init barrier
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def make_global_mesh(n_model: int = 1) -> Mesh:
    """A (data, model) mesh over ALL hosts' devices.

    Axis order puts `data` outermost so the batch axis crosses DCN (pure
    DP needs no inter-chip traffic there — each host scores its slice and
    only verdict gathers cross hosts) while `model` stays inside a host's
    ICI domain where tensor-parallel collectives are cheap. This is the
    scaling-book recipe: collectives ride ICI, DCN only sees the
    embarrassingly-parallel axis.
    """
    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    if n_model > 1:
        local = [d for d in devs if d.process_index == devs[0].process_index]
        # groups of n_model consecutive devices form the model axis
        # (row-major reshape), so each host's device count must divide
        # cleanly or a group would straddle hosts and its collectives
        # would ride DCN
        if n_model > len(local) or len(local) % n_model != 0:
            raise ValueError(
                f"model axis {n_model} must evenly divide the {len(local)} "
                "devices of a single host — tensor parallelism must stay "
                "inside ICI"
            )
    return make_mesh(n_model=n_model, devices=devs)
