"""Mesh construction, sharded batch scoring, sequence parallelism."""

from foremast_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    data_sharding,
    init_distributed,
    make_global_mesh,
    make_mesh,
    pad_to_multiple,
    replicated,
    shard_leading,
)
from foremast_tpu.parallel.batch import (
    ShardedJudge,
    pad_batch,
    shard_batch,
    throughput_batch,
)
from foremast_tpu.parallel.distributed import (
    LeaderSource,
    LeaderStore,
    PodWorker,
    broadcast_obj,
)
from foremast_tpu.parallel.seqparallel import (
    score_time_sharded,
    sharded_ewma,
    sharded_linear_scan,
    sharded_masked_moments,
    sharded_masked_stats,
    sharded_phase_means,
)

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "data_sharding",
    "init_distributed",
    "make_global_mesh",
    "make_mesh",
    "pad_to_multiple",
    "replicated",
    "shard_leading",
    "ShardedJudge",
    "LeaderSource",
    "LeaderStore",
    "PodWorker",
    "broadcast_obj",
    "pad_batch",
    "shard_batch",
    "throughput_batch",
    "score_time_sharded",
    "sharded_ewma",
    "sharded_linear_scan",
    "sharded_masked_moments",
    "sharded_masked_stats",
    "sharded_phase_means",
]
