"""Pod-spanning worker mode: leader claim + broadcast + SPMD judgment.

The reference scales the brain by running N shared-nothing pods against
the shared ES store (`docs/guides/design.md:35-43`); that mode works here
unchanged (independent `BrainWorker`s, CAS claims — no jax.distributed
involved). This module adds the mode the reference cannot express: ONE
logical worker spanning every host of a multi-host slice, its judgment
partitioned over the global (data, model) mesh.

The multi-controller contract is that every process must execute the
same program over the same global batch, while exactly one process may
talk to the outside world. The adapters enforce that split:

  * `broadcast_obj`   — pickle-broadcast any host object from process 0
    (two `broadcast_one_to_all` collectives: size, then payload);
  * `LeaderStore`     — JobStore adapter: process 0 claims/writes against
    the real store and broadcasts the claim set, so all processes tick
    over IDENTICAL documents; follower writes are no-ops;
  * `LeaderSource`    — MetricSource adapter: process 0 fetches, results
    broadcast. `concurrent_fetch = False` is load-bearing: fetches are
    collectives, so their ORDER must be identical on every process — a
    thread pool would interleave them nondeterministically and deadlock;
  * `PodWorker`       — BrainWorker whose tick clock is broadcast (the
    settled-history admission gates compare against `now`; divergent
    clocks near a boundary would route the same doc down different code
    paths on different processes, desynchronizing the SPMD program).

Determinism argument for everything else: given identical docs, series,
clock and caches, the worker's control flow is a pure function, so fit
caches, gap anchors and arena row assignment evolve identically on every
process — which is what lets the arena stay REPLICATED over the mesh
(see engine/arena.py `sharding`) with each process scattering identical
rows.
"""

from __future__ import annotations

import logging
import os
import pickle
import threading
import time

import jax
import numpy as np

from foremast_tpu.jobs.store import JobStore
from foremast_tpu.jobs.worker import BrainWorker
from foremast_tpu.metrics.source import MetricSource

log = logging.getLogger("foremast_tpu.parallel.distributed")


def is_leader() -> bool:
    return jax.process_index() == 0


class PodCollectiveTimeout(RuntimeError):
    """A pod broadcast did not complete within the watchdog budget —
    a peer process (usually the leader) is dead or wedged. Raised so
    the process FAILS FAST instead of hanging inside a collective
    forever: the tick aborts, the process exits, and the in-flight
    claims age out into another worker via MAX_STUCK_IN_SECONDS
    (docs/operations.md, "Pod-mode failure and recovery")."""


def _pod_timeout_seconds() -> float | None:
    raw = os.environ.get("FOREMAST_POD_TIMEOUT_SECONDS", "300")
    try:
        t = float(raw)
    except ValueError:
        return 300.0
    return t if t > 0 else None


def _broadcast_raw(obj=None):
    from jax.experimental import multihost_utils as mhu

    leader = is_leader()
    if leader:
        payload = np.frombuffer(pickle.dumps(obj), np.uint8)
        size = np.array([payload.size], np.int64)
    else:
        payload = None
        size = np.zeros(1, np.int64)
    # np.asarray + explicit dtype restore: depending on jax/collectives
    # version the broadcast returns the payload UPCAST to a wider
    # integer type (observed with 0.4.x gloo CPU collectives: uint8 in,
    # int out — element values correct, so `.tobytes()` silently
    # interleaves zero bytes and the pickle stream corrupts)
    size = np.asarray(mhu.broadcast_one_to_all(size))
    buf = payload if leader else np.zeros(int(size[0]), np.uint8)
    buf = np.asarray(mhu.broadcast_one_to_all(buf)).astype(np.uint8)
    return obj if leader else pickle.loads(buf.tobytes())


class _BroadcastWorker:
    """ONE persistent daemon thread executing pod broadcasts in order.

    Per-call thread spawn would land on the per-fetch hot path (a
    fleet-cold pod tick issues tens of thousands of broadcasts); a
    single worker keeps the watchdog at one Event wait per call and —
    unlike ThreadPoolExecutor — never registers an atexit join, so a
    thread wedged inside a dead peer's collective cannot block the
    fail-fast process exit the watchdog exists to guarantee."""

    def __init__(self):
        import queue

        self._tasks: "queue.Queue" = queue.Queue()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="foremast-pod-broadcast"
        )
        self._thread.start()

    def _loop(self):
        while True:
            obj, box, done = self._tasks.get()
            try:
                box.append(("ok", _broadcast_raw(obj)))
            except BaseException as e:  # noqa: BLE001 — re-raised on the caller
                box.append(("err", e))
            finally:
                done.set()

    def run(self, obj, timeout: float):
        done = threading.Event()
        box: list = []
        self._tasks.put((obj, box, done))
        if not done.wait(timeout):
            # the worker stays wedged in the dead collective; that is
            # fine — the contract is that the caller now aborts the
            # tick and the process EXITS (daemon thread, no atexit join)
            raise PodCollectiveTimeout(
                f"pod broadcast incomplete after {timeout:.0f}s — a peer "
                "process is dead or wedged; aborting the tick so "
                "in-flight claims can age out (MAX_STUCK_IN_SECONDS "
                "takeover)"
            )
        kind, val = box[0]
        if kind == "err":
            raise val
        return val


_broadcast_worker: _BroadcastWorker | None = None


def broadcast_obj(obj=None):
    """Broadcast a picklable host object from process 0 to every process.

    Followers pass anything (ignored) and receive the leader's object.
    Single-process: returns `obj` unchanged with zero collectives.

    Every pod broadcast runs under a WATCHDOG
    (`FOREMAST_POD_TIMEOUT_SECONDS`, default 300; `0` disables): the
    runtime's own failure detection takes minutes to notice a dead
    coordinator, and a follower blocked inside a collective would
    otherwise hang the pod silently while its claims sit un-aged on the
    store. On timeout `PodCollectiveTimeout` propagates — the worker
    tick aborts, the process exits, and the reference's stuck-claim
    takeover recovers the in-flight documents (VERDICT r5 #6). Only the
    tick thread may call this (collective ORDER is load-bearing), so
    the single persistent worker thread preserves sequencing."""
    if jax.process_count() == 1:
        return obj
    timeout = _pod_timeout_seconds()
    if timeout is None:
        return _broadcast_raw(obj)
    global _broadcast_worker
    if _broadcast_worker is None:
        _broadcast_worker = _BroadcastWorker()
    return _broadcast_worker.run(obj, timeout)


class LeaderStore(JobStore):
    """Only process 0 talks to the real store; claims are broadcast.

    Followers construct this with `inner=None` — they never need a real
    connection, which also means ES credentials only have to exist on
    the leader."""

    def __init__(self, inner: JobStore | None):
        if is_leader() and inner is None:
            raise ValueError("process 0 needs the real store")
        self.inner = inner

    def claim(self, worker_id, max_stuck_seconds, limit=64, claim_filter=None):
        # a leader-side claim failure must CROSS the broadcast (ISSUE 9):
        # raising before broadcast_obj would leave every follower blocked
        # in the collective while the leader's worker loop moved on —
        # silent broadcast misalignment, worse than the crash it used to
        # be. The error ships as a marker and re-raises on every process
        # with its transience preserved, so the worker's claim
        # degradation (transient -> empty tick) stays pod-consistent.
        #
        # `claim_filter` is the mesh-of-pods seam (ISSUE 13): only the
        # leader holds a worker-mesh seat, so only it passes a filter —
        # the partition-filtered claim set then broadcasts like any
        # other, and followers (whose kwarg is always None) tick over
        # the identical documents. Partitioning cannot desync the pod
        # because it is applied BEFORE the broadcast, never after.
        if is_leader():
            try:
                kw = (
                    {"claim_filter": claim_filter}
                    if claim_filter is not None
                    else {}
                )
                docs = self.inner.claim(
                    worker_id, max_stuck_seconds, limit, **kw
                )
            except Exception as e:  # noqa: BLE001 — must cross processes
                from foremast_tpu.chaos.degrade import is_transient_error

                docs = _ClaimError(repr(e), is_transient_error(e))
        else:
            docs = None
        docs = broadcast_obj(docs)
        if isinstance(docs, _ClaimError):
            if docs.transient:
                raise ConnectionError(docs.msg)
            raise RuntimeError(docs.msg)
        return docs

    def update(self, doc):
        if is_leader():
            return self.inner.update(doc)
        return doc

    def update_many(self, docs):
        if is_leader():
            self.inner.update_many(docs)

    def create(self, doc):
        if not is_leader():
            raise RuntimeError("create() is leader-only in pod mode")
        return self.inner.create(doc)

    def get(self, doc_id):
        return broadcast_obj(
            self.inner.get(doc_id) if is_leader() else None
        )

    def list_open(self):
        return broadcast_obj(
            self.inner.list_open() if is_leader() else None
        )

    def count_open(self):
        # varz-only (worker /debug/state), called from the observe-server
        # probe thread — it must NOT enter a collective: followers never
        # serve debug_state, so a broadcast here would have no matching
        # participants and hang the pod on the first scrape. Leader
        # answers locally; followers report 0 (they hold no queue).
        return self.inner.count_open() if is_leader() else 0


class LeaderSource(MetricSource):
    """Only process 0 performs metric fetches; series are broadcast.

    Every fetch is a collective, so ordering must be deterministic —
    `concurrent_fetch = False` forces the worker's serial fetch loop
    (doc order is broadcast-identical, alias order is config order).
    A leader-side fetch error must not desynchronize the cluster: the
    exception itself is broadcast and re-raised on every process, so
    all of them take the preprocess-failure branch together."""

    concurrent_fetch = False

    def __init__(self, inner: MetricSource | None):
        if is_leader() and inner is None:
            raise ValueError("process 0 needs the real source")
        self.inner = inner

    def fetch(self, url: str):
        if is_leader():
            try:
                out = self.inner.fetch(url)
            except Exception as e:  # noqa: BLE001 — must cross processes
                out = _FetchError(repr(e))
        else:
            out = None
        out = broadcast_obj(out)
        if isinstance(out, _FetchError):
            raise RuntimeError(out.msg)
        return out


class _FetchError:
    def __init__(self, msg: str):
        self.msg = msg


class _ClaimError:
    """Broadcast marker for a leader-side claim failure (see
    `LeaderStore.claim`); `transient` carries the degradation
    classification across processes."""

    def __init__(self, msg: str, transient: bool):
        self.msg = msg
        self.transient = transient


class PodWorker(BrainWorker):
    """BrainWorker for the pod-spanning mode: broadcast tick clock.

    Construct with a LeaderStore/LeaderSource pair and a judge whose
    univariate engine is a ShardedJudge over `make_global_mesh()`. The
    claim set, series, and clock are leader-broadcast, the judgment runs
    SPMD over the global mesh, and only the leader persists results.

    Control-flow-shaping knobs are ALSO leader-broadcast at
    construction: a per-host env skew in the cold-chunk size or the
    arena byte budgets would make processes issue differently-shaped
    judge programs (or one take the stacked-score fallback while its
    peers use the arena) and deadlock the collectives.
    """

    # Knob-level arena interaction only (budget read on the leader,
    # identical set on every host) — no row access involved; pod mode
    # always runs replicated arenas (batch.py:_resolve_arena_shards
    # forces shards=1 when process_count > 1), which trivially honors
    # the row-placement contract.
    # foremast: sharded-arena
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        from foremast_tpu.engine.arena import (
            _arena_bytes,
            _arena_max_bytes,
            set_arena_budget,
        )
        from foremast_tpu.engine.scoring import (
            bf16_delta_enabled,
            set_bf16_delta,
        )

        knobs = broadcast_obj(
            (
                self.cold_chunk_docs,
                self.pipeline_depth,
                self.fetch_workers,
                _arena_bytes(),
                _arena_max_bytes(),
                bf16_delta_enabled(),
            )
            if is_leader()
            else None
        )
        # the per-tick deadline (ISSUE 9 partial-tick release) decides
        # per-doc control flow off a LOCAL wall clock: two processes
        # disagreeing on "past the budget" would judge differently-
        # shaped batches into one SPMD program and deadlock the
        # collectives. Until the release decision is leader-broadcast,
        # pod mode runs unbudgeted (the pod watchdog still bounds a
        # wedged collective via FOREMAST_POD_TIMEOUT_SECONDS).
        self._degrade.tick_budget_seconds = 0.0
        # Sliced sweeps (ISSUE 15) stay OFF in pod mode for the same
        # class of reason: slice boundaries, dirty promotion, and the
        # warm pipeline's prefetch-thread fetches are process-local
        # control flow (and LeaderSource fetches are ordered
        # collectives that must never run off the tick thread). Every
        # process runs the monolithic tick body.
        self.sweep_slice_docs = 0
        if knobs is not None and not is_leader():
            self.cold_chunk_docs = knobs[0]
            # pipeline depth/pool size are broadcast for completeness:
            # LeaderSource forces the serial (depth-1) path regardless,
            # but no control-flow-shaping knob may ever skew per host
            self.pipeline_depth = knobs[1]
            self.fetch_workers = knobs[2]
            # explicit process-local overrides, NOT os.environ writes:
            # mutating the env after threads exist is a cross-thread
            # race, and a per-host skew in either knob would dispatch
            # f32 fits on one process and bf16-delta fits on its peers —
            # differently-shaped SPMD programs over the shared mesh
            set_arena_budget(knobs[3], knobs[4])
            set_bf16_delta(knobs[5])

    def tick(self, now: float | None = None) -> int:
        if now is None:
            now = broadcast_obj(time.time() if is_leader() else None)
        return super().tick(now=now)
