"""Sequence/context parallelism for long metric windows.

The reference's longest series is the 7-day history (~10k points) — one
chip's worth. But the framework treats long-context as first-class: when
windows outgrow a single device's HBM (year-long histories, 1 s steps, or
very wide batches), the *time* axis itself is sharded over the mesh and
recurrences run as distributed scans.

Two primitives, both built on `shard_map` + XLA collectives over ICI:

  * `sharded_linear_scan` — the EWMA/exponential-smoothing family is the
    linear recurrence l_t = a_t l_{t-1} + b_t, whose composition law
    (a1,b1)o(a2,b2) = (a1 a2, a2 b1 + b2) is associative. Each device
    scans its local time block, `all_gather`s the per-block composed
    elements (2 scalars per series per device — tiny on ICI), computes its
    exclusive prefix, and applies it locally. One collective total.
  * `sharded_masked_moments` — global masked mean/var across a time-sharded
    window via `psum` (the partial-sum trick), for bounds computed against
    statistics of a sequence no single chip holds.

This is the all-to-all/ring-style sequence-parallel design of the scaling
playbook applied to scans rather than attention: the sequence axis maps to
mesh axis `model`, batch stays on `data`.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from foremast_tpu.ops.forecasters import _linrec_assoc as _compose
from foremast_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS


def sharded_linear_scan(a: jax.Array, b: jax.Array, mesh: Mesh) -> jax.Array:
    """Distributed l_t = a_t * l_{t-1} + b_t (l_0 = 0) with time sharded.

    a, b: [B, T] with B sharded over `data` and T sharded over `model`.
    Returns l: [B, T] with the same sharding. The cross-device step moves
    2 scalars per (series, device) over ICI.
    """

    def local(a_blk, b_blk):
        # local inclusive scan of composed elements
        ca, cb = jax.lax.associative_scan(_compose, (a_blk, b_blk), axis=-1)
        # per-block total = last composed element
        tot_a = ca[..., -1:]
        tot_b = cb[..., -1:]
        # gather all block totals along the sequence axis group
        gat_a = jax.lax.all_gather(tot_a, MODEL_AXIS, axis=-1, tiled=True)  # [B, D]
        gat_b = jax.lax.all_gather(tot_b, MODEL_AXIS, axis=-1, tiled=True)
        idx = jax.lax.axis_index(MODEL_AXIS)
        # exclusive prefix over preceding blocks: compose blocks < idx
        d = gat_a.shape[-1]
        mask = jnp.arange(d) < idx  # [D]
        # composing with identity (1, 0) where masked out
        pa = jnp.where(mask, gat_a, 1.0)
        pb = jnp.where(mask, gat_b, 0.0)

        def fold(carry, i):
            ca_, cb_ = carry
            return _compose((ca_, cb_), (pa[..., i], pb[..., i])), None

        (pre_a, pre_b), _ = jax.lax.scan(
            fold,
            (jnp.ones_like(tot_a[..., 0]), jnp.zeros_like(tot_b[..., 0])),
            jnp.arange(d),
        )
        # apply prefix state l_prev = pre_b (l_0 = 0): l = ca * l_prev + cb
        return ca * pre_b[..., None] + cb

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, MODEL_AXIS), P(DATA_AXIS, MODEL_AXIS)),
        out_specs=P(DATA_AXIS, MODEL_AXIS),
        check_vma=False,
    )
    return fn(a, b)


def sharded_ewma(
    values: jax.Array, mask: jax.Array, alpha: float, mesh: Mesh
) -> jax.Array:
    """EWMA levels over a time-sharded window (mirrors ops.ewma_levels).

    values/mask: [B, T] sharded (data, model). First-valid-point
    initialization needs the global running count of valid points, computed
    as a second distributed linear scan (a=1, b=mask).
    """
    # global prefix count of valid points, inclusive
    cnt = sharded_linear_scan(
        jnp.ones_like(values), mask.astype(values.dtype), mesh
    )
    is_first = mask & (cnt == 1.0)
    a_eff = jnp.where(mask, jnp.asarray(alpha, values.dtype), 0.0)
    a_eff = jnp.where(is_first, 1.0, a_eff)
    return sharded_linear_scan(1.0 - a_eff, a_eff * values, mesh)


def sharded_masked_stats(
    values: jax.Array, mask: jax.Array, mesh: Mesh
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Global masked (count, mean, var) over a time-sharded window ->
    three [B] arrays replicated along `model`. One psum over ICI."""

    def local(v, m):
        mf = m.astype(v.dtype)
        s1 = jax.lax.psum(jnp.sum(v * mf, axis=-1), MODEL_AXIS)
        s2 = jax.lax.psum(jnp.sum(v * v * mf, axis=-1), MODEL_AXIS)
        n = jax.lax.psum(jnp.sum(mf, axis=-1), MODEL_AXIS)
        mean = jnp.where(n > 0, s1 / jnp.maximum(n, 1.0), 0.0)
        var = jnp.where(n > 0, s2 / jnp.maximum(n, 1.0) - mean * mean, 0.0)
        return n, mean, jnp.maximum(var, 0.0)

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, MODEL_AXIS), P(DATA_AXIS, MODEL_AXIS)),
        out_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
        check_vma=False,
    )
    return fn(values, mask)


def sharded_masked_moments(
    values: jax.Array, mask: jax.Array, mesh: Mesh
) -> tuple[jax.Array, jax.Array]:
    """(mean, var) view of `sharded_masked_stats` (kept for callers that
    don't need the count)."""
    _, mean, var = sharded_masked_stats(values, mask, mesh)
    return mean, var


def score_time_sharded(batch, mesh: Mesh, config=None):
    """Full moving_average_all judgment with the HISTORY time axis sharded
    over `model` — context parallelism end-to-end.

    For histories no single chip holds (year-long windows, 1 s steps):
    place `batch.historical` as [B over data, Th over model]; the model
    statistics reduce with one psum over ICI, and everything downstream
    (pairwise tests, bounds, flags, verdict) runs on the short
    data-sharded current/baseline windows. Semantics match
    `engine.scoring.score(algorithm="moving_average_all")`.

    `config`: a BrainConfig for pairwise/threshold parameters (defaults).
    """
    from foremast_tpu.config import BrainConfig
    from foremast_tpu.engine import scoring

    cfg = config or BrainConfig()

    n, mean, var = sharded_masked_stats(
        batch.historical.values, batch.historical.mask, mesh
    )
    pred = jnp.broadcast_to(mean[:, None], batch.current.values.shape)
    # the jitted shared tail: judgment semantics are defined once, in
    # engine/scoring — this path can never diverge from _score_xla
    return scoring.judgment_tail(
        batch,
        pred,
        jnp.sqrt(var),
        n,
        pairwise_algorithm=cfg.pairwise.algorithm,
        p_threshold=cfg.pairwise.threshold,
        min_mw=cfg.pairwise.min_mann_white_points,
        min_wilcoxon=cfg.pairwise.min_wilcoxon_points,
        min_kruskal=cfg.pairwise.min_kruskal_points,
        min_friedman=cfg.pairwise.min_friedman_points,
    )
