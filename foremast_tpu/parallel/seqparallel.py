"""Sequence/context parallelism for long metric windows.

The reference's longest series is the 7-day history (~10k points) — one
chip's worth. But the framework treats long-context as first-class: when
windows outgrow a single device's HBM (year-long histories, 1 s steps, or
very wide batches), the *time* axis itself is sharded over the mesh and
recurrences run as distributed scans.

Two primitives, both built on `shard_map` + XLA collectives over ICI:

  * `sharded_linear_scan` — the EWMA/exponential-smoothing family is the
    linear recurrence l_t = a_t l_{t-1} + b_t, whose composition law
    (a1,b1)o(a2,b2) = (a1 a2, a2 b1 + b2) is associative. Each device
    scans its local time block, `all_gather`s the per-block composed
    elements (2 scalars per series per device — tiny on ICI), computes its
    exclusive prefix, and applies it locally. One collective total.
  * `sharded_masked_moments` — global masked mean/var across a time-sharded
    window via `psum` (the partial-sum trick), for bounds computed against
    statistics of a sequence no single chip holds.
  * `sharded_phase_means` — the daily-seasonal (phase-pooled) fit over a
    time-sharded window: trend moments, per-phase sums/counts, and the
    leave-one-out residual scale are all per-block partial sums, so the
    whole long-season fit (including the trend<->season backfit rounds)
    costs a handful of batched psums plus one pmax.

This is the all-to-all/ring-style sequence-parallel design of the scaling
playbook applied to scans rather than attention: the sequence axis maps to
mesh axis `model`, batch stays on `data`.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

try:  # jax >= 0.5 top-level export
    from jax import shard_map
except ImportError:  # 0.4.x: experimental module, check_rep spelling
    from jax.experimental.shard_map import shard_map as _shard_map_04

    def shard_map(f, **kw):
        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
        return _shard_map_04(f, **kw)
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from foremast_tpu.ops.forecasters import _linrec_assoc as _compose
from foremast_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS


def sharded_linear_scan(a: jax.Array, b: jax.Array, mesh: Mesh) -> jax.Array:
    """Distributed l_t = a_t * l_{t-1} + b_t (l_0 = 0) with time sharded.

    a, b: [B, T] with B sharded over `data` and T sharded over `model`.
    Returns l: [B, T] with the same sharding. The cross-device step moves
    2 scalars per (series, device) over ICI.
    """

    def local(a_blk, b_blk):
        # local inclusive scan of composed elements
        ca, cb = jax.lax.associative_scan(_compose, (a_blk, b_blk), axis=-1)
        # per-block total = last composed element
        tot_a = ca[..., -1:]
        tot_b = cb[..., -1:]
        # gather all block totals along the sequence axis group
        gat_a = jax.lax.all_gather(tot_a, MODEL_AXIS, axis=-1, tiled=True)  # [B, D]
        gat_b = jax.lax.all_gather(tot_b, MODEL_AXIS, axis=-1, tiled=True)
        idx = jax.lax.axis_index(MODEL_AXIS)
        # exclusive prefix over preceding blocks: compose blocks < idx
        d = gat_a.shape[-1]
        mask = jnp.arange(d) < idx  # [D]
        # composing with identity (1, 0) where masked out
        pa = jnp.where(mask, gat_a, 1.0)
        pb = jnp.where(mask, gat_b, 0.0)

        def fold(carry, i):
            ca_, cb_ = carry
            return _compose((ca_, cb_), (pa[..., i], pb[..., i])), None

        (pre_a, pre_b), _ = jax.lax.scan(
            fold,
            (jnp.ones_like(tot_a[..., 0]), jnp.zeros_like(tot_b[..., 0])),
            jnp.arange(d),
        )
        # apply prefix state l_prev = pre_b (l_0 = 0): l = ca * l_prev + cb
        return ca * pre_b[..., None] + cb

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, MODEL_AXIS), P(DATA_AXIS, MODEL_AXIS)),
        out_specs=P(DATA_AXIS, MODEL_AXIS),
        check_vma=False,
    )
    return fn(a, b)


def sharded_ewma(
    values: jax.Array, mask: jax.Array, alpha: float, mesh: Mesh
) -> jax.Array:
    """EWMA levels over a time-sharded window (mirrors ops.ewma_levels).

    values/mask: [B, T] sharded (data, model). First-valid-point
    initialization needs the global running count of valid points, computed
    as a second distributed linear scan (a=1, b=mask).
    """
    # global prefix count of valid points, inclusive
    cnt = sharded_linear_scan(
        jnp.ones_like(values), mask.astype(values.dtype), mesh
    )
    is_first = mask & (cnt == 1.0)
    a_eff = jnp.where(mask, jnp.asarray(alpha, values.dtype), 0.0)
    a_eff = jnp.where(is_first, 1.0, a_eff)
    return sharded_linear_scan(1.0 - a_eff, a_eff * values, mesh)


def sharded_masked_stats(
    values: jax.Array, mask: jax.Array, mesh: Mesh
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Global masked (count, mean, var) over a time-sharded window ->
    three [B] arrays replicated along `model`. One psum over ICI."""

    def local(v, m):
        mf = m.astype(v.dtype)
        s1 = jax.lax.psum(jnp.sum(v * mf, axis=-1), MODEL_AXIS)
        s2 = jax.lax.psum(jnp.sum(v * v * mf, axis=-1), MODEL_AXIS)
        n = jax.lax.psum(jnp.sum(mf, axis=-1), MODEL_AXIS)
        mean = jnp.where(n > 0, s1 / jnp.maximum(n, 1.0), 0.0)
        var = jnp.where(n > 0, s2 / jnp.maximum(n, 1.0) - mean * mean, 0.0)
        return n, mean, jnp.maximum(var, 0.0)

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, MODEL_AXIS), P(DATA_AXIS, MODEL_AXIS)),
        out_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
        check_vma=False,
    )
    return fn(values, mask)


def sharded_masked_moments(
    values: jax.Array, mask: jax.Array, mesh: Mesh
) -> tuple[jax.Array, jax.Array]:
    """(mean, var) view of `sharded_masked_stats` (kept for callers that
    don't need the count)."""
    _, mean, var = sharded_masked_stats(values, mask, mesh)
    return mean, var


def sharded_phase_means(
    values: jax.Array,
    mask: jax.Array,
    season_length: int,
    mesh: Mesh,
) -> tuple[
    jax.Array, jax.Array, jax.Array, jax.Array, jax.Array, jax.Array
]:
    """Daily-seasonal (phase-pooled) fit over a TIME-SHARDED window —
    context parallelism for the long-season workhorse
    (`ops.forecasters.fit_phase_means`).

    values/mask: [B, T] with B over `data` and T over `model`. For
    year-long 60 s histories (~525k points) no single chip need hold the
    window: every statistic the fit needs — the masked linear trend, the
    per-phase pooled sums/counts, and the centered leave-one-out residual
    scale — is a per-block partial sum, so the whole fit costs eight
    batched (pytree) psums plus one pmax over ICI. Phase alignment
    requires the local block length to be a multiple of `season_length`
    (asserted; pad the window host-side), which makes every block's phase
    grid start at offset ≡ 0 (mod m).

    Semantics match `fit_phase_means` including the per-series 2-cycle
    identifiability rule: series with fewer than two cycles of VALID
    points keep the global-mean model (zero season/trend, historical
    mean/std as level/scale).

    Returns (season [B, m], level [B], trend [B], scale [B],
    season_phase [B] int32, n_hist [B] int32), replicated along `model`
    — the full terminal state `horizon` / `engine.scoring.score_from_state`
    consume.
    """
    m_len = int(season_length)
    n_model = mesh.shape[MODEL_AXIS]
    t_total = values.shape[1]
    t_loc = t_total // n_model
    assert t_total % n_model == 0, (
        f"model-axis size ({n_model}) must divide the time axis ({t_total})"
    )
    assert t_loc % m_len == 0, (
        f"local block ({t_loc}) must be a multiple of season_length "
        f"({m_len}) so every block is phase-aligned — pad the window"
    )

    def local(v, mk):
        b, t_blk = v.shape
        idx = jax.lax.axis_index(MODEL_AXIS)
        gidx = idx * t_blk + jnp.arange(t_blk)  # global time index, int
        tn = gidx.astype(v.dtype) / t_total  # normalized (bf16-matmul-safe)
        mf = mk.astype(v.dtype)
        phase = gidx % m_len

        # psum 1 (batched): mask-only trend moments, raw value moments
        # (identifiability guard), and per-phase counts — the block is
        # phase-aligned, so a local reshape gives exact phase columns
        n, st, stt, sx0, sxx, k = jax.lax.psum(
            (
                jnp.sum(mf, axis=-1),
                jnp.sum(tn * mf, axis=-1),
                jnp.sum(tn * tn * mf, axis=-1),
                jnp.sum(v * mf, axis=-1),
                jnp.sum(v * v * mf, axis=-1),
                jnp.sum(mf.reshape(b, t_blk // m_len, m_len), axis=1),
            ),
            MODEL_AXIS,
        )
        nn = jnp.maximum(n, 1.0)
        denom = stt - st * st / nn

        # Backfit trend <-> pooled phase means — same iteration count and
        # math as `fit_phase_means` (see its cycle/trend-leakage comment);
        # two batched psums per round, so the whole fit is 8 psums + pmax
        # (1 moments + 3 rounds x 2 + 1 residual-scale).
        season = jnp.zeros((b, m_len), v.dtype)
        for _ in range(3):
            y = v - jnp.take(season, phase, axis=1)
            sx, stx = jax.lax.psum(
                (jnp.sum(y * mf, axis=-1), jnp.sum(tn * y * mf, axis=-1)),
                MODEL_AXIS,
            )
            slope_n = jnp.where(
                denom > 1e-12,
                (stx - st * sx / nn) / jnp.maximum(denom, 1e-12),
                0.0,
            )
            intercept = sx / nn - slope_n * st / nn
            det = (v - (intercept[:, None] + slope_n[:, None] * tn)) * mf
            ssum = jax.lax.psum(
                jnp.sum(det.reshape(b, t_blk // m_len, m_len), axis=1),
                MODEL_AXIS,
            )
            season = jnp.where(k > 0, ssum / jnp.maximum(k, 1.0), 0.0)

        # centered leave-one-out residual scale (k=1 phases carry zero
        # information and are excluded; degenerate gap patterns fall back
        # to the plain residual std — same rules as fit_phase_means)
        k_at = jnp.take(k, phase, axis=1)
        pred = (
            intercept[:, None]
            + slope_n[:, None] * tn
            + jnp.take(season, phase, axis=1)
        )
        loo = k_at / jnp.maximum(k_at - 1.0, 1.0)
        smask = mf * (k_at > 1.5)
        r = (v - pred) * loo
        r_all = (v - pred) * mf
        # psum 3 (batched): residual norms/means for both scale paths
        ss, s1, n2, ss_all, s1_all = jax.lax.psum(
            (
                jnp.sum(r * r * smask, axis=-1),
                jnp.sum(r * smask, axis=-1),
                jnp.sum(smask, axis=-1),
                jnp.sum(r_all * r_all, axis=-1),
                jnp.sum(r_all, axis=-1),
            ),
            MODEL_AXIS,
        )

        def _std(sq, s1_, cnt):
            c = jnp.maximum(cnt, 1.0)
            mu = s1_ / c
            return jnp.sqrt(jnp.maximum(sq / c - mu * mu, 0.0))

        scale = jnp.where(
            n2 > 0, _std(ss, s1, n2), _std(ss_all, s1_all, nn)
        )

        # terminal level/trend/phase at the LAST globally valid index
        local_last = jnp.max(jnp.where(mk, gidx[None, :], -1), axis=-1)
        last_valid = jax.lax.pmax(local_last, MODEL_AXIS)
        level = intercept + slope_n * last_valid.astype(v.dtype) / t_total
        trend = slope_n / t_total
        season_phase = ((last_valid + 1) % m_len).astype(jnp.int32)

        # per-series 2-cycle identifiability: under-observed series keep
        # the global-mean model (fit_phase_means applies the same select
        # via _guard_unidentifiable)
        enough = n >= 2.0 * m_len
        mean_v = jnp.where(n > 0, sx0 / nn, 0.0)
        var_v = jnp.maximum(sxx / nn - mean_v * mean_v, 0.0)
        season = jnp.where(enough[:, None], season, 0.0)
        level = jnp.where(enough, level, mean_v)
        trend = jnp.where(enough, trend, 0.0)
        scale = jnp.where(enough, scale, jnp.sqrt(var_v))
        return season, level, trend, scale, season_phase, n.astype(jnp.int32)

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, MODEL_AXIS), P(DATA_AXIS, MODEL_AXIS)),
        out_specs=(
            P(DATA_AXIS, None),
            P(DATA_AXIS),
            P(DATA_AXIS),
            P(DATA_AXIS),
            P(DATA_AXIS),
            P(DATA_AXIS),
        ),
        check_vma=False,
    )
    return fn(values, mask)


def score_time_sharded(
    batch,
    mesh: Mesh,
    config=None,
    algorithm: str = "moving_average_all",
    gap_steps: jax.Array | None = None,
):
    """Full judgment with the HISTORY time axis sharded over `model` —
    context parallelism end-to-end.

    For histories no single chip holds (year-long windows, 1 s steps):
    place `batch.historical` as [B over data, Th over model]; the model
    fit reduces over ICI, and everything downstream (pairwise tests,
    bounds, flags, verdict) runs on the short data-sharded current/
    baseline windows. Two fits are supported:

      * `moving_average_all` (the deployed default) — one psum of masked
        moments; semantics match `engine.scoring.score`.
      * `phase_means` (the daily-seasonal workhorse) — the distributed
        phase-pooled fit (`sharded_phase_means`, season from
        `config.season_steps`), whose terminal state feeds the SAME
        jitted judgment program the fit cache uses
        (`scoring.score_from_state`), so bounds/flags/verdicts cannot
        diverge from the single-chip path.

    `config`: a BrainConfig for season/pairwise/threshold parameters.
    `gap_steps` [B]: hist->cur gap for drifted re-check windows — the
    seasonal phase must advance by it exactly like every other
    phase_means path (`scoring._advance_gap`; `judge._gap_steps`
    computes it from task timestamps). Ignored by the trendless,
    seasonless mean model.
    """
    from foremast_tpu.config import BrainConfig
    from foremast_tpu.engine import scoring

    cfg = config or BrainConfig()
    pw = dict(
        pairwise_algorithm=cfg.pairwise.algorithm,
        p_threshold=cfg.pairwise.threshold,
        min_mw=cfg.pairwise.min_mann_white_points,
        min_wilcoxon=cfg.pairwise.min_wilcoxon_points,
        min_kruskal=cfg.pairwise.min_kruskal_points,
        min_friedman=cfg.pairwise.min_friedman_points,
    )

    if algorithm == "phase_means":
        season, level, trend, scale, phase, n_hist = sharded_phase_means(
            batch.historical.values,
            batch.historical.mask,
            cfg.season_steps,
            mesh,
        )
        return scoring.score_from_state(
            batch,
            level,
            trend,
            season,
            phase,
            scale,
            n_hist,
            gap_steps=gap_steps,
            **pw,
        )
    if algorithm != "moving_average_all":
        raise ValueError(
            f"score_time_sharded supports moving_average_all and "
            f"phase_means, not {algorithm!r}"
        )

    n, mean, var = sharded_masked_stats(
        batch.historical.values, batch.historical.mask, mesh
    )
    pred = jnp.broadcast_to(mean[:, None], batch.current.values.shape)
    # the jitted shared tail: judgment semantics are defined once, in
    # engine/scoring — this path can never diverge from _score_xla
    return scoring.judgment_tail(batch, pred, jnp.sqrt(var), n, **pw)
