"""Sharded batch scoring — the cluster-scale engine (BASELINE.md config 5:
10k services x 4 metrics x 30-min windows over a v5e-8).

Design (SURVEY.md section 7.4): the (service x metric) population is one
`[B, T]` batch whose leading axis is sharded over the mesh's `data` axis.
The scoring program contains no cross-window dependencies, so XLA
partitions it with zero collectives — each chip judges its slice of the
fleet; only the verdict gather crosses ICI.

The host-side `ShardedJudge` rounds batches up to a multiple of the data
axis (padding windows are fully masked -> verdict UNKNOWN, dropped on
decode) and placement happens once per batch via `device_put` with a
NamedSharding — double-buffered H2D comes from dispatching the next batch
while the previous result is still in flight (jax's async dispatch).
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from foremast_tpu.config import BrainConfig
from foremast_tpu.engine import scoring
from foremast_tpu.engine.judge import HealthJudge, MetricTask, MetricVerdict
from foremast_tpu.ops.windows import MetricWindows
from foremast_tpu.parallel import mesh as meshlib


def pad_batch(batch: scoring.ScoreBatch, multiple: int) -> scoring.ScoreBatch:
    """Pad the leading axis to a multiple; padded rows are all-masked."""
    b = batch.current.values.shape[0]
    target = meshlib.pad_to_multiple(b, multiple)
    if target == b:
        return batch
    pad = target - b

    def pad_leading(a):
        widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
        return jnp.pad(a, widths)

    return jax.tree.map(pad_leading, batch)


def shard_batch(batch: scoring.ScoreBatch, mesh) -> scoring.ScoreBatch:
    """Place a (padded) batch with its leading axis over the data axis."""
    return meshlib.shard_leading(batch, mesh)


def sharded_univariate(config: BrainConfig | None = None, mesh=None):
    """The worker's mesh judge, or None for the identity.

    THE one place a device mesh turns into a univariate judge —
    BrainWorker (both its `device_mesh="env"` default and an explicit
    Mesh argument) and cli.cmd_worker all construct through here, so
    the resolution rules (auto span, 1-device identity, pod guard,
    infeasible-grid fallback — all in mesh.worker_device_mesh) and the
    construction/log wiring can never drift between call sites.
    `mesh=None` resolves FOREMAST_DEVICE_MESH."""
    import logging

    if mesh is None:
        mesh = meshlib.worker_device_mesh()
    if mesh is None:
        return None
    judge = ShardedJudge(config, mesh=mesh)
    logging.getLogger("foremast_tpu.worker").info(
        "device mesh: judge partitioned over %s", dict(mesh.shape)
    )
    return judge


class ShardedJudge(HealthJudge):
    """HealthJudge whose compiled scorer runs partitioned over a mesh.

    Drop-in: same `judge(tasks) -> [MetricVerdict]` surface AND the same
    `judge_columnar(...)` fast-tick surface (ISSUE 13): the worker's
    whole warm path — univariate columnar (both its baseline-less and
    canary pairwise-active variants: the ISSUE 14 baseline buffer rides
    the ScoreBatch pytree through `_place`, partitioning like every
    other [B, tc] operand) plus, through `_place_cols`, the joint
    from-rows programs — rides the mesh. Placement only:
    batches shard their leading axis over `data` and arenas shard their
    ROW space over the same axis (`_arena_sharding` / `_arena_shards`,
    ISSUE 19 — FOREMAST_ARENA_SHARDED=0 restores the replicated
    layout), so admission, fit-cache identity and every degradation
    contract are untouched. A 1-device mesh is the identity
    (the worker skips this wrapper then — parallel.mesh.
    worker_device_mesh).
    """

    def __init__(self, config: BrainConfig | None = None, mesh=None):
        super().__init__(config)
        self.mesh = mesh if mesh is not None else meshlib.make_mesh()
        self.n_data = int(self.mesh.shape[meshlib.DATA_AXIS])
        self._arena_shards_n = self._resolve_arena_shards()  # foremast: sharded-arena
        # roofline accounting (benchmarks/scaleout_bench.py sharded
        # variant): wall-clock + bytes of the two host<->device hops the
        # mesh changes — H2D placement and the sharded-result gather.
        # Dispatch/decode stay on the judge's existing stage spans.
        self.mesh_stats = {
            "place_seconds": 0.0,
            "place_bytes": 0,
            "place_calls": 0,
            "fetch_seconds": 0.0,
            "fetch_bytes": 0,
        }

    def _batch_multiple(self) -> int:
        return self.n_data

    def _account_place(self, t0: float, lead, leaves) -> None:
        """Shared epilogue of both placement hooks: the acceptance
        assert (ISSUE 13 — every local shard of the leading array holds
        B/n_data rows) plus the roofline accounting. One body so the
        bench's H2D leg and `foremast_device_mesh_transfer_*` can never
        skew between the ScoreBatch and bare-operand paths."""
        meshlib.assert_partitioned(lead, self.n_data)
        st = self.mesh_stats
        st["place_seconds"] += time.perf_counter() - t0
        # Iterates the host LIST of placed leaves; size/dtype metadata
        # only, no device data read.
        # foremast: ignore[device-flow]
        st["place_bytes"] += sum(
            a.size * a.dtype.itemsize for a in leaves
        )
        st["place_calls"] += 1

    def _place(self, batch):
        # leading axis over `data`; the batch is already padded to a
        # multiple of the data axis (judge_columnar's rounding, or
        # _judge_bucket's task-list pad below)
        t0 = time.perf_counter()
        placed = shard_batch(batch, self.mesh)
        self._account_place(
            t0, placed.current.values, jax.tree.leaves(placed)
        )
        return placed

    def _place_cols(self, *arrays):
        # bare [B, ...] operands (joint from-rows cur/mask/x): leading
        # axis over `data`, same assert as the ScoreBatch path
        t0 = time.perf_counter()
        # Iterates the host operand TUPLE; device_put is the H2D
        # placement itself, not a D2H sync.
        # foremast: ignore[device-flow]
        placed = tuple(
            jax.device_put(
                a, meshlib.data_sharding(self.mesh, np.ndim(a))
            )
            for a in arrays
        )
        if placed:
            self._account_place(t0, placed[0], placed)
        return placed

    def mesh_debug(self) -> dict:
        """The worker `/debug/state` device_mesh section body."""
        rows = self.batch_rows_total
        return {
            "shape": dict(self.mesh.shape),
            "devices": int(np.prod(list(self.mesh.shape.values()))),
            "batch_rows_total": rows,
            "pad_rows_total": self.pad_rows_total,
            "padded_row_fraction": (
                round(self.pad_rows_total / rows, 4) if rows else None
            ),
            **{k: round(v, 4) if isinstance(v, float) else v
               for k, v in self.mesh_stats.items()},
        }

    # foremast: sharded-arena
    def _resolve_arena_shards(self) -> int:
        """How many data-axis blocks the arena row space splits into,
        decided ONCE at construction (env mutation mid-process must not
        flip a live judge's layout). n_data by default; 1 (replicated)
        when FOREMAST_ARENA_SHARDED is off, or under multi-controller —
        a pod judge's sharded arena would leave most blocks on
        non-addressable devices, and pod row assignment relies on every
        process deriving identical maps (parallel/distributed.py), so
        pods keep the PR 13 replicated layout."""
        import os

        raw = (
            (os.environ.get("FOREMAST_ARENA_SHARDED") or "1")
            .strip()
            .lower()
        )
        if raw in ("0", "off", "false", "no", "none", "disabled"):
            return 1
        if jax.process_count() > 1:
            return 1
        return self.n_data

    # foremast: sharded-arena
    def _arena_shards(self) -> int:
        return self._arena_shards_n

    # foremast: sharded-arena
    def _arena_sharding(self):
        # Arena placement (ISSUE 19, superseding the VERDICT r4 weak #4
        # replication): SHARD the state rows over the mesh's data axis,
        # in the same contiguous blocks as the batch. The judge's block
        # placement rule (engine.arena._assign_sharded) puts position
        # i's row on the device that holds batch position i, so the
        # warm gather stays device-local — the property replication
        # bought — while aggregate capacity scales linearly with the
        # mesh instead of being bounded by ONE chip's HBM (the exact
        # inverse the million-service north star needs, ROADMAP item
        # 2). The replicated layout survives behind
        # FOREMAST_ARENA_SHARDED=0 and remains the pod-mode layout
        # (_resolve_arena_shards).
        from jax.sharding import NamedSharding, PartitionSpec as P

        if self._arena_shards_n > 1:
            return NamedSharding(self.mesh, P(meshlib.DATA_AXIS))
        return NamedSharding(self.mesh, P())

    def _fetch(self, tree):
        # Sharded results are not fully addressable from one process
        # under multi-controller: allgather them to every host (small
        # arrays — int8 verdicts, packed bits, band-last points).
        # Single-process meshes keep the plain overlapped device_get.
        # Timed as the "host gather" leg of the roofline account — on a
        # warm tick this wait also absorbs the device execution the
        # async dispatch deferred, which is exactly what the bench wants
        # attributed (gather-vs-dispatch is the saturation question).
        t0 = time.perf_counter()
        if jax.process_count() == 1:
            out = jax.device_get(tree)
        else:
            from jax.experimental import multihost_utils as mhu

            out = jax.tree.map(
                lambda a: np.asarray(mhu.process_allgather(a, tiled=True)),
                tree,
            )
        st = self.mesh_stats
        st["fetch_seconds"] += time.perf_counter() - t0
        st["fetch_bytes"] += sum(
            int(np.asarray(a).size * np.asarray(a).dtype.itemsize)
            for a in jax.tree.leaves(out)
        )
        return out

    def _judge_bucket(self, tasks, th, tc):
        n_data = self.mesh.shape[meshlib.DATA_AXIS]
        # Build host-side arrays via the parent packing, then pad + shard.
        # Parent returns decoded verdicts, so replicate its packing here
        # only for placement: intercept by padding the *task list* instead —
        # padded tasks are empty windows, decoded then dropped.
        b = len(tasks)
        target = meshlib.pad_to_multiple(b, n_data)
        if target != b:
            empty = np.zeros(0, np.float32)
            et = np.zeros(0, np.int64)
            # constant fit-cache keys: the empty-history "fit" (n=0 ->
            # UNKNOWN, dropped below) caches once, so warm re-check
            # ticks stay fit-free even when the batch needs padding.
            # Sharded arenas get one pad key PER data-axis block (the
            # tail positions' blocks move with b, and a single key would
            # migrate between shards every call); models.cache
            # .is_pad_fit_key matches the whole "__pad__*" family, so
            # none of them ever journals or chases a document.
            # foremast: sharded-arena
            shards = self._arena_shards()
            per = target // shards

            def pad_task(pos: int) -> MetricTask:
                fk = "__pad__" if shards == 1 else f"__pad__@{pos // per}"
                return MetricTask(
                    job_id="__pad__",
                    alias="__pad__",
                    metric_type=None,
                    hist_times=et,
                    hist_values=empty,
                    cur_times=et,
                    cur_values=empty,
                    fit_key=fk,
                )

            tasks = list(tasks) + [
                pad_task(pos) for pos in range(b, target)
            ]
        out = super()._judge_bucket(tasks, th, tc)
        return out[:b]


def throughput_batch(
    n_windows: int,
    hist_len: int,
    cur_len: int,
    mesh=None,
    seed: int = 0,
) -> scoring.ScoreBatch:
    """Synthetic fixed-shape batch for benchmarking (bench.py)."""
    rng = np.random.default_rng(seed)
    hv = (0.5 + 0.05 * rng.standard_normal((n_windows, hist_len))).astype(np.float32)
    cv = (0.5 + 0.05 * rng.standard_normal((n_windows, cur_len))).astype(np.float32)
    bv = (0.5 + 0.05 * rng.standard_normal((n_windows, cur_len))).astype(np.float32)
    t0 = 1_700_000_000
    ht = np.broadcast_to(t0 + 60 * np.arange(hist_len, dtype=np.int64), hv.shape)
    ct = np.broadcast_to(t0 + 60 * np.arange(cur_len, dtype=np.int64), cv.shape)
    ones_h = np.ones(hv.shape, bool)
    ones_c = np.ones(cv.shape, bool)

    def win(v, t, m):
        # Bench-only constructor: builds the synthetic batch on the
        # default device; a mesh run re-places it via shard_batch below.
        return MetricWindows(
            # foremast: ignore[sharding-contract]
            values=jnp.asarray(v), mask=jnp.asarray(m), times=jnp.asarray(t.astype(np.int32))
        )

    batch = scoring.ScoreBatch(
        historical=win(hv, ht, ones_h),
        current=win(cv, ct, ones_c),
        baseline=win(bv, ct, ones_c),
        threshold=jnp.full((n_windows,), 5.0, jnp.float32),
        bound=jnp.full((n_windows,), 1, jnp.int32),
        min_lower_bound=jnp.zeros((n_windows,), jnp.float32),
        min_points=jnp.full((n_windows,), 10, jnp.int32),
    )
    if mesh is not None:
        batch = shard_batch(pad_batch(batch, mesh.shape[meshlib.DATA_AXIS]), mesh)
    return batch
