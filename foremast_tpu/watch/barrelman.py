"""Deployment watcher — the core controller of the watch plane.

Behavior parity with `foremast-barrelman/pkg/controller/Barrelman.go`:

* add/update/delete handling for labeled Deployments (the ``app`` label is
  required, Barrelman.go:310-313); canary detected by the
  ``-foremast-canary`` name suffix (Barrelman.go:62,326-328).
* update trigger = container image or env diff (EnvArrayEquals,
  Barrelman.go:127-137,224-234).
* namespace gating: hard blacklist {kube-public, kube-system, opa,
  monitoring} + ``foremast.ai/monitoring: "false"`` namespace annotation,
  cached 5 min (Barrelman.go:93-101,477-494).
* metadata fallback chain: app name -> ``appType`` label in the app's
  namespace -> ``appType`` in the watcher's own namespace; lookup errors
  negative-cached 1 min (Barrelman.go:139-174).
* rollback-loop suppression: skip when the new revision equals the
  monitor's rollbackRevision or the legacy rollback annotation is set
  (Barrelman.go:238-253).
* pod/RS discovery: ReplicaSets owned by the Deployment with replicas>0;
  newest revision = current pods, older = baseline pods; bounded
  sleep-retries (Barrelman.go:632-780).
* monitor window: 10 min analysis (watchTime), 30 min expiry
  (waitUntilMax) (Barrelman.go:52-54).
* continuous mode re-arms through ``monitor_continuously``
  (Barrelman.go:176-203) using app-aggregated queries with no pod pinning
  (metricsquery.go:56-58).

Structure differs deliberately: no goroutines/workqueues — the plane is a
single-threaded event loop over the pluggable KubeClient, and all blocking
retries take an injectable sleep/clock so tests run instantly.
"""

from __future__ import annotations

import logging
import time as _time
from typing import Callable

from foremast_tpu.jobs.models import AnalyzeRequest
from foremast_tpu.jobs.store import now_rfc3339
from foremast_tpu.metrics.promql import (
    STRATEGY_CANARY,
    STRATEGY_CONTINUOUS,
    STRATEGY_ROLLING_UPDATE,
    create_metrics_info,
)
from foremast_tpu.watch.analyst import AnalystClient, HttpAnalyst
from foremast_tpu.watch.crds import (
    CANARY_SUFFIX,
    MONITOR_OPT_OUT_ANNOTATION,
    ROLLBACK_ANNOTATION,
    DeploymentMetadata,
    DeploymentMonitor,
    MonitorPhase,
    MonitorStatus,
    Remediation,
)
from foremast_tpu.watch.kubeapi import (
    KubeClient,
    NotFound,
    deployment_containers,
    deployment_revision,
    owner_uids,
    record_event,
)

log = logging.getLogger("foremast_tpu.watch")

NAMESPACE_BLACKLIST = frozenset({"kube-public", "kube-system", "opa", "monitoring"})
NAMESPACE_CACHE_TTL = 300.0  # 5 min, Barrelman.go:99-101
METADATA_NEG_CACHE_TTL = 60.0  # 1 min, Barrelman.go:139-174
WATCH_TIME_SECONDS = 600  # 10 min analysis window, Barrelman.go:52
WAIT_UNTIL_MAX_SECONDS = 1800  # 30 min expiry, Barrelman.go:54
POD_RETRY_COUNT = 3  # Barrelman.go:632-780
POD_RETRY_SLEEP = 5.0

APP_TYPE_LABEL = "appType"


def _rfc3339(ts: float) -> str:
    return _time.strftime("%Y-%m-%dT%H:%M:%SZ", _time.gmtime(ts))


def env_equals(a: list[dict] | None, b: list[dict] | None) -> bool:
    """Order-insensitive env-var list equality (EnvArrayEquals,
    Barrelman.go:127-137)."""

    def norm(env):
        return sorted(
            (e.get("name", ""), e.get("value", ""), str(e.get("valueFrom", "")))
            for e in (env or [])
        )

    return norm(a) == norm(b)


def containers_changed(old: dict, new: dict) -> bool:
    """True when any container image or env changed (Barrelman.go:224-234)."""
    olds = {c.get("name"): c for c in deployment_containers(old)}
    news = {c.get("name"): c for c in deployment_containers(new)}
    if set(olds) != set(news):
        return True
    for name, nc in news.items():
        oc = olds[name]
        if oc.get("image") != nc.get("image"):
            return True
        if not env_equals(oc.get("env"), nc.get("env")):
            return True
    return False


class Barrelman:
    def __init__(
        self,
        kube: KubeClient,
        own_namespace: str = "foremast",
        analyst_factory: Callable[[str], AnalystClient] | None = None,
        clock: Callable[[], float] = _time.time,
        sleep: Callable[[float], None] = _time.sleep,
    ) -> None:
        self.kube = kube
        self.own_namespace = own_namespace
        self.analyst_factory = analyst_factory or HttpAnalyst
        self.clock = clock
        self.sleep = sleep
        self._ns_cache: dict[str, tuple[float, bool]] = {}
        self._md_neg_cache: dict[str, float] = {}

    # ------------------------------------------------------------------
    # informer-equivalent entry points
    # ------------------------------------------------------------------

    def handle_deployment(self, event: str, dep: dict, old: dict | None) -> None:
        """Dispatch an add/update/delete Deployment event
        (Barrelman.go:310-464)."""
        meta = dep.get("metadata", {})
        namespace, name = meta.get("namespace", ""), meta.get("name", "")
        app = (meta.get("labels", {}) or {}).get("app")
        if not app:
            return  # app label required, Barrelman.go:310-313
        if not self.namespace_monitored(namespace):
            return

        if event == "delete":
            try:
                self.kube.delete_monitor(namespace, name)
            except NotFound:
                pass
            return

        if name.endswith(CANARY_SUFFIX):
            # canary Deployment created/changed -> monitor against the
            # primary; status-only churn (replica counts, conditions) must
            # not restart the analysis window
            if event == "add" or (old is not None and containers_changed(old, dep)):
                self.monitor_deployment(dep, old, strategy=STRATEGY_CANARY)
        elif event == "add":
            # ensure a monitor CR exists for every labeled Deployment
            self._ensure_monitor(dep)
        elif event == "update" and old is not None and containers_changed(old, dep):
            self.monitor_deployment(dep, old, strategy=STRATEGY_ROLLING_UPDATE)

    # ------------------------------------------------------------------
    # gating + metadata resolution
    # ------------------------------------------------------------------

    def namespace_monitored(self, namespace: str) -> bool:
        """Blacklist + annotation opt-out with a 5-min TTL cache
        (Barrelman.go:93-101,477-494)."""
        if namespace in NAMESPACE_BLACKLIST:
            return False
        now = self.clock()
        cached = self._ns_cache.get(namespace)
        if cached and now - cached[0] < NAMESPACE_CACHE_TTL:
            return cached[1]
        monitored = True
        try:
            ns = self.kube.get_namespace(namespace)
            ann = ns.get("metadata", {}).get("annotations", {}) or {}
            monitored = ann.get(MONITOR_OPT_OUT_ANNOTATION, "true") != "false"
        except NotFound:
            pass
        self._ns_cache[namespace] = (now, monitored)
        return monitored

    def get_metadata(self, dep: dict) -> DeploymentMetadata | None:
        """app name -> appType label (same ns) -> appType (own ns), with a
        1-min negative cache (Barrelman.go:139-174)."""
        meta = dep.get("metadata", {})
        namespace = meta.get("namespace", "")
        labels = meta.get("labels", {}) or {}
        app = labels.get("app", "")
        app_type = labels.get(APP_TYPE_LABEL, "")
        candidates = [(namespace, app)]
        if app_type:
            candidates.append((namespace, app_type))
            candidates.append((self.own_namespace, app_type))
        now = self.clock()
        for ns, name in candidates:
            if not name:
                continue
            key = f"{ns}/{name}"
            neg = self._md_neg_cache.get(key)
            if neg and now - neg < METADATA_NEG_CACHE_TTL:
                continue
            try:
                return self.kube.get_metadata(ns, name)
            except NotFound:
                self._md_neg_cache[key] = now
        return None

    # ------------------------------------------------------------------
    # monitoring
    # ------------------------------------------------------------------

    def monitor_deployment(self, dep: dict, old: dict | None, strategy: str) -> None:
        """Validate, suppress rollback loops, then start an analysis job
        (monitorDeployment, Barrelman.go:205-263)."""
        meta = dep.get("metadata", {})
        namespace, name = meta.get("namespace", ""), meta.get("name", "")
        metadata = self.get_metadata(dep)
        if metadata is None:
            log.info("no DeploymentMetadata for %s/%s; skipping", namespace, name)
            return
        ann = meta.get("annotations", {}) or {}
        if ann.get(ROLLBACK_ANNOTATION):
            # rollback in flight (Barrelman.go:245-253). The v1beta1 API
            # server consumed this annotation; on apps/v1 our controller
            # sets it, so consume it here (null deletes under strategic
            # merge) or the app would never be monitored again.
            self.kube.patch_deployment(
                namespace, name, {"metadata": {"annotations": {ROLLBACK_ANNOTATION: None}}}
            )
            return
        revision = deployment_revision(dep)
        try:
            monitor = self.kube.get_monitor(namespace, self._monitor_name(name))
            if monitor.rollback_revision and revision == monitor.rollback_revision:
                return  # this update IS our own rollback
        except NotFound:
            monitor = None
        self.monitor_new_deployment(dep, old, strategy, metadata)

    def monitor_continuously(self, monitor: DeploymentMonitor) -> None:
        """Re-arm a continuous watch: app-aggregated queries, no pod
        pinning (Barrelman.go:176-203, metricsquery.go:56-58)."""
        try:
            dep = self.kube.get_deployment(monitor.namespace, monitor.name)
        except NotFound:
            return
        metadata = self.get_metadata(dep)
        if metadata is None:
            return
        self.monitor_new_deployment(dep, None, STRATEGY_CONTINUOUS, metadata)

    def monitor_new_deployment(
        self,
        dep: dict,
        old: dict | None,
        strategy: str,
        metadata: DeploymentMetadata,
    ) -> None:
        """Discover pods, start the analyst job (retry once), upsert the
        DeploymentMonitor (monitorNewDeployment, Barrelman.go:783-899)."""
        meta = dep.get("metadata", {})
        namespace, name = meta.get("namespace", ""), meta.get("name", "")
        app = (meta.get("labels", {}) or {}).get("app", name)

        current_pods: list[str] = []
        baseline_pods: list[str] = []
        if strategy != STRATEGY_CONTINUOUS:
            current_pods, baseline_pods = self.get_pod_names(dep)
            if not current_pods:
                log.warning("no pods found for %s/%s; aborting monitor", namespace, name)
                return
            if strategy == STRATEGY_CANARY and not baseline_pods:
                # a canary Deployment owns only its own ReplicaSet; the
                # baseline population is the PRIMARY Deployment's pods
                # (reference walks the old Deployment's ReplicaSets,
                # Barrelman.go:632-780)
                try:
                    primary = self.kube.get_deployment(
                        namespace, name.removesuffix(CANARY_SUFFIX)
                    )
                    primary_cur, primary_old = self.get_pod_names(primary)
                    baseline_pods = primary_cur + primary_old
                except NotFound:
                    pass

        now = self.clock()
        start = now
        end = now + WATCH_TIME_SECONDS
        info = create_metrics_info(
            strategy=strategy,
            metric_names=metadata.metric_names(),
            namespace=namespace,
            app=app,
            start=int(start),
            end=int(end),
            endpoint=metadata.metrics_endpoint,
            new_pods=current_pods,
            old_pods=baseline_pods,
        )
        req = AnalyzeRequest(
            app_name=app,
            start_time=_rfc3339(start),
            end_time=_rfc3339(end),
            metrics=info,
            strategy=strategy,
            namespace=namespace,
        )
        job_id = self._start_job(metadata.analyst_endpoint, req)
        if job_id is None:
            phase, reason = MonitorPhase.FAILED, "analyst create failed"
        else:
            phase, reason = MonitorPhase.RUNNING, ""

        monitor = self._get_or_new_monitor(namespace, name, app)
        monitor.analyst_endpoint = metadata.analyst_endpoint
        monitor.start_time = _rfc3339(start)
        monitor.wait_until = _rfc3339(now + WAIT_UNTIL_MAX_SECONDS)
        monitor.continuous = monitor.continuous or strategy == STRATEGY_CONTINUOUS
        if old is not None:
            monitor.rollback_revision = deployment_revision(old)
        monitor.status = MonitorStatus(
            job_id=job_id or "",
            phase=phase,
            timestamp=now_rfc3339(),
        )
        if reason:
            monitor.status.anomaly = {"reason": reason}
        self.kube.upsert_monitor(monitor)
        record_event(
            self.kube,
            namespace,
            name,
            reason="MonitoringStarted" if job_id else "AnalystUnavailable",
            message=(
                f"health analysis job {job_id} started ({strategy})"
                if job_id
                else "could not create analysis job"
            ),
            event_type="Normal" if job_id else "Warning",
        )

    def _start_job(self, endpoint: str, req: AnalyzeRequest) -> str | None:
        """StartAnalyzing with the reference's retry-once
        (Barrelman.go:819-826)."""
        client = self.analyst_factory(endpoint)
        for attempt in (1, 2):
            try:
                return client.start_analyzing(req)
            except Exception as e:  # noqa: BLE001 - parity: any failure retried once
                log.warning("StartAnalyzing attempt %d failed: %s", attempt, e)
        return None

    # ------------------------------------------------------------------
    # pod / ReplicaSet discovery
    # ------------------------------------------------------------------

    def get_pod_names(self, dep: dict) -> tuple[list[str], list[str]]:
        """(current_pods, baseline_pods) via ReplicaSet ownership.

        The reference walks ReplicaSets owned by the old/new Deployment
        with replicas>0, disambiguating via DeploymentCondition messages
        and sleeping between retries (Barrelman.go:632-780). Equivalent
        rule here: among live owned ReplicaSets, the highest
        ``deployment.kubernetes.io/revision`` is current, the rest are
        baseline.
        """
        meta = dep.get("metadata", {})
        namespace = meta.get("namespace", "")
        dep_uid = meta.get("uid", "")
        for attempt in range(POD_RETRY_COUNT):
            live = [
                rs
                for rs in self.kube.list_replicasets(namespace)
                if dep_uid in owner_uids(rs)
                and (rs.get("status", {}).get("replicas") or rs.get("spec", {}).get("replicas") or 0) > 0
            ]
            if live:
                live.sort(key=deployment_revision)
                new_rs, old_rs = live[-1], live[:-1]
                pods = self.kube.list_pods(namespace)
                current = self._pods_of(pods, new_rs["metadata"].get("uid", ""))
                baseline = [
                    p
                    for rs in old_rs
                    for p in self._pods_of(pods, rs["metadata"].get("uid", ""))
                ]
                if current:
                    return current, baseline
            if attempt < POD_RETRY_COUNT - 1:
                self.sleep(POD_RETRY_SLEEP)
        return [], []

    @staticmethod
    def _pods_of(pods: list[dict], rs_uid: str) -> list[str]:
        return [
            p["metadata"]["name"]
            for p in pods
            if rs_uid in owner_uids(p)
        ]

    # ------------------------------------------------------------------
    # monitor CR helpers
    # ------------------------------------------------------------------

    @staticmethod
    def _monitor_name(dep_name: str) -> str:
        """Monitor CR is named after the primary Deployment: canary
        deployments map onto the primary's monitor (Barrelman.go:326-328)."""
        return dep_name.removesuffix(CANARY_SUFFIX)

    def _get_or_new_monitor(self, namespace: str, dep_name: str, app: str) -> DeploymentMonitor:
        name = self._monitor_name(dep_name)
        try:
            return self.kube.get_monitor(namespace, name)
        except NotFound:
            return DeploymentMonitor(
                name=name,
                namespace=namespace,
                selector={"app": app},
                remediation=Remediation(),
            )

    def _ensure_monitor(self, dep: dict) -> None:
        meta = dep.get("metadata", {})
        namespace, name = meta.get("namespace", ""), meta.get("name", "")
        app = (meta.get("labels", {}) or {}).get("app", name)
        try:
            self.kube.get_monitor(namespace, self._monitor_name(name))
        except NotFound:
            self.kube.upsert_monitor(
                DeploymentMonitor(
                    name=self._monitor_name(name),
                    namespace=namespace,
                    selector={"app": app},
                )
            )
