"""Custom-resource types for the watch plane.

Parity with the reference CRD Go types
(`foremast-barrelman/pkg/apis/deployment/v1alpha1/types.go`):

* ``DeploymentMetadata`` (types.go:14-156) — per-app / per-app-type config:
  analyst endpoint, metric source + endpoint + the list of monitored
  metrics ({metricName, metricType, metricAlias}), log config, descriptor.
* ``DeploymentMonitor`` (types.go:175-295) — per-deployment runtime state:
  spec {selector, analyst, startTime, waitUntil, metrics, continuous,
  remediation{option, parameters}, rollbackRevision} and status {jobId,
  phase, remediationTaken, anomaly, timestamp, expired}.
* Phases Healthy/Running/Failed/Unhealthy/Warning/Expired/Abort
  (types.go:241-255); remediation options None/AutoRollback/AutoPause/Auto
  (types.go:258-269).

Both types round-trip to the K8s CR wire form (apiVersion
``deployment.foremast.ai/v1alpha1``) so HttpKube can CRUD them against a
real API server and manifests stay compatible with reference CRs.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping

GROUP = "deployment.foremast.ai"
VERSION = "v1alpha1"
API_VERSION = f"{GROUP}/{VERSION}"

CANARY_SUFFIX = "-foremast-canary"  # Barrelman.go:62
MONITOR_OPT_OUT_ANNOTATION = "foremast.ai/monitoring"  # Barrelman.go:93-101
ROLLBACK_ANNOTATION = "deprecated.deployment.rollback.to"  # Barrelman.go:245-253


class MonitorPhase:
    """DeploymentMonitor.status.phase enum (types.go:241-255)."""

    HEALTHY = "Healthy"
    RUNNING = "Running"
    FAILED = "Failed"
    UNHEALTHY = "Unhealthy"
    WARNING = "Warning"
    EXPIRED = "Expired"
    ABORT = "Abort"


class RemediationOption:
    """spec.remediation.option enum (types.go:258-269)."""

    NONE = "None"
    AUTO_ROLLBACK = "AutoRollback"
    AUTO_PAUSE = "AutoPause"
    AUTO = "Auto"


@dataclasses.dataclass
class MonitoredMetric:
    """One entry of DeploymentMetadata.spec.metrics.monitoring
    (types.go:74-90): the metric to watch plus its brain-side type (keys
    the per-type threshold table) and its alias in the job payload."""

    metric_name: str
    metric_type: str = ""
    metric_alias: str = ""

    @staticmethod
    def from_json(d: Mapping[str, Any]) -> "MonitoredMetric":
        return MonitoredMetric(
            metric_name=d.get("metricName", ""),
            metric_type=d.get("metricType", ""),
            metric_alias=d.get("metricAlias", "") or d.get("metricName", ""),
        )

    def to_json(self) -> dict[str, Any]:
        return {
            "metricName": self.metric_name,
            "metricType": self.metric_type,
            "metricAlias": self.metric_alias or self.metric_name,
        }


@dataclasses.dataclass
class DeploymentMetadata:
    """Per-app configuration CR (types.go:14-156)."""

    name: str
    namespace: str
    analyst_endpoint: str = ""
    metrics_source: str = "prometheus"  # only supported source, metricsquery.go:96
    metrics_endpoint: str = ""
    monitoring: list[MonitoredMetric] = dataclasses.field(default_factory=list)
    logs: dict[str, Any] = dataclasses.field(default_factory=dict)
    descriptor: dict[str, Any] = dataclasses.field(default_factory=dict)

    @staticmethod
    def from_json(obj: Mapping[str, Any]) -> "DeploymentMetadata":
        meta = obj.get("metadata", {})
        spec = obj.get("spec", {})
        metrics = spec.get("metrics", {})
        return DeploymentMetadata(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", ""),
            analyst_endpoint=(spec.get("analyst") or {}).get("endpoint", ""),
            metrics_source=metrics.get("source", "prometheus"),
            metrics_endpoint=metrics.get("endpoint", ""),
            monitoring=[
                MonitoredMetric.from_json(m) for m in metrics.get("monitoring", []) or []
            ],
            logs=dict(spec.get("logs", {}) or {}),
            descriptor=dict(spec.get("descriptor", {}) or {}),
        )

    def to_json(self) -> dict[str, Any]:
        return {
            "apiVersion": API_VERSION,
            "kind": "DeploymentMetadata",
            "metadata": {"name": self.name, "namespace": self.namespace},
            "spec": {
                "analyst": {"endpoint": self.analyst_endpoint},
                "metrics": {
                    "source": self.metrics_source,
                    "endpoint": self.metrics_endpoint,
                    "monitoring": [m.to_json() for m in self.monitoring],
                },
                "logs": self.logs,
                "descriptor": self.descriptor,
            },
        }

    def metric_names(self) -> dict[str, str]:
        """alias -> metricName map consumed by the query builder."""
        return {(m.metric_alias or m.metric_name): m.metric_name for m in self.monitoring}


@dataclasses.dataclass
class Remediation:
    option: str = RemediationOption.NONE
    parameters: dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class MonitorStatus:
    """DeploymentMonitor.status (types.go:223-240)."""

    job_id: str = ""
    phase: str = ""
    remediation_taken: bool = False
    # alias -> {"tags": str, "values": [{"time": t, "value": v}, ...]} —
    # the typed form barrelman decodes from the flat pairs
    # (Barrelman.go:593-620).
    anomaly: dict[str, Any] = dataclasses.field(default_factory=dict)
    timestamp: str = ""
    expired: bool = False

    def to_json(self) -> dict[str, Any]:
        return {
            "jobId": self.job_id,
            "phase": self.phase,
            "remediationTaken": self.remediation_taken,
            "anomaly": self.anomaly,
            "timestamp": self.timestamp,
            "expired": self.expired,
        }

    @staticmethod
    def from_json(d: Mapping[str, Any]) -> "MonitorStatus":
        return MonitorStatus(
            job_id=d.get("jobId", ""),
            phase=d.get("phase", ""),
            remediation_taken=bool(d.get("remediationTaken", False)),
            anomaly=dict(d.get("anomaly", {}) or {}),
            timestamp=d.get("timestamp", ""),
            expired=bool(d.get("expired", False)),
        )


@dataclasses.dataclass
class DeploymentMonitor:
    """Per-deployment monitoring CR (types.go:175-295)."""

    name: str
    namespace: str
    selector: dict[str, str] = dataclasses.field(default_factory=dict)
    analyst_endpoint: str = ""
    start_time: str = ""
    wait_until: str = ""
    metrics: dict[str, Any] = dataclasses.field(default_factory=dict)
    continuous: bool = False
    remediation: Remediation = dataclasses.field(default_factory=Remediation)
    rollback_revision: int = 0
    status: MonitorStatus = dataclasses.field(default_factory=MonitorStatus)

    @staticmethod
    def from_json(obj: Mapping[str, Any]) -> "DeploymentMonitor":
        meta = obj.get("metadata", {})
        spec = obj.get("spec", {})
        rem = spec.get("remediation", {}) or {}
        return DeploymentMonitor(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", ""),
            selector=dict(spec.get("selector", {}) or {}),
            analyst_endpoint=(spec.get("analyst") or {}).get("endpoint", ""),
            start_time=spec.get("startTime", ""),
            wait_until=spec.get("waitUntil", ""),
            metrics=dict(spec.get("metrics", {}) or {}),
            continuous=bool(spec.get("continuous", False)),
            remediation=Remediation(
                option=rem.get("option", RemediationOption.NONE),
                parameters=dict(rem.get("parameters", {}) or {}),
            ),
            rollback_revision=int(spec.get("rollbackRevision", 0) or 0),
            status=MonitorStatus.from_json(obj.get("status", {}) or {}),
        )

    def to_json(self) -> dict[str, Any]:
        return {
            "apiVersion": API_VERSION,
            "kind": "DeploymentMonitor",
            "metadata": {"name": self.name, "namespace": self.namespace},
            "spec": {
                "selector": self.selector,
                "analyst": {"endpoint": self.analyst_endpoint},
                "startTime": self.start_time,
                "waitUntil": self.wait_until,
                "metrics": self.metrics,
                "continuous": self.continuous,
                "remediation": {
                    "option": self.remediation.option,
                    "parameters": self.remediation.parameters,
                },
                "rollbackRevision": self.rollback_revision,
            },
            "status": self.status.to_json(),
        }
