"""Pluggable Kubernetes API for the watch plane.

The reference talks to K8s through client-go clientsets + generated CRD
clients (`foremast-barrelman/pkg/client/`, ~2,200 LoC of codegen). Here the
same surface is a small protocol with two implementations:

* ``InMemoryKube`` — the test substrate, replacing the reference's
  generated fake clientsets
  (`pkg/client/clientset/versioned/fake/clientset_generated.go`).
* ``HttpKube`` — a direct REST client against the API server using the
  in-cluster service-account credentials (no kubernetes python package in
  the image; the API surface needed is tiny).

Builtin objects (Deployment / ReplicaSet / Pod / Namespace) are handled in
their K8s wire form (plain dicts); the two foremast CRDs are typed
(`crds.py`).
"""

from __future__ import annotations

import copy
import json
import os
import ssl
import urllib.parse
import urllib.request
from typing import Any, Callable, Iterable, Protocol

from foremast_tpu.watch.crds import (
    API_VERSION,
    GROUP,
    VERSION,
    DeploymentMetadata,
    DeploymentMonitor,
)


class NotFound(KeyError):
    """Object absent — the analogue of a k8s 404 / IsNotFound."""


class WatchGone(Exception):
    """410 Gone from a watch: the resume `resourceVersion` fell out of
    the API server's event window — the stream cannot resume and the
    watcher must RE-LIST (client-go's ErrResourceExpired → reflector
    relist). Raised both for an immediate 410 answer and for the
    mid-stream ``{"type": "ERROR", ...code 410}`` event."""


class KubeClient(Protocol):
    # builtin workloads ---------------------------------------------------
    def list_namespaces(self) -> list[dict]: ...
    def get_namespace(self, name: str) -> dict: ...
    def list_deployments(self, namespace: str | None = None) -> list[dict]: ...
    def get_deployment(self, namespace: str, name: str) -> dict: ...
    def patch_deployment(self, namespace: str, name: str, patch: dict) -> dict: ...
    def list_replicasets(self, namespace: str) -> list[dict]: ...
    def list_pods(self, namespace: str) -> list[dict]: ...
    def create_event(self, namespace: str, event: dict) -> dict: ...

    # foremast CRDs -------------------------------------------------------
    def get_metadata(self, namespace: str, name: str) -> DeploymentMetadata: ...
    def list_monitors(self, namespace: str | None = None) -> list[DeploymentMonitor]: ...
    def get_monitor(self, namespace: str, name: str) -> DeploymentMonitor: ...
    def upsert_monitor(self, monitor: DeploymentMonitor) -> DeploymentMonitor: ...
    def patch_monitor(
        self, namespace: str, name: str, patch: dict
    ) -> DeploymentMonitor: ...
    def delete_monitor(self, namespace: str, name: str) -> None: ...


# ---------------------------------------------------------------------------
# helpers shared by both implementations and by Barrelman
# ---------------------------------------------------------------------------


def owner_uids(obj: dict) -> set[str]:
    return {
        ref.get("uid", "")
        for ref in obj.get("metadata", {}).get("ownerReferences", []) or []
    }


def deployment_containers(dep: dict) -> list[dict]:
    return (
        dep.get("spec", {})
        .get("template", {})
        .get("spec", {})
        .get("containers", [])
        or []
    )


def deployment_revision(dep: dict) -> int:
    """`deployment.kubernetes.io/revision` annotation as int (0 if unset)."""
    ann = dep.get("metadata", {}).get("annotations", {}) or {}
    try:
        return int(ann.get("deployment.kubernetes.io/revision", 0))
    except (TypeError, ValueError):
        return 0


# ---------------------------------------------------------------------------
# In-memory fake
# ---------------------------------------------------------------------------


class InMemoryKube:
    """Dict-backed kube API with synchronous event delivery.

    Tests seed namespaces/deployments/replicasets/pods, register handlers
    (the informer-equivalent), and mutate objects through
    ``apply_deployment`` to fire add/update events — covering what the
    reference exercised through client-go informers + fake clientsets.
    """

    def __init__(self) -> None:
        self.namespaces: dict[str, dict] = {}
        self.deployments: dict[tuple[str, str], dict] = {}
        self.replicasets: dict[tuple[str, str], dict] = {}
        self.pods: dict[tuple[str, str], dict] = {}
        self.metadatas: dict[tuple[str, str], DeploymentMetadata] = {}
        self.monitors: dict[tuple[str, str], DeploymentMonitor] = {}
        self.deployment_handlers: list[Callable[[str, dict, dict | None], None]] = []
        self.monitor_handlers: list[
            Callable[[str, DeploymentMonitor, DeploymentMonitor | None], None]
        ] = []
        # audit trail of (verb, kind, namespace, name, detail) for asserts
        self.actions: list[tuple[str, str, str, str, Any]] = []
        self.events: list[dict] = []

    def create_event(self, namespace: str, event: dict) -> dict:
        self.events.append(event)
        return event

    # --- seeding / events ------------------------------------------------

    def add_namespace(self, name: str, annotations: dict | None = None) -> None:
        self.namespaces[name] = {
            "metadata": {"name": name, "annotations": annotations or {}}
        }

    def on_deployment(self, fn: Callable[[str, dict, dict | None], None]) -> None:
        self.deployment_handlers.append(fn)

    def on_monitor(
        self, fn: Callable[[str, DeploymentMonitor, DeploymentMonitor | None], None]
    ) -> None:
        self.monitor_handlers.append(fn)

    def apply_deployment(self, dep: dict) -> None:
        """Create or update a Deployment and fire the informer event."""
        meta = dep["metadata"]
        key = (meta["namespace"], meta["name"])
        old = self.deployments.get(key)
        self.deployments[key] = dep
        event = "update" if old is not None else "add"
        for fn in list(self.deployment_handlers):
            fn(event, dep, old)

    def remove_deployment(self, namespace: str, name: str) -> None:
        dep = self.deployments.pop((namespace, name), None)
        if dep is not None:
            for fn in list(self.deployment_handlers):
                fn("delete", dep, None)

    def add_replicaset(self, rs: dict) -> None:
        meta = rs["metadata"]
        self.replicasets[(meta["namespace"], meta["name"])] = rs

    def add_pod(self, pod: dict) -> None:
        meta = pod["metadata"]
        self.pods[(meta["namespace"], meta["name"])] = pod

    def add_metadata(self, md: DeploymentMetadata) -> None:
        self.metadatas[(md.namespace, md.name)] = md

    # --- KubeClient ------------------------------------------------------

    def list_namespaces(self) -> list[dict]:
        return list(self.namespaces.values())

    def get_namespace(self, name: str) -> dict:
        try:
            return self.namespaces[name]
        except KeyError:
            raise NotFound(name)

    def list_deployments(self, namespace: str | None = None) -> list[dict]:
        return [
            d
            for (ns, _), d in self.deployments.items()
            if namespace is None or ns == namespace
        ]

    def get_deployment(self, namespace: str, name: str) -> dict:
        try:
            return self.deployments[(namespace, name)]
        except KeyError:
            raise NotFound(f"{namespace}/{name}")

    def patch_deployment(self, namespace: str, name: str, patch: dict) -> dict:
        dep = self.get_deployment(namespace, name)
        old = copy.deepcopy(dep)  # handlers must see the pre-patch object
        _deep_merge(dep, patch)
        self.actions.append(("patch", "Deployment", namespace, name, patch))
        for fn in list(self.deployment_handlers):
            fn("update", dep, old)
        return dep

    def list_replicasets(self, namespace: str) -> list[dict]:
        return [r for (ns, _), r in self.replicasets.items() if ns == namespace]

    def list_pods(self, namespace: str) -> list[dict]:
        return [p for (ns, _), p in self.pods.items() if ns == namespace]

    def get_metadata(self, namespace: str, name: str) -> DeploymentMetadata:
        try:
            return self.metadatas[(namespace, name)]
        except KeyError:
            raise NotFound(f"{namespace}/{name}")

    def list_monitors(self, namespace: str | None = None) -> list[DeploymentMonitor]:
        return [
            m
            for (ns, _), m in self.monitors.items()
            if namespace is None or ns == namespace
        ]

    def get_monitor(self, namespace: str, name: str) -> DeploymentMonitor:
        try:
            return self.monitors[(namespace, name)]
        except KeyError:
            raise NotFound(f"{namespace}/{name}")

    def upsert_monitor(self, monitor: DeploymentMonitor) -> DeploymentMonitor:
        key = (monitor.namespace, monitor.name)
        old = self.monitors.get(key)
        self.monitors[key] = monitor
        self.actions.append(
            ("update" if old else "create", "DeploymentMonitor", *key, None)
        )
        for fn in list(self.monitor_handlers):
            fn("update" if old else "add", monitor, old)
        return monitor

    def patch_monitor(
        self, namespace: str, name: str, patch: dict
    ) -> DeploymentMonitor:
        """Merge-patch a monitor (what `kubectl patch --type=merge` does):
        only the patched fields change, concurrent writers are preserved."""
        old = self.get_monitor(namespace, name)
        # to_json() returns the monitor's live dicts by reference; deepcopy
        # before merging so handlers see the true pre-patch object (same
        # reason patch_deployment deepcopies).
        obj = copy.deepcopy(old.to_json())
        _deep_merge(obj, patch)
        merged = DeploymentMonitor.from_json(obj)
        self.monitors[(namespace, name)] = merged
        self.actions.append(("patch", "DeploymentMonitor", namespace, name, patch))
        for fn in list(self.monitor_handlers):
            fn("update", merged, old)
        return merged

    def delete_monitor(self, namespace: str, name: str) -> None:
        m = self.monitors.pop((namespace, name), None)
        if m is not None:
            self.actions.append(("delete", "DeploymentMonitor", namespace, name, None))
            for fn in list(self.monitor_handlers):
                fn("delete", m, None)


def record_event(
    kube: "KubeClient",
    namespace: str,
    name: str,
    reason: str,
    message: str,
    event_type: str = "Normal",
    kind: str = "Deployment",
) -> None:
    """Emit a corev1 Event against an object (the reference does this via
    an EventBroadcaster, Barrelman.go:272-276 / MonitorController.go:59-63).
    Best-effort: event failures must never affect the control loop."""
    import time

    event = {
        "apiVersion": "v1",
        "kind": "Event",
        "metadata": {
            # client-go convention: unique per emission (a counter would
            # repeat names after restart -> silent 409 drops)
            "name": f"{name}.{time.time_ns():x}",
            "namespace": namespace,
        },
        "involvedObject": {
            "kind": kind,
            "namespace": namespace,
            "name": name,
            "apiVersion": "apps/v1" if kind == "Deployment" else API_VERSION,
        },
        "reason": reason,
        "message": message,
        "type": event_type,
        "source": {"component": "foremast-watch"},
        "count": 1,
    }
    try:
        kube.create_event(namespace, event)
    except Exception:  # noqa: BLE001 - never let event plumbing break control
        import logging

        logging.getLogger("foremast_tpu.watch").debug(
            "event emit failed for %s/%s %s", namespace, name, reason
        )


def _deep_merge(dst: dict, patch: dict) -> None:
    for k, v in patch.items():
        if v is None:
            dst.pop(k, None)  # strategic-merge null deletes the key
        elif isinstance(v, dict) and isinstance(dst.get(k), dict):
            _deep_merge(dst[k], v)
        else:
            dst[k] = v


# ---------------------------------------------------------------------------
# In-cluster REST client
# ---------------------------------------------------------------------------

_SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class HttpKube:
    """Direct REST client for the API server (in-cluster credentials).

    Covers the verbs the control plane needs: list/get/patch on apps/v1
    Deployments, list on ReplicaSets/Pods/Namespaces, CRUD on the two
    foremast CRDs. Uses blocking urllib (call sites run it via
    ``asyncio.to_thread`` when inside the event loop).

    Robustness (ISSUE 9 satellite — this was the last HTTP client with
    neither timeouts nor a retry policy): every request carries an
    explicit socket timeout (`timeout`, env
    ``FOREMAST_KUBE_TIMEOUT_SECONDS``, covering connect AND read — the
    urllib timeout applies to each blocking socket op), and transient
    failures on GETs retry with jittered exponential backoff under
    exactly `PrometheusSource`'s classification: HTTP 429/5xx +
    connection/timeout errors retry (`retries`, env
    ``FOREMAST_FETCH_RETRIES``), hard 4xx fails fast (404 stays
    `NotFound`). Writes (POST/PUT/PATCH/DELETE) stay single-shot: a
    timeout is AMBIGUOUS — the server may have committed — so a blind
    retry could duplicate an Event or turn a committed PUT into a
    spurious 409; the control loop's own reconcile cycle is the retry
    for writes. `chaos`/`breaker` (both default None = pass-through)
    are the ISSUE 9 seams at the one request choke point.
    """

    def __init__(
        self,
        base_url: str | None = None,
        token: str | None = None,
        ca_file: str | None = None,
        timeout: float | None = None,
        retries: int | None = None,
        backoff_seconds: float = 0.25,
        chaos=None,
        breaker=None,
    ) -> None:
        host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default.svc")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        self.base_url = (base_url or f"https://{host}:{port}").rstrip("/")
        if token is None and os.path.exists(f"{_SA_DIR}/token"):
            with open(f"{_SA_DIR}/token") as f:
                token = f.read().strip()
        self.token = token
        ca = ca_file or (f"{_SA_DIR}/ca.crt" if os.path.exists(f"{_SA_DIR}/ca.crt") else None)
        self._ctx = ssl.create_default_context(cafile=ca) if ca else None
        if timeout is None:
            timeout = float(
                os.environ.get("FOREMAST_KUBE_TIMEOUT_SECONDS", "") or 30.0
            )
        self.timeout = timeout
        if retries is None:
            retries = int(os.environ.get("FOREMAST_FETCH_RETRIES", "") or 2)
        self.retries = max(0, int(retries))
        self.backoff_seconds = float(backoff_seconds)
        self.chaos = chaos
        self.breaker = breaker

    # the transient-status set shared with PrometheusSource: throttling
    # and server-side failures retry; configuration errors fail fast
    def _req(self, method: str, path: str, body: dict | None = None,
             content_type: str = "application/json") -> dict:
        import random as _random
        import time as _time

        from foremast_tpu.metrics.source import RETRY_STATUSES

        url = f"{self.base_url}{path}"
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Accept", "application/json")
        if data is not None:
            req.add_header("Content-Type", content_type)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        breaker = self.breaker
        if breaker is not None:
            breaker.allow()
        # non-idempotent verbs never retry (see class docstring)
        retries = self.retries if method == "GET" else 0
        for attempt in range(retries + 1):
            last = attempt == retries
            try:
                if self.chaos is not None:
                    self.chaos.perturb(path)
                with urllib.request.urlopen(
                    req, context=self._ctx, timeout=self.timeout
                ) as resp:
                    out = json.loads(resp.read() or b"{}")
                if breaker is not None:
                    breaker.record_success()
                return out
            except urllib.error.HTTPError as e:
                code = e.code
                e.close()
                if code not in RETRY_STATUSES:
                    # the API server ANSWERED: the endpoint is alive
                    # regardless of what it thought of the request
                    if breaker is not None:
                        breaker.record_success()
                    if code == 404:
                        raise NotFound(path) from None
                    raise
                if last:
                    if breaker is not None:
                        breaker.record_failure()
                    raise
            except OSError:
                # URLError (connection refused/reset/DNS), socket
                # timeouts, and injected chaos faults all land here
                if last:
                    if breaker is not None:
                        breaker.record_failure()
                    raise
            _time.sleep(
                self.backoff_seconds
                * (2**attempt)
                * (0.5 + 0.5 * _random.random())
            )
        raise AssertionError("unreachable")  # pragma: no cover

    # --- streaming watch (reactive plane, ISSUE 12) ----------------------

    def _deployments_path(self, namespace: str | None) -> str:
        return (
            f"/apis/apps/v1/namespaces/{namespace}/deployments"
            if namespace
            else "/apis/apps/v1/deployments"
        )

    def list_deployments_rv(
        self, namespace: str | None = None
    ) -> tuple[list[dict], str]:
        """One list round trip returning (items, list resourceVersion) —
        the watch resume point (a plain `list_deployments` throws the
        list's own resourceVersion away, forcing the first watch to
        start from "now" and miss anything between list and watch)."""
        out = self._req("GET", self._deployments_path(namespace))
        return out.get("items", []), str(
            (out.get("metadata") or {}).get("resourceVersion") or ""
        )

    def watch_deployments(
        self,
        namespace: str | None = None,
        resource_version: str = "",
        timeout_seconds: float = 30.0,
        stall_margin: float = 5.0,
    ):
        """Long-poll streaming watch (``?watch=true``): yields
        ``(type, object)`` pairs — type ADDED/MODIFIED/DELETED — as the
        API server writes them, until the server closes the window
        (``timeoutSeconds``) or the stream dies.

        Semantics mirror client-go's reflector contract:

          * a 410 answer OR a mid-stream ERROR event with code 410
            raises `WatchGone` — the caller must re-list (the informer
            diffs the fresh list against its snapshot, so no event is
            lost, only batched);
          * a stream STALL (the server stops writing without closing —
            half-open TCP, wedged proxy) surfaces as the socket read
            timeout: every read blocks at most ``timeout_seconds +
            stall_margin``, so a stalled stream raises `TimeoutError`
            instead of hanging the watcher forever;
          * a torn tail (disconnect mid-JSON-line) ends the stream
            cleanly at the last complete event — the caller resumes
            from the last resourceVersion it APPLIED;
          * chaos/breaker ride the same per-request seam as `_req`
            (edge ``kube``): the op string contains ``watch=true`` so
            plans can scope stream-stall rules to the watch alone. No
            retry loop here — the informer's reconnect IS the retry.
        """
        # the apiserver takes integer seconds (min 1); the client's
        # stall detector must measure from the window actually SENT,
        # or a sub-second request would read its own rounding as a
        # stalled stream
        window = max(1, int(round(timeout_seconds)))
        q = f"?watch=true&timeoutSeconds={window}"
        if resource_version:
            q += f"&resourceVersion={urllib.parse.quote(str(resource_version))}"
        path = self._deployments_path(namespace) + q
        url = f"{self.base_url}{path}"
        req = urllib.request.Request(url, method="GET")
        req.add_header("Accept", "application/json")
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        from foremast_tpu.metrics.source import RETRY_STATUSES

        breaker = self.breaker
        if breaker is not None:
            breaker.allow()
        try:
            # perturb INSIDE the try (the _req composition): an
            # injected fault is a ConnectionError and must land in the
            # OSError arm below so it drives breaker accounting — and a
            # half-open probe granted by allow() always records an
            # outcome
            if self.chaos is not None:
                self.chaos.perturb(path)
            resp = urllib.request.urlopen(
                req,
                context=self._ctx,
                timeout=window + max(0.0, float(stall_margin)),
            )
        except urllib.error.HTTPError as e:
            code = e.code
            e.close()
            if breaker is not None:
                # _req's policy: the API server ANSWERED, so outside
                # the transient statuses the endpoint is alive — a 403
                # on the watch path must not open the shared kube
                # breaker for the whole controller
                if code in RETRY_STATUSES:
                    breaker.record_failure()
                else:
                    breaker.record_success()
            if code == 410:
                raise WatchGone(path) from None
            raise
        except OSError:
            if breaker is not None:
                breaker.record_failure()
            raise
        if breaker is not None:
            breaker.record_success()
        import http.client as _http_client

        with resp:
            while True:
                try:
                    raw = resp.readline()
                except _http_client.HTTPException:
                    # a REAL apiserver streams chunked; a connection
                    # torn mid-chunk raises IncompleteRead (NOT an
                    # OSError) — same torn-tail semantics: end at the
                    # last complete event, resume from the applied rv
                    return
                if not raw:
                    return  # clean window end
                line = raw.strip()
                if not line:
                    continue
                try:
                    evt = json.loads(line)
                except ValueError:
                    return  # torn tail: resume from the last applied rv
                etype = evt.get("type", "")
                obj = evt.get("object") or {}
                if etype == "ERROR":
                    if int(obj.get("code", 0) or 0) == 410:
                        raise WatchGone(path)
                    # a server-side failure event (etcd leader change,
                    # internal error): surface as a connection-class
                    # error so the informer counts an ERROR restart,
                    # not a benign clean end
                    raise ConnectionError(
                        f"watch ERROR event on {path}: {obj}"
                    )
                yield etype, obj

    # --- builtin workloads ----------------------------------------------

    def list_namespaces(self) -> list[dict]:
        return self._req("GET", "/api/v1/namespaces").get("items", [])

    def get_namespace(self, name: str) -> dict:
        return self._req("GET", f"/api/v1/namespaces/{name}")

    def list_deployments(self, namespace: str | None = None) -> list[dict]:
        return self._req("GET", self._deployments_path(namespace)).get(
            "items", []
        )

    def get_deployment(self, namespace: str, name: str) -> dict:
        return self._req("GET", f"/apis/apps/v1/namespaces/{namespace}/deployments/{name}")

    def patch_deployment(self, namespace: str, name: str, patch: dict) -> dict:
        return self._req(
            "PATCH",
            f"/apis/apps/v1/namespaces/{namespace}/deployments/{name}",
            patch,
            content_type="application/strategic-merge-patch+json",
        )

    def list_replicasets(self, namespace: str) -> list[dict]:
        return self._req(
            "GET", f"/apis/apps/v1/namespaces/{namespace}/replicasets"
        ).get("items", [])

    def list_pods(self, namespace: str) -> list[dict]:
        return self._req("GET", f"/api/v1/namespaces/{namespace}/pods").get("items", [])

    def create_event(self, namespace: str, event: dict) -> dict:
        return self._req("POST", f"/api/v1/namespaces/{namespace}/events", event)

    # --- foremast CRDs ---------------------------------------------------

    def _crd_path(self, plural: str, namespace: str | None, name: str | None = None) -> str:
        p = f"/apis/{GROUP}/{VERSION}"
        if namespace:
            p += f"/namespaces/{namespace}"
        p += f"/{plural}"
        if name:
            p += f"/{urllib.parse.quote(name)}"
        return p

    def get_metadata(self, namespace: str, name: str) -> DeploymentMetadata:
        obj = self._req("GET", self._crd_path("deploymentmetadatas", namespace, name))
        return DeploymentMetadata.from_json(obj)

    def list_monitors(self, namespace: str | None = None) -> list[DeploymentMonitor]:
        items = self._req("GET", self._crd_path("deploymentmonitors", namespace)).get(
            "items", []
        )
        return [DeploymentMonitor.from_json(o) for o in items]

    def get_monitor(self, namespace: str, name: str) -> DeploymentMonitor:
        obj = self._req("GET", self._crd_path("deploymentmonitors", namespace, name))
        return DeploymentMonitor.from_json(obj)

    def upsert_monitor(self, monitor: DeploymentMonitor) -> DeploymentMonitor:
        path = self._crd_path("deploymentmonitors", monitor.namespace, monitor.name)
        try:
            existing = self._req("GET", path)
            body = monitor.to_json()
            body["metadata"]["resourceVersion"] = existing["metadata"].get(
                "resourceVersion", ""
            )
            return DeploymentMonitor.from_json(self._req("PUT", path, body))
        except NotFound:
            return DeploymentMonitor.from_json(
                self._req(
                    "POST",
                    self._crd_path("deploymentmonitors", monitor.namespace),
                    monitor.to_json(),
                )
            )

    def patch_monitor(
        self, namespace: str, name: str, patch: dict
    ) -> DeploymentMonitor:
        obj = self._req(
            "PATCH",
            self._crd_path("deploymentmonitors", namespace, name),
            patch,
            content_type="application/merge-patch+json",
        )
        return DeploymentMonitor.from_json(obj)

    def delete_monitor(self, namespace: str, name: str) -> None:
        try:
            self._req("DELETE", self._crd_path("deploymentmonitors", namespace, name))
        except NotFound:
            pass
