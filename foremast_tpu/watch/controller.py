"""Status poller + remediation — the MonitorController equivalent.

Parity with `foremast-barrelman/pkg/controller/`:

* 10-second poll tick (Barrelman.go:467-472): for every Running monitor,
  GET job status from the analyst, map to a phase, decode the anomaly
  payload, expire jobs past waitUntil as Healthy+expired
  (checkRunningStatus, Barrelman.go:496-591).
* anomaly decoding: flat [t1,v1,t2,v2,...] pairs -> typed
  [{"time": t, "value": v}] lists (convertToAnomaly, Barrelman.go:593-620).
* remediation on transition to Unhealthy with remediationTaken==false,
  dispatched by spec.remediation.option (MonitorController.go:85-148):
  AutoRollback -> roll the Deployment's pod template back to the
  rollbackRevision ReplicaSet (the reference used the long-removed
  extensions/v1beta1 DeploymentRollback, MonitorController.go:214-229;
  the template-patch below is the apps/v1 equivalent); AutoPause -> set
  spec.paused (MonitorController.go:254-281); Auto -> no-op
  (MonitorController.go:283-286).
* continuous re-arm each tick while phase != Running, with a 60 s backoff
  after Unhealthy (Barrelman.go:576-586, MonitorController.go:138-147).
"""

from __future__ import annotations

import logging
import time as _time
from typing import Callable

from foremast_tpu.jobs.store import now_rfc3339, parse_time
from foremast_tpu.observe.logs import ctx_log
from foremast_tpu.observe.spans import counter, span
from foremast_tpu.watch.analyst import AnalystClient, HttpAnalyst
from foremast_tpu.watch.barrelman import Barrelman
from foremast_tpu.watch.crds import (
    ROLLBACK_ANNOTATION,
    DeploymentMonitor,
    MonitorPhase,
    RemediationOption,
)
from foremast_tpu.watch.kubeapi import (
    KubeClient,
    NotFound,
    deployment_revision,
    owner_uids,
    record_event,
)

log = logging.getLogger("foremast_tpu.watch")

POLL_PERIOD_SECONDS = 10.0  # Barrelman.go:467
UNHEALTHY_REARM_BACKOFF = 60.0  # MonitorController.go:138-147


def convert_to_anomaly(payload: dict) -> dict:
    """Flat-pair wire form -> typed form (Barrelman.go:593-620).

    In:  {"tags": t, "values": {alias: [t1, v1, t2, v2, ...]}}
         (AnomalyInfo, models.go:60-80)
    Out: {alias: {"tags": t, "values": [{"time": t1, "value": v1}, ...]}}
    """
    out: dict = {}
    tags = (payload or {}).get("tags", "")
    for alias, flat in ((payload or {}).get("values") or {}).items():
        flat = flat or []
        pairs = [
            {"time": flat[i], "value": flat[i + 1]}
            for i in range(0, len(flat) - 1, 2)
        ]
        out[alias] = {"tags": tags, "values": pairs}
    return out




class MonitorController:
    def __init__(
        self,
        kube: KubeClient,
        barrelman: Barrelman | None = None,
        analyst_factory: Callable[[str], AnalystClient] | None = None,
        clock: Callable[[], float] = _time.time,
        tracer=None,
        registry=None,
    ) -> None:
        self.kube = kube
        self.barrelman = barrelman
        self.analyst_factory = analyst_factory or (
            barrelman.analyst_factory if barrelman else HttpAnalyst
        )
        self.clock = clock
        self._unhealthy_since: dict[tuple[str, str], float] = {}
        # span tracer (observe/spans.py): each monitor poll opens a root
        # span; transition/rollback/pause spans nest under it so a
        # remediation's latency is attributable on the same timeline as
        # the worker's judgment stages
        self.tracer = tracer
        self.transitions = counter(
            "foremast_controller_transitions_total",
            "DeploymentMonitor phase transitions observed by the poller",
            ("phase",),
            registry,
        )

    # ------------------------------------------------------------------
    # poll tick (checkRunningStatus)
    # ------------------------------------------------------------------

    def tick(self) -> None:
        for monitor in self.kube.list_monitors():
            try:
                self._check_monitor(monitor)
            except Exception:  # noqa: BLE001 - one bad monitor must not stop the tick
                log.exception(
                    "poll failed for %s/%s", monitor.namespace, monitor.name
                )

    def _check_monitor(self, monitor: DeploymentMonitor) -> None:
        phase = monitor.status.phase
        if phase == MonitorPhase.RUNNING and monitor.status.job_id:
            self._poll_running(monitor)
        elif monitor.continuous:
            self._rearm_continuous(monitor)

    def _poll_running(self, monitor: DeploymentMonitor) -> None:
        if self.tracer is None:
            return self._poll_running_inner(monitor)
        with self.tracer.span(
            "controller.poll",
            stage="poll",
            namespace=monitor.namespace,
            monitor=monitor.name,
            job_id=monitor.status.job_id,
        ):
            return self._poll_running_inner(monitor)

    def _poll_running_inner(self, monitor: DeploymentMonitor) -> None:
        now = self.clock()
        wait_until = parse_time(monitor.wait_until)
        try:
            with span(
                "controller.get_status",
                stage="get_status",
                job_id=monitor.status.job_id,
            ):
                status = self.analyst_factory(
                    monitor.analyst_endpoint
                ).get_status(monitor.status.job_id)
            new_phase = status.phase
        except Exception as e:  # noqa: BLE001 - analyst down must not stall expiry
            log.warning(
                "get_status failed for %s/%s: %s", monitor.namespace, monitor.name, e
            )
            status, new_phase = None, MonitorPhase.RUNNING
        if new_phase == MonitorPhase.RUNNING:
            if wait_until and now > wait_until:
                # expiry defaults the job to Healthy (Barrelman.go:556-565)
                monitor.status.phase = MonitorPhase.HEALTHY
                monitor.status.expired = True
                monitor.status.timestamp = now_rfc3339()
                self.transitions.labels(phase=MonitorPhase.HEALTHY).inc()
                self.kube.upsert_monitor(monitor)
            return
        old_phase = monitor.status.phase
        # "update", not "transition": this span times every poll's
        # status write-back + remediation dispatch, most of which merely
        # re-assert the current phase
        with span(
            "controller.update",
            stage="update",
            phase=new_phase,
            changed=new_phase != old_phase,
            namespace=monitor.namespace,
            monitor=monitor.name,
        ):
            monitor.status.phase = new_phase
            monitor.status.timestamp = now_rfc3339()
            # count/log PHASE CHANGES only — every poll re-asserts the
            # current phase, and a rate() over re-assertions would just
            # measure poll frequency
            if new_phase != old_phase:
                self.transitions.labels(phase=new_phase).inc()
                ctx_log(
                    log,
                    logging.INFO,
                    "monitor transition",
                    namespace=monitor.namespace,
                    monitor=monitor.name,
                    phase=new_phase,
                    from_phase=old_phase,
                    job_id=monitor.status.job_id,
                )
            if status.anomaly:
                monitor.status.anomaly = convert_to_anomaly(status.anomaly)
            self.kube.upsert_monitor(monitor)
            self.handle_transition(monitor)

    # ------------------------------------------------------------------
    # remediation (MonitorController informer UpdateFunc)
    # ------------------------------------------------------------------

    def handle_transition(self, monitor: DeploymentMonitor) -> None:
        if monitor.status.phase != MonitorPhase.UNHEALTHY:
            return
        self._unhealthy_since[(monitor.namespace, monitor.name)] = self.clock()
        record_event(
            self.kube,
            monitor.namespace,
            monitor.name,
            reason="Unhealthy",
            message=f"health analysis job {monitor.status.job_id} detected anomalies",
            event_type="Warning",
        )
        if monitor.status.remediation_taken:
            return
        option = monitor.remediation.option
        if option == RemediationOption.AUTO_ROLLBACK:
            self.rollback(monitor)
        elif option == RemediationOption.AUTO_PAUSE:
            self.pause(monitor)
        elif option == RemediationOption.AUTO:
            pass  # reference leaves Auto unimplemented (MonitorController.go:283-286)
        else:
            return
        if option in (RemediationOption.AUTO_ROLLBACK, RemediationOption.AUTO_PAUSE):
            monitor.status.remediation_taken = True
            self.kube.upsert_monitor(monitor)

    def rollback(self, monitor: DeploymentMonitor) -> None:
        """Roll the Deployment back to spec.rollbackRevision by patching
        its pod template from that revision's ReplicaSet
        (MonitorController.go:172-238, apps/v1 form)."""
        with span(
            "controller.rollback",
            stage="rollback",
            namespace=monitor.namespace,
            monitor=monitor.name,
        ):
            self._rollback_inner(monitor)

    def _rollback_inner(self, monitor: DeploymentMonitor) -> None:
        try:
            dep = self.kube.get_deployment(monitor.namespace, monitor.name)
        except NotFound:
            log.warning("rollback target %s/%s gone", monitor.namespace, monitor.name)
            return
        target = monitor.rollback_revision
        dep_uid = dep.get("metadata", {}).get("uid", "")
        candidates = [
            rs
            for rs in self.kube.list_replicasets(monitor.namespace)
            if dep_uid in owner_uids(rs)
            and (target == 0 or deployment_revision(rs) == target)
            and deployment_revision(rs) != deployment_revision(dep)
        ]
        if not candidates:
            log.warning(
                "no ReplicaSet at revision %s for %s/%s; rollback skipped",
                target, monitor.namespace, monitor.name,
            )
            return
        candidates.sort(key=deployment_revision)
        rs = candidates[-1]
        template = rs.get("spec", {}).get("template", {})
        # drop the RS-only pod-template-hash label before reuse
        labels = dict(template.get("metadata", {}).get("labels", {}) or {})
        labels.pop("pod-template-hash", None)
        patch = {
            "metadata": {"annotations": {ROLLBACK_ANNOTATION: str(target or deployment_revision(rs))}},
            "spec": {
                "template": {
                    "metadata": {**template.get("metadata", {}), "labels": labels},
                    "spec": template.get("spec", {}),
                }
            },
        }
        self.kube.patch_deployment(monitor.namespace, monitor.name, patch)
        record_event(
            self.kube,
            monitor.namespace,
            monitor.name,
            reason="AutoRollback",
            message=f"rolled back to revision {deployment_revision(rs)} "
            "after unhealthy analysis",
        )
        log.info(
            "rolled back %s/%s to revision %s",
            monitor.namespace, monitor.name, deployment_revision(rs),
        )

    def pause(self, monitor: DeploymentMonitor) -> None:
        """Set spec.paused=true (MonitorController.go:254-281)."""
        with span(
            "controller.pause",
            stage="pause",
            namespace=monitor.namespace,
            monitor=monitor.name,
        ):
            try:
                self.kube.patch_deployment(
                    monitor.namespace, monitor.name, {"spec": {"paused": True}}
                )
                record_event(
                    self.kube,
                    monitor.namespace,
                    monitor.name,
                    reason="AutoPause",
                    message="paused rollout after unhealthy analysis",
                )
            except NotFound:
                log.warning(
                    "pause target %s/%s gone", monitor.namespace, monitor.name
                )

    # ------------------------------------------------------------------
    # continuous re-arm
    # ------------------------------------------------------------------

    def _rearm_continuous(self, monitor: DeploymentMonitor) -> None:
        if self.barrelman is None:
            return
        key = (monitor.namespace, monitor.name)
        since = self._unhealthy_since.get(key)
        if since is not None and self.clock() - since < UNHEALTHY_REARM_BACKOFF:
            return
        self._unhealthy_since.pop(key, None)
        self.barrelman.monitor_continuously(monitor)

    # ------------------------------------------------------------------
    # run loop
    # ------------------------------------------------------------------

    def run_forever(self, stop: Callable[[], bool] = lambda: False) -> None:
        while not stop():
            self.tick()
            _time.sleep(POLL_PERIOD_SECONDS)
