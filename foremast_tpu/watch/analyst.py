"""Analyst client — watcher-side REST client for the job gateway.

Parity with `foremast-barrelman/pkg/client/analyst/analystclient.go`:
``start_analyzing`` POSTs an ApplicationHealthAnalyzeRequest to
``<endpoint>create`` (analystclient.go:84-144; the retry-once wrapper lives
at Barrelman.go:819-826 and here in ``Barrelman._start_job``), and
``get_status`` GETs ``id/<jobId>`` then maps the service's external
statuses onto monitor phases (analystclient.go:211-230).

Two implementations: HTTP against a running gateway, and ``LocalAnalyst``
directly over a JobStore — the in-process path used by tests and by
single-binary deployments where watcher + brain share a process.
"""

from __future__ import annotations

import dataclasses
import json
import urllib.error
import urllib.request
from typing import Any, Protocol

from foremast_tpu.jobs.convert import request_to_document
from foremast_tpu.jobs.models import AnalyzeRequest, document_response, status_to_external
from foremast_tpu.jobs.store import JobStore
from foremast_tpu.watch.crds import MonitorPhase

# external service status -> DeploymentMonitor phase (analystclient.go:211-230)
_PHASE = {
    "new": MonitorPhase.RUNNING,
    "inprogress": MonitorPhase.RUNNING,
    "success": MonitorPhase.HEALTHY,
    "anomaly": MonitorPhase.UNHEALTHY,
    "abort": MonitorPhase.ABORT,
}


def status_to_phase(external_status: str) -> str:
    return _PHASE.get(external_status, MonitorPhase.FAILED)


@dataclasses.dataclass
class JobStatus:
    """GetStatus result: phase + reason + the anomaly payload in the flat
    [t1,v1,t2,v2,...] wire form (models.go:60-80)."""

    phase: str
    reason: str = ""
    anomaly: dict[str, Any] = dataclasses.field(default_factory=dict)


class AnalystClient(Protocol):
    def start_analyzing(self, req: AnalyzeRequest) -> str: ...
    def get_status(self, job_id: str) -> JobStatus: ...


class HttpAnalyst:
    """REST client against a foremast-service-compatible gateway."""

    def __init__(self, endpoint: str, timeout: float = 10.0) -> None:
        # endpoint as stored in DeploymentMetadata.spec.analyst.endpoint,
        # e.g. "http://foremast-api-service:8099/v1/healthcheck/"
        self.endpoint = endpoint if endpoint.endswith("/") else endpoint + "/"
        self.timeout = timeout

    def start_analyzing(self, req: AnalyzeRequest) -> str:
        body = json.dumps(req.to_json()).encode()
        r = urllib.request.Request(
            self.endpoint + "create",
            data=body,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(r, timeout=self.timeout) as resp:
            payload = json.loads(resp.read())
        job_id = payload.get("jobId", "")
        if not job_id:
            raise RuntimeError(f"analyst create returned no jobId: {payload}")
        return job_id

    def get_status(self, job_id: str) -> JobStatus:
        try:
            with urllib.request.urlopen(
                self.endpoint + "id/" + job_id, timeout=self.timeout
            ) as resp:
                payload = json.loads(resp.read())
        except urllib.error.HTTPError as e:
            if e.code == 404:  # contract parity with LocalAnalyst
                return JobStatus(phase=MonitorPhase.FAILED, reason="job not found")
            raise
        return JobStatus(
            phase=status_to_phase(payload.get("status", "")),
            reason=payload.get("reason", ""),
            anomaly=payload.get("anomalyInfo") or payload.get("anomaly") or {},
        )


class LocalAnalyst:
    """In-process analyst over a JobStore — no HTTP hop.

    Functionally identical to HttpAnalyst + the gateway's RegisterEntry /
    SearchByID handlers; used by tests and single-process deployments.
    """

    def __init__(self, store: JobStore) -> None:
        self.store = store

    def start_analyzing(self, req: AnalyzeRequest) -> str:
        doc, _created = self.store.create(request_to_document(req))
        return doc.id

    def get_status(self, job_id: str) -> JobStatus:
        doc = self.store.get(job_id)
        if doc is None:
            return JobStatus(phase=MonitorPhase.FAILED, reason="job not found")
        resp = document_response(doc)
        return JobStatus(
            phase=status_to_phase(status_to_external(doc.status)),
            reason=resp.get("reason", ""),
            anomaly=resp.get("anomalyInfo") or {},
        )
