"""The deployed watch-plane process: informer-equivalent loop tying
Barrelman (deployment events) and MonitorController (status poll +
remediation) to a KubeClient.

The reference runs two shared informers — Deployments resynced every 30 s
and DeploymentMonitors polled every 10 s (`cmd/manager/main.go:39-104`,
`Barrelman.go:467-472`). Kubernetes watch streams are an optimization of
list+diff; this plane implements the same event semantics with periodic
lists diffed against a local snapshot (add/update/delete by UID + spec),
which survives API-server reconnects for free and needs no client
machinery. Event *detection* granularity is the resync period, exactly
like a reference informer that missed its watch stream.
"""

from __future__ import annotations

import logging
import time as _time
from typing import Callable

from foremast_tpu.watch.barrelman import Barrelman
from foremast_tpu.watch.controller import MonitorController
from foremast_tpu.watch.kubeapi import KubeClient

log = logging.getLogger("foremast_tpu.watch.plane")

DEPLOY_RESYNC_SECONDS = 30.0  # main.go:58 (deployment informer resync)
MONITOR_POLL_SECONDS = 10.0  # Barrelman.go:467


def _key(dep: dict) -> tuple[str, str]:
    meta = dep.get("metadata", {})
    return meta.get("namespace", ""), meta.get("name", "")


class DeploymentInformer:
    """List+diff informer: emits add/update/delete events with the
    previous object, matching the handler contract of
    Barrelman.handle_deployment."""

    def __init__(
        self,
        kube: KubeClient,
        handler: Callable[[str, dict, dict | None], None],
    ) -> None:
        self.kube = kube
        self.handler = handler
        self._snapshot: dict[tuple[str, str], dict] = {}
        self._primed = False

    def resync(self) -> None:
        current = {_key(d): d for d in self.kube.list_deployments()}
        if not self._primed:
            # first list primes the cache; emit adds so monitors get
            # created for pre-existing Deployments (AddFunc semantics)
            self._primed = True
            for dep in current.values():
                self._emit("add", dep, None)
            self._snapshot = current
            return
        for key, dep in current.items():
            old = self._snapshot.get(key)
            if old is None:
                self._emit("add", dep, None)
            elif dep.get("metadata", {}).get("resourceVersion") != old.get(
                "metadata", {}
            ).get("resourceVersion"):
                self._emit("update", dep, old)
        for key, old in self._snapshot.items():
            if key not in current:
                self._emit("delete", old, None)
        self._snapshot = current

    def _emit(self, event: str, dep: dict, old: dict | None) -> None:
        try:
            self.handler(event, dep, old)
        except Exception:  # noqa: BLE001 - one bad object must not kill the loop
            log.exception("handler failed for %s %s", event, _key(dep))


class WatchPlane:
    """The whole deployed controller: deployment informer + monitor poll."""

    def __init__(
        self,
        kube: KubeClient,
        own_namespace: str = "foremast",
        clock: Callable[[], float] = _time.time,
        sleep: Callable[[float], None] = _time.sleep,
        analyst_factory=None,
        tracer=None,
        registry=None,
    ) -> None:
        self.barrelman = Barrelman(
            kube,
            own_namespace=own_namespace,
            clock=clock,
            analyst_factory=analyst_factory,
        )
        self.controller = MonitorController(
            kube,
            barrelman=self.barrelman,
            clock=clock,
            tracer=tracer,
            registry=registry,
        )
        self.informer = DeploymentInformer(kube, self.barrelman.handle_deployment)
        self.clock = clock
        self.sleep = sleep
        self._started = clock()

    def debug_state(self) -> dict:
        """The /debug/state varz payload for the controller's scrape
        port (observe.start_observe_server): identity, cached informer
        size, and the tracer's poll-stage breakdown."""
        from foremast_tpu import __version__

        state = {
            "component": "controller",
            "version": __version__,
            "uptime_seconds": round(self.clock() - self._started, 1),
            "deployments_cached": len(self.informer._snapshot),
        }
        if self.controller.tracer is not None:
            state["trace"] = self.controller.tracer.debug_state()
        return state

    def step(self, now: float | None = None, last_resync: float = 0.0) -> float:
        """One scheduler step: monitor tick always; deployment resync when
        due. Returns the new last_resync time."""
        now = self.clock() if now is None else now
        if now - last_resync >= DEPLOY_RESYNC_SECONDS or last_resync == 0.0:
            self.informer.resync()
            last_resync = now
        self.controller.tick()
        return last_resync

    def run(self, stop: Callable[[], bool] = lambda: False) -> None:
        last_resync = 0.0
        while not stop():
            try:
                last_resync = self.step(last_resync=last_resync)
            except Exception:  # noqa: BLE001 - keep the control loop alive
                log.exception("watch-plane step failed")
            self.sleep(MONITOR_POLL_SECONDS)
