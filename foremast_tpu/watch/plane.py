"""The deployed watch-plane process: informer-equivalent loop tying
Barrelman (deployment events) and MonitorController (status poll +
remediation) to a KubeClient.

The reference runs two shared informers — Deployments resynced every 30 s
and DeploymentMonitors polled every 10 s (`cmd/manager/main.go:39-104`,
`Barrelman.go:467-472`). Kubernetes watch streams are an optimization of
list+diff; this plane implements the same event semantics with periodic
lists diffed against a local snapshot (add/update/delete by UID + spec),
which survives API-server reconnects for free and needs no client
machinery. Event *detection* granularity is the resync period, exactly
like a reference informer that missed its watch stream.

Since ISSUE 12 the missed watch stream exists: `FOREMAST_WATCH_STREAM=1`
(or ``stream=True``) runs the REACTIVE loop instead — `HttpKube`'s
``watch=true`` long-poll delivers deployment events on arrival
(`reactive/watchstream.py`), resourceVersion resume + 410-Gone re-list
cover the reconnect cases, and the 30 s resync demotes to a repair
sweep that only catches what the stream lost. Detection granularity
drops from the resync period to stream delivery (milliseconds).
"""

from __future__ import annotations

import logging
import time as _time
from typing import Callable

from foremast_tpu.watch.barrelman import Barrelman
from foremast_tpu.watch.controller import MonitorController
from foremast_tpu.watch.kubeapi import KubeClient

log = logging.getLogger("foremast_tpu.watch.plane")

DEPLOY_RESYNC_SECONDS = 30.0  # main.go:58 (deployment informer resync)
MONITOR_POLL_SECONDS = 10.0  # Barrelman.go:467


def _key(dep: dict) -> tuple[str, str]:
    meta = dep.get("metadata", {})
    return meta.get("namespace", ""), meta.get("name", "")


class DeploymentInformer:
    """List+diff informer: emits add/update/delete events with the
    previous object, matching the handler contract of
    Barrelman.handle_deployment."""

    def __init__(
        self,
        kube: KubeClient,
        handler: Callable[[str, dict, dict | None], None],
    ) -> None:
        self.kube = kube
        self.handler = handler
        self._snapshot: dict[tuple[str, str], dict] = {}
        self._primed = False

    def resync(self) -> None:
        self._apply_list(
            {_key(d): d for d in self.kube.list_deployments()}
        )

    def _apply_list(self, current: dict[tuple[str, str], dict]) -> None:
        """Diff a fresh list against the snapshot and emit events — the
        resync body, shared with the streaming informer's repair/
        re-list path (reactive/watchstream.py)."""
        if not self._primed:
            # first list primes the cache; emit adds so monitors get
            # created for pre-existing Deployments (AddFunc semantics)
            self._primed = True
            for dep in current.values():
                self._emit("add", dep, None)
            self._snapshot = current
            return
        for key, dep in current.items():
            old = self._snapshot.get(key)
            if old is None:
                self._emit("add", dep, None)
            elif dep.get("metadata", {}).get("resourceVersion") != old.get(
                "metadata", {}
            ).get("resourceVersion"):
                self._emit("update", dep, old)
        for key, old in self._snapshot.items():
            if key not in current:
                self._emit("delete", old, None)
        self._snapshot = current

    def _emit(self, event: str, dep: dict, old: dict | None) -> None:
        try:
            self.handler(event, dep, old)
        except Exception:  # noqa: BLE001 - one bad object must not kill the loop
            log.exception("handler failed for %s %s", event, _key(dep))


class WatchPlane:
    """The whole deployed controller: deployment informer + monitor poll."""

    def __init__(
        self,
        kube: KubeClient,
        own_namespace: str = "foremast",
        clock: Callable[[], float] = _time.time,
        sleep: Callable[[float], None] = _time.sleep,
        analyst_factory=None,
        tracer=None,
        registry=None,
        stream: bool | None = None,
    ) -> None:
        self.barrelman = Barrelman(
            kube,
            own_namespace=own_namespace,
            clock=clock,
            analyst_factory=analyst_factory,
        )
        self.controller = MonitorController(
            kube,
            barrelman=self.barrelman,
            clock=clock,
            tracer=tracer,
            registry=registry,
        )
        # Event-driven detection (reactive plane, ISSUE 12): `stream`
        # (env FOREMAST_WATCH_STREAM) swaps the list+diff informer for
        # a streaming one — deployment events dispatch on ARRIVAL from
        # the API server's watch stream, the 30 s resync demotes to a
        # repair sweep, and run() takes the event loop below. Requires
        # a kube client that can stream (HttpKube); InMemoryKube keeps
        # the poll loop (its event delivery is already synchronous).
        if stream is None:
            import os as _os

            stream = _os.environ.get("FOREMAST_WATCH_STREAM", "0") == "1"
        self.stream = bool(stream) and hasattr(kube, "watch_deployments")
        if self.stream:
            from foremast_tpu.reactive.watchstream import (
                StreamingInformer,
                WatchStreamMetrics,
            )

            self.informer = StreamingInformer(
                kube,
                self.barrelman.handle_deployment,
                metrics=WatchStreamMetrics(registry=registry),
            )
        else:
            self.informer = DeploymentInformer(
                kube, self.barrelman.handle_deployment
            )
        self.clock = clock
        self.sleep = sleep
        self._started = clock()

    def debug_state(self) -> dict:
        """The /debug/state varz payload for the controller's scrape
        port (observe.start_observe_server): identity, cached informer
        size, and the tracer's poll-stage breakdown."""
        from foremast_tpu import __version__

        state = {
            "component": "controller",
            "version": __version__,
            "uptime_seconds": round(self.clock() - self._started, 1),
            "deployments_cached": len(self.informer._snapshot),
            "watch_stream": self.stream,
        }
        if self.stream:
            state["stream"] = self.informer.debug_state()
        if self.controller.tracer is not None:
            state["trace"] = self.controller.tracer.debug_state()
        return state

    def step(self, now: float | None = None, last_resync: float = 0.0) -> float:
        """One scheduler step: monitor tick always; deployment resync when
        due. Returns the new last_resync time."""
        now = self.clock() if now is None else now
        if now - last_resync >= DEPLOY_RESYNC_SECONDS or last_resync == 0.0:
            self.informer.resync()
            last_resync = now
        self.controller.tick()
        return last_resync

    def run(self, stop: Callable[[], bool] = lambda: False) -> None:
        if self.stream:
            return self.run_stream(stop)
        last_resync = 0.0
        while not stop():
            try:
                last_resync = self.step(last_resync=last_resync)
            except Exception:  # noqa: BLE001 - keep the control loop alive
                log.exception("watch-plane step failed")
            self.sleep(MONITOR_POLL_SECONDS)

    def run_stream(self, stop: Callable[[], bool] = lambda: False) -> None:
        """Event-driven loop: hold the watch stream open between
        scheduler duties, dispatching deployment events the instant
        they arrive. Monitor polling keeps its 10 s cadence (job
        status is a poll by nature until the service pushes), and the
        30 s deployment resync is DEMOTED to a repair sweep — it no
        longer bounds detection latency, it only catches what a lossy
        stream (410 re-lists, compaction) might have dropped."""
        last_resync = self.clock()
        last_poll = 0.0
        try:
            self.informer.resync()  # prime the snapshot + resume point
        except Exception:  # noqa: BLE001 - retried inside the loop
            log.exception("watch-plane initial list failed")
        while not stop():
            try:
                now = self.clock()
                if now - last_poll >= MONITOR_POLL_SECONDS:
                    self.controller.tick()
                    last_poll = now
                if now - last_resync >= DEPLOY_RESYNC_SECONDS:
                    self.informer.resync()  # repair sweep
                    last_resync = now
                window = min(
                    MONITOR_POLL_SECONDS - (self.clock() - last_poll),
                    DEPLOY_RESYNC_SECONDS - (self.clock() - last_resync),
                )
                window = max(0.05, min(window, MONITOR_POLL_SECONDS))
                t0 = self.clock()
                self.informer.consume(window)
                leftover = window - (self.clock() - t0)
                if leftover > 0.05:
                    # the stream died early (API server down, breaker
                    # open): pace the reconnects instead of spinning
                    self.sleep(min(leftover, 1.0))
            except Exception:  # noqa: BLE001 - keep the control loop alive
                log.exception("watch-plane stream step failed")
                self.sleep(1.0)
