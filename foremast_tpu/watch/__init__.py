"""Watcher / remediation control plane — the foremast-barrelman equivalent.

The reference implements this layer as a Go Kubernetes controller
(`foremast-barrelman/`, SURVEY.md section 2.1). Here it is an asyncio
control plane over a pluggable kube API so the same logic runs against a
real cluster (HttpKube) or an in-memory fake (InMemoryKube) in tests. The
TPU scoring engine is reached only through the analyst REST contract, so
this plane stays a thin pure-control layer.
"""

from foremast_tpu.watch.crds import (
    DeploymentMetadata,
    DeploymentMonitor,
    MonitorPhase,
    MonitorStatus,
    RemediationOption,
)
from foremast_tpu.watch.kubeapi import InMemoryKube, KubeClient
from foremast_tpu.watch.analyst import AnalystClient
from foremast_tpu.watch.barrelman import Barrelman
from foremast_tpu.watch.controller import MonitorController

__all__ = [
    "AnalystClient",
    "Barrelman",
    "DeploymentMetadata",
    "DeploymentMonitor",
    "InMemoryKube",
    "KubeClient",
    "MonitorController",
    "MonitorPhase",
    "MonitorStatus",
    "RemediationOption",
]
