"""Command-line surface: score / serve / worker / watch / unwatch / rules.

The reference drives its system with kubectl plus two tiny plugins
(`bin/kubectl-watch`, `bin/kubectl-unwatch` — merge-patching
DeploymentMonitor.spec.continuous, `bin/kubectl-watch:3`). This framework
adds a first-class CLI:

  score    one-shot health judgment of an ApplicationHealthAnalyzeRequest
           JSON (the minimum end-to-end slice: request -> windows -> batched
           TPU judgment -> reference wire-format response)
  serve    the REST job gateway on :8099 (foremast-service equivalent)
  worker   the scoring worker loop + :8000 gauge exposition (brain
           equivalent)
  watch    / unwatch — toggle continuous monitoring on a DeploymentMonitor
           (kubectl-watch parity, via the API server)
  rules    print the generated PrometheusRule recording-rules manifest

`python -m foremast_tpu <cmd>` and the `bin/foremast` shim both land here.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import time


def _add_score(sub: argparse._SubParsersAction) -> None:
    p = sub.add_parser(
        "score", help="one-shot health judgment of a request JSON"
    )
    p.set_defaults(fn=cmd_score)
    p.add_argument(
        "--request",
        required=True,
        help="path to ApplicationHealthAnalyzeRequest JSON ('-' for stdin)",
    )
    p.add_argument(
        "--current",
        action="append",
        default=[],
        metavar="ALIAS=CSV",
        help="replay trace for the current window of ALIAS",
    )
    p.add_argument("--baseline", action="append", default=[], metavar="ALIAS=CSV")
    p.add_argument("--historical", action="append", default=[], metavar="ALIAS=CSV")
    p.add_argument(
        "--prometheus",
        action="store_true",
        help="fetch real query_range URLs instead of replay traces",
    )
    p.add_argument(
        "--follow",
        action="store_true",
        help="keep re-checking until the job reaches a terminal status "
        "(the reference's incremental re-check loop); default judges once "
        "and finalizes",
    )
    p.add_argument("--poll", type=float, default=5.0, help="--follow poll seconds")


def _parse_assignments(pairs: list[str], flag: str) -> dict[str, str]:
    out = {}
    for pair in pairs:
        alias, sep, path = pair.partition("=")
        if not sep or not alias or not path:
            raise SystemExit(f"{flag} expects ALIAS=CSV, got {pair!r}")
        out[alias] = path
    return out


def cmd_score(args: argparse.Namespace) -> int:
    from foremast_tpu.jobs.convert import request_to_document
    from foremast_tpu.jobs.models import AnalyzeRequest, document_response
    from foremast_tpu.jobs.store import InMemoryStore, parse_time
    from foremast_tpu.jobs.worker import BrainWorker
    from urllib.parse import unquote

    from foremast_tpu.metrics.promql import decode_config
    from foremast_tpu.metrics.source import PrometheusSource, ReplaySource

    raw = sys.stdin.read() if args.request == "-" else open(args.request).read()
    req = AnalyzeRequest.from_json(json.loads(raw))
    doc = request_to_document(req)

    if args.prometheus:
        source = PrometheusSource()
    else:
        # replay traces are keyed by exact query URL: current/baseline/
        # historical configs for the same alias differ only in their URLs,
        # so route each category's URL to its own trace
        source = ReplaySource()
        for flag, config in (
            ("--current", doc.current_config),
            ("--baseline", doc.baseline_config),
            ("--historical", doc.historical_config),
        ):
            assignments = _parse_assignments(getattr(args, flag[2:]), flag)
            urls = decode_config(config)
            for alias, path in assignments.items():
                if alias not in urls:
                    raise SystemExit(
                        f"{flag} {alias}: no such alias in the request's "
                        f"{flag[2:]} metrics (have: {sorted(urls) or 'none'})"
                    )
                # ReplaySource matches patterns against the *unquoted* URL
                source.register_csv(unquote(urls[alias]), path)

    store = InMemoryStore()
    doc, _ = store.create(doc)
    # same env-var config surface as the worker loop (the reference brain
    # is configured entirely through env, foremast-brain/README.md:20-38)
    from foremast_tpu.config import BrainConfig

    worker = BrainWorker(store, source, BrainConfig.from_env(), claim_limit=1)

    from foremast_tpu.jobs.models import (
        STATUS_COMPLETED_HEALTH,
        STATUS_COMPLETED_UNHEALTH,
        TERMINAL_STATUSES,
    )

    if args.follow:
        while store.get(doc.id).status not in TERMINAL_STATUSES:
            worker.tick()
            if store.get(doc.id).status in TERMINAL_STATUSES:
                break
            time.sleep(args.poll)
    else:
        # one-shot: clamp "now" past endTime so a healthy window finalizes
        end = parse_time(doc.end_time)
        worker.tick(now=max(time.time(), end + 1))

    final = store.get(doc.id)
    json.dump(document_response(final), sys.stdout, indent=2)
    print()
    # exit 0 only when the judgment actually evaluated the metrics
    # (healthy OR anomaly); preprocess_failed / abort / completed_unknown
    # mean no judgment was made, which must fail a CI gate.
    return (
        0
        if final.status in (STATUS_COMPLETED_HEALTH, STATUS_COMPLETED_UNHEALTH)
        else 1
    )


def _make_store(elastic_url: str | None, chaos=None, breaker=None, stop=None):
    """ES-backed store with the reference's connect-retry loop
    (service main.go:248-260), or in-memory when no URL is given.

    Falls back to the reference's env vars (`ELASTIC_URL` for the service,
    `ES_ENDPOINT` for the engine, main.go:236-243 / foremast-brain.yaml:22)
    so the deployed containers need no flags.

    The connect loop is bounded (ISSUE 9 satellite):
    `FOREMAST_ES_CONNECT_DEADLINE_SECONDS` (0/unset = the reference's
    forever-retry) turns a store that never comes up into a LOUD exit
    instead of an un-stoppable wait, and `stop` (shutdown signal) is
    honored between retries."""
    import os

    from foremast_tpu.jobs.store import ElasticsearchStore, InMemoryStore

    elastic_url = (
        elastic_url or os.environ.get("ELASTIC_URL") or os.environ.get("ES_ENDPOINT")
    )
    if not elastic_url:
        return InMemoryStore()
    store = ElasticsearchStore(elastic_url, chaos=chaos, breaker=breaker)
    deadline = float(
        os.environ.get("FOREMAST_ES_CONNECT_DEADLINE_SECONDS", "") or 0.0
    )
    if not store.wait_ready(max_wait=deadline or None, stop=stop):
        if stop and stop():
            # a SIGTERM during the connect loop is a GRACEFUL shutdown:
            # exit 0, or a rolling restart reads as a crash loop
            print(
                "shutdown requested during Elasticsearch connect; "
                "exiting cleanly",
                file=sys.stderr,
            )
            raise SystemExit(0)
        state = store.connect_state
        raise SystemExit(
            f"could not reach Elasticsearch at {elastic_url} within "
            f"{deadline:.0f}s ({state['attempts']} attempts, last error: "
            f"{state['last_error']}); set "
            "FOREMAST_ES_CONNECT_DEADLINE_SECONDS=0 to wait forever"
        )
    return store


def _mesh_member(store, worker_id: str, chaos_plan=None):
    """THE worker-mesh Membership + MeshRouter construction — shared by
    the single-worker branch and the mesh-of-pods leader (ISSUE 13), so
    the lease/replica/route-label env resolution and the chaos clock
    wiring can never drift between the two deployment modes."""
    import os

    from foremast_tpu.mesh import Membership, MeshRouter

    mesh_kw = {}
    if chaos_plan is not None:
        # chaos "clock" edge: skew rules shift the clock this member
        # stamps leases with AND reads peers' leases by (membership.py
        # documents the tolerance: renewal every lease/3 means a reader
        # surviving skew < 2/3 lease)
        mesh_kw["clock"] = chaos_plan.edge("clock").clock()
    membership = Membership(
        store,
        worker_id,
        lease_seconds=float(
            os.environ.get("FOREMAST_MESH_LEASE_SECONDS", "") or "15"
        ),
        **mesh_kw,
    )
    router = MeshRouter(
        membership,
        replicas=_env_int("FOREMAST_MESH_REPLICAS", 64),
        route_label=(
            os.environ.get("FOREMAST_MESH_ROUTE_LABEL", "") or "app"
        ),
    )
    return membership, router


def cmd_serve(args: argparse.Namespace) -> int:
    from foremast_tpu.observe import setup_logging
    from foremast_tpu.observe.spans import Tracer
    from foremast_tpu.service.app import serve

    setup_logging()
    store = _make_store(args.elastic_url)
    serve(
        host=args.host,
        port=args.port,
        store=store,
        query_endpoint=args.query_endpoint,
        # per-request spans + the /debug/state trace section; the ring
        # buffer dump is gated by FOREMAST_TRACE_DIR as everywhere
        tracer=Tracer(service="service"),
    )
    return 0


def _enable_compile_cache() -> None:
    """FOREMAST_COMPILE_CACHE_DIR: point JAX's persistent compilation
    cache at a durable directory so the 20-40 s per-bucket warmup
    compiles (`BrainWorker.warmup`) are paid once per binary, not once
    per process restart — a worker pod restarting on the same image
    reloads every judgment program from the cache. Must run before the
    first jax computation; warmup logs hit/miss from the entry counts."""
    path = os.environ.get("FOREMAST_COMPILE_CACHE_DIR")
    if not path:
        return
    import jax

    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # the default gates skip fast/small compiles; the worker wants EVERY
    # judgment bucket persisted, including sub-second CPU-sized ones
    for flag, value in (
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(flag, value)
        except Exception:  # noqa: BLE001 — older jaxlib without the flag
            pass
    logging.getLogger("foremast_tpu.cli").info(
        "persistent compile cache enabled at %s", path
    )


def _mount_ingest(
    inner, gauge_port: int, router=None, snapshot_dir=None,
    chaos=None, degrade=None, handoff=None, dirty=None,
):
    """FOREMAST_INGEST=1: wrap the pull source in the push-plane
    RingSource (docs/operations.md "Ingest plane") — warm fetches become
    resident ring gathers, cold misses fall back to `inner` and are
    backfilled so the next tick hits. Starts the remote-write receiver
    (FOREMAST_INGEST_PORT; 0 = direct push/backfill only; port 0 taken
    literally means ephemeral in mesh mode, where every co-hosted
    worker needs its own receiver) and registers the foremast_ingest_*
    families when a scrape port is live. `router` (mesh mode) makes the
    receiver answer pushes for series another member owns with that
    member's advertised address. `snapshot_dir` mounts the durable ring
    (docs/operations.md "Restarts and upgrades"): restore runs BEFORE
    the receiver accepts its first push, then live pushes journal.
    Returns (source, ring, receiver or None, snapshotter or None)."""
    from foremast_tpu.ingest import (
        IngestCollector,
        RingSource,
        RingStore,
        start_ingest_server,
    )

    ring = RingStore.from_env()
    snapshotter = None
    if snapshot_dir:
        from foremast_tpu.ingest import RingSnapshotter

        snapshotter = RingSnapshotter.from_env(ring, snapshot_dir)
        # restore() logs series/samples + the discard breakdown itself
        snapshotter.restore()
        snapshotter.attach()
    source = RingSource(ring, fallback=inner)
    # ring-first cold path (ISSUE 10): the worker reads historical
    # ranges straight off resident columns (hist_columns), admits
    # newcomers on short coverage, and refines provisional fits in the
    # background — say so at startup, with the two knobs that tune it.
    # Partial admission is pure-push only (source.hist_columns), so a
    # fallback-configured fleet is told its floor is inert.
    from foremast_tpu.jobs.refine import refine_docs_per_tick_from_env

    logging.getLogger("foremast_tpu.cli").info(
        "cold-start path: ring-resident historical reads ON "
        "(admit floor %.0f s%s — FOREMAST_ADMIT_MIN_COVERAGE_SECONDS; "
        "refinement %d docs/tick — FOREMAST_REFINE_DOCS_PER_TICK; "
        "docs/operations.md \"Cold start & churn\")",
        source.admit_floor,
        "" if inner is None else " [inert: fallback configured]",
        refine_docs_per_tick_from_env(),
    )
    if handoff is not None:
        # the handoff plane streams/applies THIS ring's series; the
        # manager exists before the ring (it needs the chaos edge and
        # the router's route label), so bind it here
        handoff.ring_store = ring
    port = _env_int("FOREMAST_INGEST_PORT", 9009)
    srv = None
    if port or router is not None:
        srv, _ = start_ingest_server(
            port, ring, book=source.book, router=router,
            chaos=chaos,
            degrade_stats=degrade.stats if degrade is not None else None,
            handoff=handoff,
            dirty=dirty,
        )
    if gauge_port:
        from prometheus_client import REGISTRY

        REGISTRY.register(
            IngestCollector(
                ring,
                book=source.book,
                # per-codec stage breakdown, live only when a receiver
                # is (ISSUE 18: the wire families come from the wire)
                wire=getattr(srv, "_foremast_wire_stats", None),
            )
        )
    return source, ring, srv, snapshotter


def _persistent_worker_id(snap_dir: str, minted: str) -> str:
    """Stable worker identity across restarts (``<snap_dir>/worker.id``):
    a restarted worker re-joins the mesh as the SAME member, so the hash
    ring does not move and the restored ring/fit state matches exactly
    the partition it reclaims. First boot persists the minted id."""
    from foremast_tpu.ingest.snapshot import atomic_write

    path = os.path.join(snap_dir, "worker.id")
    try:
        with open(path) as fh:
            wid = fh.read().strip()
        if wid:
            return wid
    except OSError:
        pass
    atomic_write(path, minted.encode())
    return minted


def cmd_worker(args: argparse.Namespace) -> int:
    # FIRST, before the imports below create any package lock (native
    # and observe.spans both make module-level locks at import time):
    # FOREMAST_LOCK_WITNESS=1 wraps threading.Lock/RLock to record real
    # acquisition order and verify it against the committed static lock
    # graph at exit — installing later would leave those locks raw and
    # their edges invisible to the witness
    from foremast_tpu.analysis.witness import install_from_env

    install_from_env()
    # FOREMAST_RECOMPILE_WITNESS=1: count actual XLA backend compiles
    # for the life of the worker and log the total at exit — the
    # zero-warm-recompile contract's runtime witness (installed before
    # the first dispatch so the cold compiles are attributed too)
    from foremast_tpu.analysis.recompile_witness import (
        install_from_env as install_recompile_witness,
    )

    install_recompile_witness()

    from foremast_tpu import native
    from foremast_tpu.config import BrainConfig
    from foremast_tpu.jobs.worker import BrainWorker
    from foremast_tpu.metrics.source import PrometheusSource
    from foremast_tpu.observe.gauges import BrainGauges, make_verdict_hook
    from foremast_tpu.observe.spans import Tracer, start_observe_server

    from foremast_tpu.observe import setup_logging

    setup_logging()  # structured JSON logs at INFO (operational events —
    # claims, warmup, checkpoint, takeovers — are info-level)
    _enable_compile_cache()  # before ANY jax computation below
    native.ensure_built()  # startup-time compile, never in the hot path
    config = BrainConfig.from_env()

    # chaos plane + degradation bundle (ISSUE 9): FOREMAST_CHAOS_PLAN
    # unset (production) means chaos_plan is None and every injection
    # seam below receives None — a plain attribute check, no other
    # cost. The Degradation bundle (breakers, write-behind, tick
    # budget) is ALWAYS on: degrading through a real outage must not
    # require having opted into chaos testing.
    from foremast_tpu.chaos import Degradation, chaos_from_env

    chaos_plan = chaos_from_env()

    def _edge(name: str):
        return chaos_plan.edge(name) if chaos_plan is not None else None

    degrade = Degradation.from_env(
        max_stuck_seconds=config.max_stuck_seconds, chaos_plan=chaos_plan
    )

    # graceful shutdown flag, installed BEFORE the store connect loop so
    # a SIGTERM during an ES outage at startup stops the retry promptly
    # (wait_ready polls `stop` between sliced sleeps) instead of dying
    # on the default disposition; the worker loop reuses the same event
    import signal
    import threading

    stop_event = threading.Event()
    try:
        signal.signal(signal.SIGTERM, lambda s, f: stop_event.set())
        signal.signal(signal.SIGINT, lambda s, f: stop_event.set())
    except ValueError:
        pass  # not the main thread (embedded use); rely on the caller

    from foremast_tpu.engine.multivariate import MultivariateJudge

    univariate = None
    pod_mode = False
    if args.sharded:
        from foremast_tpu.parallel import init_distributed, make_global_mesh
        from foremast_tpu.parallel.batch import sharded_univariate

        # MUST run before any jax computation — including an orbax restore
        init_distributed()  # no-op single-host; JAX_COORDINATOR_* envs for pods
        univariate = sharded_univariate(config, mesh=make_global_mesh())
        import jax as _jax_sh

        pod_mode = _jax_sh.process_count() > 1
    else:
        # single-process worker: the judge spans the local device mesh
        # by default (ISSUE 13, FOREMAST_DEVICE_MESH — "auto" = all
        # local devices; a stock 1-device host resolves to None and
        # keeps the plain single-device judge). ONE shared resolver
        # with BrainWorker's device_mesh="env" path — the rules must
        # never drift between CLI and library construction.
        from foremast_tpu.parallel.batch import sharded_univariate

        univariate = sharded_univariate(config)
    judge = MultivariateJudge(config, univariate=univariate)

    if pod_mode:
        # followers never dial ES/Prometheus: only the leader needs
        # credentials and reachability (docs/operations.md pod mode)
        import jax as _jax_pm

        store = (
            _make_store(
                args.elastic_url,
                chaos=_edge("store"),
                breaker=degrade.breakers.get("store"),
                stop=stop_event.is_set,
            )
            if _jax_pm.process_index() == 0
            else None
        )
    else:
        store = _make_store(
            args.elastic_url,
            chaos=_edge("store"),
            breaker=degrade.breakers.get("store"),
            stop=stop_event.is_set,
        )

    ckpt_path = None
    ckpt_save = None
    if args.model_cache_dir:
        import os as _os

        import jax as _jax

        if _jax.process_count() > 1:
            # Pod mode (VERDICT r4 #1): the determinism contract
            # (parallel/distributed.py) requires IDENTICAL caches on
            # every process — a host restoring fewer fits than its peers
            # would route docs down different code paths and desync the
            # SPMD program. So only the LEADER touches disk (host-local
            # pickle — orbax's save is itself a cross-process collective
            # and would barrier-deadlock), the restore decision and the
            # restored entries are broadcast to every process, and
            # follower saves are no-ops.
            from foremast_tpu.parallel import broadcast_obj

            leader_pm = _jax.process_index() == 0
            ckpt_path = _os.path.abspath(
                _os.path.join(args.model_cache_dir, "model_cache.pod")
            )
            ckpt_save = (
                judge.cache.save_local if leader_pm else (lambda path: None)
            )
            restored = None
            if leader_pm and _os.path.exists(ckpt_path):
                try:
                    judge.cache.load_local(ckpt_path)
                    restored = judge.cache.snapshot()
                except Exception as e:  # noqa: BLE001 - stale/corrupt
                    print(
                        f"model-cache restore failed ({e}); starting cold",
                        file=sys.stderr,
                    )
            items = broadcast_obj(restored)
            if items:
                if not leader_pm:
                    judge.cache.put_many(items.items())
                print(
                    f"restored {len(items)} cached models pod-wide from "
                    f"{ckpt_path}",
                    file=sys.stderr,
                )
        else:
            import ast

            ckpt_path = _os.path.abspath(
                _os.path.join(args.model_cache_dir, "model_cache")
            )
            ckpt_save = judge.cache.save

            if _os.path.exists(ckpt_path):
                try:
                    n = judge.cache.load(
                        ckpt_path, key_parser=ast.literal_eval
                    )
                    print(
                        f"restored {n} cached models from {ckpt_path}",
                        file=sys.stderr,
                    )
                except Exception as e:  # noqa: BLE001 - stale/corrupt
                    print(
                        f"model-cache restore failed ({e}); starting cold",
                        file=sys.stderr,
                    )

    on_verdict = None
    worker_metrics = None
    tracer = None
    # pod mode: telemetry is leader-only — every process executes the
    # full tick over the IDENTICAL broadcast fleet, so follower gauges
    # would multiply all job/verdict/arena counts by process_count
    leader = store is not None if pod_mode else True
    if leader:
        # span pipeline: stage histograms always; the Perfetto ring
        # buffer only when FOREMAST_TRACE_DIR points somewhere
        tracer = Tracer(service="worker", trace_dir=config.trace_dir)
    if args.gauge_port and leader:
        from foremast_tpu.observe.gauges import WorkerMetrics

        gauges = BrainGauges()
        worker_metrics = WorkerMetrics()
        on_verdict = make_verdict_hook(gauges)
    # push-based ingest plane (opt-in): the ring + receiver live where
    # the fetches happen — the single worker, or the pod leader (the
    # only process whose LeaderSource.inner is real; follower fetches
    # stay leader-broadcast collectives, semantics unchanged)
    ingest_on = os.environ.get("FOREMAST_INGEST", "0") == "1"
    # worker mesh (opt-in): this worker takes a membership lease in the
    # job store and claims only its consistent-hash partition of the
    # fleet (docs/operations.md "Worker mesh"). Pod mode is already ONE
    # logical worker spanning processes — mesh partitioning happens
    # BETWEEN pods/workers, so a pod's followers never see it and a
    # leader could in principle join; wiring that is future work.
    mesh_on = os.environ.get("FOREMAST_MESH", "0") == "1"
    mesh_node = None
    ingest_srv = None
    # reactive plane (opt-in, ISSUE 12): FOREMAST_MICROTICK_SECONDS > 0
    # turns pushed-sample arrivals into micro-ticks — the receiver
    # marks each push's route key dirty and the worker judges just
    # those documents between full sweeps (docs/operations.md
    # "Event-driven detection"). Needs the ingest receiver: arrivals
    # are what the receiver sees.
    from foremast_tpu.reactive import microtick_seconds_from_env

    micro_seconds = microtick_seconds_from_env()
    dirty = None
    # durable data plane (opt-in): ring snapshots + append logs, fit
    # journals, and the persistent mesh identity all under one directory
    # (docs/operations.md "Restarts and upgrades")
    snap_dir = os.environ.get("FOREMAST_SNAPSHOT_DIR") or None
    snapshotter = None
    if mesh_on and pod_mode:
        # mesh-of-pods (ISSUE 13): each worker-mesh member is one POD —
        # a PodWorker whose device program spans its hosts' chips. Only
        # the LEADER holds the membership lease and evaluates the claim
        # filter (it is the only process with a real store); the
        # filtered claim set broadcasts to the followers exactly like
        # any other claim, so partitioning never shapes follower
        # control flow. Handoff/ingest stay leader-local pull-mode in
        # pods (the transfer plane needs a receiver per member —
        # docs/operations.md "Device mesh").
        print(
            "mesh-of-pods: this pod joins the worker mesh as ONE "
            "member (leader-held lease + claim filter)",
            file=sys.stderr,
        )
    if micro_seconds > 0 and pod_mode:
        # pod ticks are SPMD-broadcast collectives; a leader-local
        # micro-tick would desync followers — wiring micro-ticks
        # through the broadcast is future work
        print(
            "FOREMAST_MICROTICK_SECONDS ignored in pod mode "
            "(micro-ticks are single-worker; pod ticks are broadcast "
            "collectives)",
            file=sys.stderr,
        )
        micro_seconds = 0.0
    if micro_seconds > 0 and not ingest_on:
        print(
            "FOREMAST_MICROTICK_SECONDS needs FOREMAST_INGEST=1 (the "
            "receiver is what marks arrivals); staying tick-paced",
            file=sys.stderr,
        )
        micro_seconds = 0.0
    if snap_dir and pod_mode:
        # pod mode's determinism contract (identical caches on every
        # process, leader-only I/O) already has its own durability path
        # (--model-cache-dir leader checkpoint + broadcast); wiring the
        # journals through the broadcast is future work
        print(
            "FOREMAST_SNAPSHOT_DIR ignored in pod mode (use "
            "--model-cache-dir: leader checkpoint + broadcast)",
            file=sys.stderr,
        )
        snap_dir = None
    if pod_mode:
        # One logical worker spanning the jax.distributed cluster: the
        # leader claims/fetches/writes, everything is broadcast, the
        # judgment runs SPMD over the global mesh. Plain BrainWorkers
        # must NOT share a global mesh — each would claim different
        # docs into one SPMD program (docs/operations.md runbook).
        from foremast_tpu.parallel import LeaderSource, LeaderStore, PodWorker

        pod_inner = (
            PrometheusSource(
                chaos=_edge("prometheus"),
                breaker=degrade.breakers.get("prometheus"),
            )
            if store is not None
            else None
        )
        if ingest_on and pod_inner is not None:
            pod_inner, _pod_ring, ingest_srv, _ = _mount_ingest(
                pod_inner, args.gauge_port,
                chaos=_edge("receiver"), degrade=degrade,
            )
        pod_worker_id = None
        if mesh_on and store is not None:
            # the leader's seat in the worker mesh (mesh-of-pods): the
            # membership record and the claim stamps share one id, and
            # the MeshNode's claim_filter rides LeaderStore.claim so
            # the whole pod ticks over this member's partition only
            import uuid as _uuid

            from foremast_tpu.mesh import MeshNode

            pod_worker_id = f"pod-{_uuid.uuid4().hex[:8]}"
            pod_membership, pod_router = _mesh_member(
                store, pod_worker_id, chaos_plan
            )
            mesh_node = MeshNode(pod_membership, pod_router)
            mesh_node.start()
        worker = PodWorker(
            LeaderStore(store),
            LeaderSource(pod_inner),
            config=config,
            judge=judge,
            claim_limit=args.claim_limit,
            on_verdict=on_verdict,
            metrics=worker_metrics,
            tracer=tracer,
            mesh=mesh_node,
            degrade=degrade,
            **({"worker_id": pod_worker_id} if pod_worker_id else {}),
        )
    else:
        # mesh identity is minted HERE so the membership record and the
        # claim's processing_content stamp agree on one worker id; with
        # a snapshot dir the id PERSISTS, so a restart re-takes the same
        # mesh seat (no rebalance) and reclaims exactly the partition
        # its restored ring/fit state belongs to
        import uuid as _uuid

        worker_id = f"brain-{_uuid.uuid4().hex[:8]}"
        snap_lock = None
        if snap_dir:
            # exclusivity: two live workers sharing one snapshot dir
            # would interleave torn frames into the same shard logs and
            # join the mesh as ONE member. flock dies with the process
            # (SIGKILL included), so restarts acquire immediately; only
            # a genuinely concurrent second worker is refused — it runs
            # ephemeral rather than corrupting the first one's state.
            from foremast_tpu.ingest import lock_snapshot_dir

            snap_lock = lock_snapshot_dir(snap_dir)
            if snap_lock is None:
                print(
                    f"FOREMAST_SNAPSHOT_DIR {snap_dir} is held by "
                    "another live worker; running ephemeral (give "
                    "each co-hosted worker its own directory)",
                    file=sys.stderr,
                )
                snap_dir = None
        if snap_dir:
            worker_id = _persistent_worker_id(snap_dir, worker_id)
        membership = router = None
        if mesh_on:
            membership, router = _mesh_member(store, worker_id, chaos_plan)
        # planned handoff (ISSUE 11): rebalance on planned scale events
        # becomes a state TRANSFER — the joiner fences until the current
        # owners stream it its partition, SIGTERM drains instead of
        # abandoning state (docs/operations.md "Elastic scaling")
        handoff = None
        # ingest gates the plane: without a receiver there is no
        # transfer endpoint anywhere in the fleet — a fenced joiner
        # would idle out its whole deadline with nothing to receive,
        # a pure regression over PR-6 immediate claiming
        if (
            mesh_on
            and ingest_on
            and os.environ.get("FOREMAST_HANDOFF", "1") == "1"
        ):
            from foremast_tpu.mesh import HandoffManager

            handoff = HandoffManager(
                route_label=router.route_label,
                chaos=_edge("transfer"),
                breaker=degrade.breakers.get("transfer"),
            )
        if micro_seconds > 0:
            # dirty routing respects partition ownership: with a mesh
            # router wired, pushes for series another member owns are
            # counted foreign and never marked (that member's own
            # receiver marks them)
            from foremast_tpu.reactive import DirtySet

            dirty = DirtySet.from_env(
                route_label=(
                    router.route_label if router is not None else "app"
                ),
                owns=(
                    router.owns_series if router is not None else None
                ),
            )
            from foremast_tpu.reactive.dirty import microtick_docs_from_env

            logging.getLogger("foremast_tpu.cli").info(
                "reactive plane ON: micro-ticks every %.3f s, %d dirty "
                "keys/tick, dirty-set cap %d "
                "(docs/operations.md \"Event-driven detection\")",
                micro_seconds, microtick_docs_from_env(),
                dirty.max_keys,
            )
        single_source = PrometheusSource(
            chaos=_edge("prometheus"),
            breaker=degrade.breakers.get("prometheus"),
        )
        single_ring = None
        if ingest_on:
            single_source, single_ring, ingest_srv, snapshotter = (
                _mount_ingest(
                    single_source, args.gauge_port, router=router,
                    snapshot_dir=snap_dir,
                    chaos=_edge("receiver"), degrade=degrade,
                    handoff=handoff, dirty=dirty,
                )
            )
        if mesh_on:
            from foremast_tpu.mesh import MeshNode

            if ingest_srv is not None:
                # advertise where pushers can actually reach the
                # receiver: FOREMAST_MESH_ADVERTISE (host or host:port)
                # wins, the bound port fills any gap
                import socket as _socket

                adv = os.environ.get("FOREMAST_MESH_ADVERTISE") or ""
                adv_host, _, adv_port = adv.partition(":")
                membership.ingest_address = "{}:{}".format(
                    adv_host or _socket.gethostname(),
                    adv_port or ingest_srv.server_address[1],
                )
            mesh_node = MeshNode(
                membership, router, ring_store=single_ring,
                handoff=handoff,
            )
            mesh_node.start()
        worker = BrainWorker(
            store,
            single_source,
            config=config,
            judge=judge,
            worker_id=worker_id,
            claim_limit=args.claim_limit,
            on_verdict=on_verdict,
            metrics=worker_metrics,
            tracer=tracer,
            mesh=mesh_node,
            degrade=degrade,
            dirty=dirty,
        )
        if worker._sweep_sliceable():
            logging.getLogger("foremast_tpu.cli").info(
                "sliced sweeps ON: %d-doc slices under the %d-doc "
                "claim, dirty-drain preemption at slice boundaries "
                "(FOREMAST_SWEEP_SLICE_DOCS; docs/operations.md "
                "\"Event-driven detection\")",
                worker.sweep_slice_docs, worker.claim_limit,
            )
        if snap_dir:
            # fit journals restore lazily (the first claim of each doc
            # rehydrates its fits, so admission passes with no history
            # re-fetch) and write through on fit completion; snapshot
            # cadence + compaction run inside the tick loop
            fit_restored = worker.enable_fit_persistence(snap_dir)
            if any(fit_restored.values()):
                print(
                    f"restored fit state {fit_restored} from {snap_dir}",
                    file=sys.stderr,
                )
            if snapshotter is not None:
                worker.attach_ring_snapshotter(snapshotter)
    if args.gauge_port and leader:
        # /metrics + /healthz + /debug/state on the scrape port (the
        # reference exposed /metrics only). Auto-increment past a busy
        # port: co-hosted mesh workers must not fight over :8000 — the
        # actual port lands in the member record below.
        obs_srv, _ = start_observe_server(
            args.gauge_port,
            state_fn=worker.debug_state,
            max_port_tries=32,
        )
        if mesh_node is not None:
            from foremast_tpu.mesh import MeshCollector
            from prometheus_client import REGISTRY as _REG

            _REG.register(MeshCollector(mesh_node))
            mesh_node.membership.observe_port = obs_srv.server_address[1]
            mesh_node.membership.renew(force=True)
        if snap_dir:
            from foremast_tpu.ingest import SnapshotCollector
            from prometheus_client import REGISTRY as _REG2

            _REG2.register(
                SnapshotCollector(
                    snapshotter, journals=worker._fit_journals.values()
                )
            )
        # chaos/degradation exposition rides the same scrape port:
        # breaker states, degraded-doc counters, injected-fault counts
        from foremast_tpu.chaos import ChaosCollector
        from prometheus_client import REGISTRY as _REG3

        _REG3.register(ChaosCollector(degrade))
        if dirty is not None:
            from foremast_tpu.reactive import ReactiveCollector
            from prometheus_client import REGISTRY as _REG4

            _REG4.register(ReactiveCollector(dirty))

    after_tick = None
    if ckpt_path:
        state = {"dirty": False}

        def after_tick(n, _state=state):
            # checkpoint when work happened, on the following idle tick —
            # so saves never add latency to a busy scoring cycle
            if n > 0:
                _state["dirty"] = True
            elif _state["dirty"]:
                ckpt_save(ckpt_path)
                _state["dirty"] = False

    # graceful shutdown: the SIGTERM/SIGINT handlers were installed
    # before the store connect loop (top of this function); from here
    # `stop_event` makes the worker finish the in-flight tick (claimed
    # docs get written back) instead of dying mid-judgment — abandoned
    # claims would otherwise wait out MAX_STUCK_IN_SECONDS
    if args.warmup:
        worker.warmup()

    stop_fn = stop_event.is_set
    if mesh_node is not None and mesh_node.handoff is not None:
        # planned shutdown (ISSUE 11): on the stop signal, stream this
        # partition to the post-drain owners on a side thread while
        # the loop KEEPS TICKING — a draining member claims and judges
        # its partition to the end, so no verdict waits out a slow or
        # blackholed transfer fenced behind this member's claim-ring
        # seat. The loop exits once the stream lands (or fails:
        # survivors cold-refit via the PR-6 path); the finally block's
        # drain() then only leaves.
        drain_thread_box: list = [None]

        def stop_fn() -> bool:
            if not stop_event.is_set():
                return False
            t = drain_thread_box[0]
            if t is None:
                t = threading.Thread(
                    target=mesh_node.stream_drain,
                    name="handoff-drain",
                    daemon=True,
                )
                drain_thread_box[0] = t
                t.start()
            return not t.is_alive()

    loop_failed = False
    try:
        worker.run(
            poll_seconds=args.poll,
            stop=stop_fn,
            after_tick=after_tick,
        )
    except BaseException:
        loop_failed = True
        raise
    finally:
        # run even when a tick raises: the persistent fetch/prefetch
        # pools must not linger to interpreter-exit join, and the cache
        # checkpoint + trace dump are worth keeping from a crashed loop.
        # After a loop failure each step is guarded so a cleanup error
        # (unwritable ckpt dir, say) can never mask the exception that
        # killed the loop; on a CLEAN shutdown a failed checkpoint
        # still raises — losing the fitted-model cache must exit loudly,
        # not as a warning under exit 0.
        try:
            worker.close()
        except Exception as e:  # noqa: BLE001 — cleanup must not mask
            logging.getLogger("foremast_tpu.cli").warning(
                "worker pool shutdown failed: %s", e
            )
        if mesh_node is not None:
            # planned shutdown: DRAIN when the handoff plane is wired —
            # the partition's ring series + fits streamed to the
            # post-drain owners under the tick loop (stop_fn above), so
            # drain() here normally just leaves and the survivors take
            # over warm (docs/operations.md "Elastic scaling");
            # otherwise leave FIRST so peers drop this member (and
            # start claiming its partition) without waiting out the
            # lease. Either way a failure degrades (survivors
            # cold-refit via stuck-claim takeover), never masks the
            # loop's own error.
            try:
                if mesh_node.handoff is not None and not loop_failed:
                    mesh_node.drain()
                else:
                    mesh_node.close()
            except Exception as e:  # noqa: BLE001 — cleanup must not mask
                logging.getLogger("foremast_tpu.cli").warning(
                    "mesh drain/leave failed: %s", e
                )
        if ingest_srv is not None:
            # bounded drain: in-flight pushes finish (or are abandoned
            # as daemon threads), the listen port frees immediately
            try:
                from foremast_tpu.ingest import stop_ingest_server

                stop_ingest_server(ingest_srv)
            except Exception as e:  # noqa: BLE001 — cleanup must not mask
                logging.getLogger("foremast_tpu.cli").warning(
                    "ingest receiver shutdown failed: %s", e
                )
        if snapshotter is not None:
            # one final pass AFTER the receiver drained (the last
            # pushes are in) so the restart replays a snapshot, not a
            # long log; then release the log handles
            try:
                snapshotter.snapshot()
                snapshotter.close()
            except Exception as e:  # noqa: BLE001 — cleanup must not mask
                logging.getLogger("foremast_tpu.cli").warning(
                    "final ring snapshot failed: %s", e
                )
        ckpt_error = None
        if ckpt_path and len(judge.cache):
            try:
                ckpt_save(ckpt_path)  # final checkpoint on the way out
            except Exception as e:  # noqa: BLE001 — see loop_failed gate
                if loop_failed:
                    logging.getLogger("foremast_tpu.cli").warning(
                        "final model-cache checkpoint failed: %s", e
                    )
                else:
                    # clean shutdown: losing the fitted-model cache must
                    # exit loudly — but only after the trace dump below
                    # gets its chance (deferred, not raised here)
                    ckpt_error = e
        if tracer is not None:
            try:
                tracer.flush()  # final Perfetto dump (no-op w/o trace dir)
            except Exception as e:  # noqa: BLE001 — cleanup must not mask
                # neither an unwritable trace dir nor a serialization
                # bug may turn a clean shutdown into a nonzero exit or
                # mask the loop/checkpoint error — the judgment work
                # already succeeded
                logging.getLogger("foremast_tpu.cli").warning(
                    "final trace flush failed: %s", e
                )
        if ckpt_error is not None:
            raise ckpt_error
    return 0


def _toggle_continuous(args: argparse.Namespace, value: bool) -> int:
    from foremast_tpu.watch.kubeapi import HttpKube, NotFound

    kube = HttpKube(base_url=args.api_server)
    try:
        # merge-patch only spec.continuous (what the reference plugin's
        # `kubectl patch --type=merge` does) so concurrent spec/status
        # writers are never reverted
        monitor = kube.patch_monitor(
            args.namespace, args.name, {"spec": {"continuous": value}}
        )
    except NotFound:
        print(f"deploymentmonitor {args.namespace}/{args.name} not found", file=sys.stderr)
        return 1
    verb = "watching" if value else "no longer watching"
    print(f"Foremast is {verb} application {args.name}")
    print(f"Job: {monitor.status.job_id}")
    return 0


def cmd_watch(args: argparse.Namespace) -> int:
    return _toggle_continuous(args, True)


def cmd_unwatch(args: argparse.Namespace) -> int:
    return _toggle_continuous(args, False)


def cmd_watch_plane(args: argparse.Namespace) -> int:
    """Run the deployed watch-plane controller (barrelman equivalent)."""
    import os

    from foremast_tpu.observe import setup_logging
    from foremast_tpu.observe.spans import Tracer
    from foremast_tpu.watch.kubeapi import HttpKube
    from foremast_tpu.watch.plane import WatchPlane

    setup_logging()
    # the controller's one dependency edge gets the same chaos seam +
    # breaker the worker's clients carry (ISSUE 9): a FOREMAST_CHAOS_PLAN
    # rule on edge "kube" injects here, and a dead API server fails
    # fast once the breaker opens instead of stalling every poll
    from foremast_tpu.chaos import Degradation, chaos_from_env

    chaos_plan = chaos_from_env()
    degrade = Degradation.from_env(chaos_plan=chaos_plan)
    kube = HttpKube(
        base_url=args.api_server,
        chaos=chaos_plan.edge("kube") if chaos_plan is not None else None,
        breaker=degrade.breakers.get("kube"),
    )
    plane = WatchPlane(
        kube,
        own_namespace=args.namespace or os.environ.get("NAMESPACE", "foremast"),
        tracer=Tracer(service="controller"),
    )
    if args.gauge_port:
        # the transition counter and poll-stage histogram register on
        # the default registry — without this server they'd be
        # unscrapeable in the only process that produces them
        from foremast_tpu.chaos import ChaosCollector
        from foremast_tpu.observe.spans import start_observe_server
        from prometheus_client import REGISTRY as _REG

        _REG.register(ChaosCollector(degrade))
        start_observe_server(args.gauge_port, state_fn=plane.debug_state)
    plane.run()
    return 0


def cmd_ui(args: argparse.Namespace) -> int:
    from foremast_tpu.ui.app import serve as serve_ui

    serve_ui(
        host=args.host,
        port=args.port,
        service_endpoint=args.service_endpoint,
        namespace=args.namespace,
        app_name=args.app,
        demo=args.demo,
    )
    return 0


def cmd_rules(args: argparse.Namespace) -> int:
    from foremast_tpu.metrics.rules import prometheus_rule_manifest, to_yaml

    sys.stdout.write(
        to_yaml(prometheus_rule_manifest(namespace=args.namespace))
    )
    return 0


def _env_int(name: str, default: int) -> int:
    """Env-var int with a warning (not a crash) on malformed values —
    build_parser runs for EVERY subcommand, so a bad env var must not
    break unrelated commands with a raw traceback."""
    # thin wrapper: every call site passes a literal, registered knob
    # (FOREMAST_CLAIM_LIMIT), so the dynamic read here stays enumerable
    raw = os.environ.get(name)  # foremast: ignore[env-contract]
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        print(f"ignoring malformed {name}={raw!r}; using {default}", file=sys.stderr)
        return default


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="foremast", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    sub = parser.add_subparsers(dest="command", required=True)

    _add_score(sub)
    # each parser carries its handler via set_defaults(fn=...) so a new
    # subcommand can never be registered without one

    p = sub.add_parser("serve", help="REST job gateway on :8099")
    p.set_defaults(fn=cmd_serve)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8099)
    p.add_argument(
        "--elastic-url",
        default=None,
        help="Elasticsearch backend (ELASTIC_URL parity); default in-memory",
    )
    p.add_argument(
        "--query-endpoint",
        default=None,
        help="upstream Prometheus for /api/v1 proxy (QUERY_SERVICE_ENDPOINT)",
    )

    p = sub.add_parser("worker", help="scoring worker loop (brain)")
    p.set_defaults(fn=cmd_worker)
    p.add_argument("--elastic-url", default=None)
    p.add_argument("--poll", type=float, default=5.0)
    p.add_argument(
        "--claim-limit",
        type=int,
        default=_env_int("FOREMAST_CLAIM_LIMIT", 256),
        help="jobs claimed per tick; the whole claim scores as ONE batched "
        "program, so fleet-scale limits amortize fixed dispatch cost "
        "(env FOREMAST_CLAIM_LIMIT)",
    )
    p.add_argument(
        "--sharded",
        action="store_true",
        help="score over the full device mesh (multi-host via "
        "JAX_COORDINATOR_ADDRESS/JAX_NUM_PROCESSES/JAX_PROCESS_ID)",
    )
    p.add_argument(
        "--model-cache-dir",
        default=None,
        help="orbax-checkpoint trained models here (warm restart skips "
        "LSTM retraining); restored on startup",
    )
    p.add_argument(
        "--warmup",
        action="store_true",
        help="precompile the scoring programs for the canonical shapes "
        "(claim-limit batch, 7-day history) at startup instead of "
        "paying the 20-40 s XLA compile inside the first real tick",
    )
    p.add_argument(
        "--gauge-port",
        type=int,
        default=8000,
        help="foremastbrain:* gauge exposition port (0 disables)",
    )

    for name, fn, helptext in (
        ("watch", cmd_watch, "enable continuous monitoring (kubectl-watch parity)"),
        ("unwatch", cmd_unwatch, "disable continuous monitoring"),
    ):
        p = sub.add_parser(name, help=helptext)
        p.set_defaults(fn=fn)
        p.add_argument("name", help="DeploymentMonitor name (the app)")
        p.add_argument("--namespace", "-n", default="default")
        p.add_argument(
            "--api-server", default=None, help="API server URL (default in-cluster)"
        )

    p = sub.add_parser(
        "watch-plane",
        help="K8s controller loop: deployment watcher + status poller + remediation",
    )
    p.set_defaults(fn=cmd_watch_plane)
    p.add_argument(
        "--api-server", default=None, help="API server URL (default in-cluster)"
    )
    p.add_argument(
        "--namespace",
        default=None,
        help="controller's own namespace (NAMESPACE downward-API parity)",
    )
    p.add_argument(
        "--gauge-port",
        type=int,
        default=0,
        help="controller metrics/varz exposition port (0 disables; pick a "
        "port distinct from the worker's :8000 when co-hosted)",
    )

    p = sub.add_parser("ui", help="dashboard on :8080 (foremast-browser parity)")
    p.set_defaults(fn=cmd_ui)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument(
        "--service-endpoint",
        default=None,
        help="job-gateway base URL (FOREMAST_SERVICE_ENDPOINT)",
    )
    p.add_argument("--namespace", default=None, help="charted namespace label")
    p.add_argument("--app", default=None, help="charted app label")
    p.add_argument(
        "--demo",
        action="store_true",
        help="serve synthetic series from this process (no Prometheus needed)",
    )

    p = sub.add_parser("rules", help="print recording-rules manifest YAML")
    p.set_defaults(fn=cmd_rules)
    p.add_argument("--namespace", default="monitoring")

    return parser


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
