"""The dirty-series set — the ingest half of the reactive plane.

Detection in every plane built so far is tick-paced: a pushed anomaly
sits resident in the ring until the next full sweep claims its
document. The ingest plane already KNOWS the instant a sample arrives
(the receiver's handler thread), so this module turns that arrival
into work: the receiver marks the sample's route key dirty, and the
worker drains the dirty set between full ticks through micro-ticks
(`BrainWorker.micro_tick`) that claim JUST the dirty documents — full
ticks demote to repair sweeps that catch whatever micro-ticks missed.

`DirtySet` is a bounded, lock-guarded, insertion-ordered map of

    route key (the mesh partition identity: an app name, or the whole
    canonical series key for label-less series)  ->  arrival stamp

with these contracts:

  * **Arrival stamps are the RECEIVER's clock.** The stamp is taken
    when the push handler marks the key (`clock()`, wall time on the
    receiving worker), never from the pusher's sample timestamps —
    the push→verdict latency SLO (`foremast_verdict_latency_seconds`)
    must be immune to client clock skew. A pusher replaying yesterday's
    samples measures the time WE took, not the age of its data.
  * **Coalescing keeps the EARLIEST stamp.** Many pushes for one key
    before a drain are one unit of pending work; the latency a verdict
    finally observes is the oldest un-judged arrival's wait — the
    honest worst case, counted on ``coalesced``.
  * **Bounded, drop-oldest, never a leak.** Past ``max_keys`` the
    oldest entry drops and is counted on ``dropped``; the full sweep
    still judges those documents on its own cadence, so an overflow
    degrades latency attribution, never correctness.
  * **Ownership-filtered (mesh).** With an ``owns`` predicate wired
    (`MeshRouter.owns_series` — the CLAIM ring, the same ring the
    micro-tick's claim filter composes with), pushes for series
    another member owns are counted on ``foreign`` and NOT marked:
    the receiver accepts them losslessly (accept-and-hint), but this
    worker will never be able to claim their documents.

Thread-safety: receiver handler threads mark while the worker's tick
thread takes/requeues; everything behind one leaf lock (the ownership
probe runs BEFORE the lock is taken — no nesting into MeshRouter's).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import OrderedDict

from foremast_tpu.mesh.routing import DEFAULT_ROUTE_LABEL, series_route_key

DEFAULT_DIRTY_MAX = 8_192
DEFAULT_MICROTICK_DOCS = 256
# Sliced, preemptible sweeps (ISSUE 15): the slice size that bounds a
# sweep's preemption latency; 0 = monolithic sweeps. Resolved here —
# next to the other reactive-plane pacing knobs — so the worker and
# the cli's startup log share one warn-and-default parser.
DEFAULT_SWEEP_SLICE_DOCS = 2_048

_EVENTS = (
    "marked", "coalesced", "dropped", "foreign", "requeued",
    "unattributed",
    # sliced-sweep preemption outcomes (ISSUE 15, worker-side via
    # count()): an arrival triaged at a slice boundary either PROMOTED
    # its pooled documents to the next slice, or was requeued because
    # its document's slice was already in flight (windows possibly
    # pre-arrival — retried once the slice's write releases the doc)
    "promoted", "inflight_requeued",
)

log = logging.getLogger("foremast_tpu.reactive")


def _num(raw: str, default, cast, name: str):
    """Warn-and-default numeric env parse: a malformed knob must not
    kill worker startup with a raw traceback (cli._env_int's policy,
    shared by every reactive knob)."""
    if not raw:
        return default
    try:
        return cast(raw)
    except ValueError:
        log.warning("ignoring malformed %s=%r; using %r", name, raw, default)
        return default


def microtick_seconds_from_env() -> float:
    """THE resolution of FOREMAST_MICROTICK_SECONDS (empty = unset,
    0 = micro-ticks off) — one definition so the worker's pacing and
    the cli's startup log can never report different values."""
    return _num(
        os.environ.get("FOREMAST_MICROTICK_SECONDS", ""),
        0.0, float, "FOREMAST_MICROTICK_SECONDS",
    )


def microtick_docs_from_env() -> int:
    """THE resolution of FOREMAST_MICROTICK_DOCS (dirty route keys
    drained per micro-tick) — same single-definition discipline."""
    return _num(
        os.environ.get("FOREMAST_MICROTICK_DOCS", ""),
        DEFAULT_MICROTICK_DOCS, int, "FOREMAST_MICROTICK_DOCS",
    )


def sweep_slice_docs_from_env() -> int:
    """THE resolution of FOREMAST_SWEEP_SLICE_DOCS (ISSUE 15: sweep
    slice size, 0 = monolithic) — warn-and-default like every reactive
    knob, so an empty templated value degrades instead of killing
    worker startup."""
    return _num(
        os.environ.get("FOREMAST_SWEEP_SLICE_DOCS", ""),
        DEFAULT_SWEEP_SLICE_DOCS, int, "FOREMAST_SWEEP_SLICE_DOCS",
    )


class DirtySet:
    """Bounded arrival ledger keyed by route key; see module docstring."""

    def __init__(
        self,
        max_keys: int = DEFAULT_DIRTY_MAX,
        route_label: str = DEFAULT_ROUTE_LABEL,
        owns=None,
        clock=time.time,
        tenancy=None,
    ):
        self.max_keys = max(1, int(max_keys))
        self.route_label = route_label
        self.owns = owns  # series-key predicate (MeshRouter.owns_series)
        self._clock = clock
        self._lock = threading.Lock()
        self._keys: OrderedDict[str, float] = OrderedDict()
        self._counts = dict.fromkeys(_EVENTS, 0)
        # Tenant-fair drain (ISSUE 20): with >= 2 tenants configured,
        # take() serves tenants deficit-weighted instead of strictly
        # oldest-first, so a whale tenant's backlog cannot starve a
        # quiet tenant's arrival. With one (or zero) tenants, tenancy
        # stays None here and every path below is byte-identical to the
        # untenanted drain (the ISSUE 20 parity pin).
        self.tenancy = tenancy if tenancy is not None and tenancy.fair else None
        self._tenants: dict[str, str] = {}
        self._drr = None
        if self.tenancy is not None:
            from foremast_tpu.tenant.fairness import DeficitRoundRobin

            self._drr = DeficitRoundRobin(self.tenancy.weights())

    @staticmethod
    def from_env(route_label: str = DEFAULT_ROUTE_LABEL, owns=None, env=None):
        from foremast_tpu.tenant.registry import get_tenancy

        e = os.environ if env is None else env
        return DirtySet(
            max_keys=_num(
                e.get("FOREMAST_MICROTICK_DIRTY_MAX", ""),
                DEFAULT_DIRTY_MAX, int, "FOREMAST_MICROTICK_DIRTY_MAX",
            ),
            route_label=route_label,
            owns=owns,
            tenancy=get_tenancy(),
        )

    def __len__(self) -> int:
        with self._lock:
            return len(self._keys)

    # -- marking (receiver handler threads) -----------------------------

    def mark_series(self, key: str, now: float | None = None) -> bool:
        """Mark one pushed series' route key dirty, stamped with THIS
        process's clock (the receiver's arrival instant — see the
        module docstring's clock contract). Returns whether the key was
        marked (False = foreign under the ownership predicate)."""
        owns = self.owns
        if owns is not None and not owns(key):
            # probe OUTSIDE the dirty lock: MeshRouter takes its own
            with self._lock:
                self._counts["foreign"] += 1
            return False
        # tenant resolution OUTSIDE the dirty lock too (the registry's
        # cache lock is a peer leaf, never nested under this one)
        tenant = (
            self.tenancy.tenant_of_series(key)
            if self.tenancy is not None
            else None
        )
        self.mark(
            series_route_key(key, self.route_label),
            self._clock() if now is None else now,
            tenant=tenant,
        )
        return True

    def mark(self, route_key: str, now: float | None = None,
             requeue: bool = False, tenant: str | None = None) -> None:
        """Insert keeping the EARLIEST stamp; evict oldest past the cap.
        ``requeue=True`` is the worker giving back an arrival it could
        not attribute yet (released docs, claim brownout) — counted
        separately so the marked/coalesced counters stay push-only, and
        re-inserted at the FRONT of the drain order: its stamp is the
        original (oldest-running) arrival, and parking it behind
        fresher marks would priority-invert the very latencies the SLO
        histogram exists to bound."""
        if now is None:
            now = self._clock()
        with self._lock:
            if tenant is not None:
                self._tenants[route_key] = tenant
            cur = self._keys.get(route_key)
            if cur is not None:
                if now < cur:
                    self._keys[route_key] = now
                if requeue:
                    self._keys.move_to_end(route_key, last=False)
                self._counts["requeued" if requeue else "coalesced"] += 1
                return
            self._keys[route_key] = now
            if requeue:
                self._keys.move_to_end(route_key, last=False)
            self._counts["requeued" if requeue else "marked"] += 1
            while len(self._keys) > self.max_keys:
                old, _ = self._keys.popitem(last=False)
                self._tenants.pop(old, None)
                self._counts["dropped"] += 1

    # -- draining (worker tick thread) ----------------------------------

    def take(self, limit: int) -> list[tuple[str, float]]:
        """Pop up to `limit` entries as (key, stamp): oldest-marked
        first, and — when tenant fairness is active (ISSUE 20) —
        deficit-weighted across tenants so a whale's backlog cannot
        push a quiet tenant's arrival past one drain. Within a tenant
        the order stays oldest-first; with fairness off this is the
        exact pre-ISSUE-20 FIFO pop."""
        with self._lock:
            n = min(max(0, int(limit)), len(self._keys))
            if self._drr is None or n == len(self._keys):
                # fairness off, or draining everything anyway: plain
                # FIFO (identical order when every key is served)
                out = [self._keys.popitem(last=False) for _ in range(n)]
            else:
                # group pending keys by tenant (insertion order is
                # preserved per tenant), then serve in DRR order
                queues: dict[str, list[str]] = {}
                for rk in self._keys:
                    t = self._tenants.get(rk, "default")
                    queues.setdefault(t, []).append(rk)
                order = self._drr.pick(
                    {t: len(q) for t, q in queues.items()}, n
                )
                out = []
                for t in order:
                    rk = queues[t].pop(0)
                    out.append((rk, self._keys.pop(rk)))
            # the tenant map survives a take so a worker requeue
            # (mark(..., requeue=True)) keeps its attribution; prune
            # entries for keys no longer pending once it bloats past
            # twice the dirty cap
            if len(self._tenants) > 2 * self.max_keys:
                self._tenants = {
                    rk: t
                    for rk, t in self._tenants.items()
                    if rk in self._keys
                }
            return out

    def take_all(self) -> list[tuple[str, float]]:
        """Pop everything (the full sweep's catch-all drain)."""
        with self._lock:
            out = list(self._keys.items())
            self._keys.clear()
            return out

    def count(self, event: str, n: int = 1) -> None:
        """Fold a worker-side accounting event (``unattributed``) into
        the shared counter dict so one collector exports them all."""
        with self._lock:
            self._counts[event] = self._counts.get(event, 0) + n

    def counts(self) -> dict:
        with self._lock:
            return dict(self._counts)

    def debug_state(self) -> dict:
        with self._lock:
            return {
                "pending": len(self._keys),
                "max_keys": self.max_keys,
                "route_label": self.route_label,
                "owned_only": self.owns is not None,
                "tenant_fair": self._drr is not None,
                **self._counts,
            }


class ReactiveCollector:
    """prometheus_client custom collector over a `DirtySet` — the
    `foremast_microtick_dirty_*` families (docs/observability.md),
    materialized at scrape time so the push/mark hot path never touches
    prometheus_client."""

    def __init__(self, dirty: DirtySet):
        self._dirty = dirty

    def collect(self):
        from prometheus_client.core import (
            CounterMetricFamily,
            GaugeMetricFamily,
        )

        counts = self._dirty.counts()
        events = CounterMetricFamily(
            "foremast_microtick_dirty_events",
            "dirty-set traffic (marked=new key, coalesced=key already "
            "pending, dropped=evicted past FOREMAST_MICROTICK_DIRTY_MAX, "
            "foreign=owned by another mesh member, requeued=given back "
            "un-judged, unattributed=arrival no judged doc matched, "
            "promoted=sweep slice pulled forward for the arrival, "
            "inflight_requeued=arrival retried behind an in-flight slice)",
            labels=["event"],
        )
        for event in _EVENTS:
            events.add_metric([event], counts.get(event, 0))
        yield events
        yield GaugeMetricFamily(
            "foremast_microtick_dirty_pending",
            "route keys currently pending in the dirty set",
            value=len(self._dirty),
        )
