"""Reactive plane: event-driven detection — judge on arrival, not on tick.

Two halves (ISSUE 12), both riding existing machinery:

  * `dirty` — the ingest-triggered half: the receiver marks each
    pushed series' route key in a bounded `DirtySet`, and the worker
    drains it between full ticks through micro-ticks
    (`BrainWorker.micro_tick`) that claim just the dirty documents;
    full ticks demote to sweeps. The push→verdict latency histogram
    (`foremast_verdict_latency_seconds`) is the plane's SLO metric.
  * `watchstream` — the K8s half: `StreamingInformer` dispatches
    deployment events on arrival from `HttpKube.watch_deployments`
    (``watch=true`` long-poll, resourceVersion resume, 410-Gone
    re-list), with the 30 s resync demoted to a repair sweep.

See docs/operations.md "Event-driven detection".
"""

from foremast_tpu.reactive.dirty import (
    DEFAULT_DIRTY_MAX,
    DirtySet,
    ReactiveCollector,
    microtick_seconds_from_env,
)
from foremast_tpu.reactive.watchstream import (
    StreamingInformer,
    WatchStreamMetrics,
)

__all__ = [
    "DEFAULT_DIRTY_MAX",
    "DirtySet",
    "ReactiveCollector",
    "StreamingInformer",
    "WatchStreamMetrics",
    "microtick_seconds_from_env",
]
