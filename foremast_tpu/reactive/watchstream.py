"""Streaming deployment informer — the watch half of the reactive plane.

`StreamingInformer` replaces the list+diff `DeploymentInformer`'s
*detection* path with the API server's own event stream
(`HttpKube.watch_deployments`): events dispatch to the Barrelman
handler the instant the server writes them, instead of waiting out the
30 s resync. The list+diff machinery is NOT deleted — it becomes the
recovery and repair path, exactly client-go's reflector shape:

  * **prime**: one list (capturing the list's resourceVersion as the
    watch resume point) populates the snapshot and emits adds;
  * **consume**: hold the watch open for a scheduler window, applying
    each event to the snapshot and emitting the same
    add/update/delete handler calls the poll informer makes (one
    handler contract, two delivery mechanisms);
  * **resume**: a window that ends cleanly (server timeout), a
    mid-stream disconnect, or a torn tail reconnects from the last
    resourceVersion actually APPLIED — nothing is dropped, at-least-
    once delivery is the informer contract (handlers are level-driven);
  * **410 Gone** (resume point fell out of the server's event window):
    re-list and DIFF against the snapshot — missed events collapse
    into synthetic add/update/delete exactly like a resync, so the
    handler sees every net change even across a lossy stream;
  * **stall** (server stops writing without closing): the client's
    read timeout fires (`stall_margin`), counted and reconnected —
    a wedged proxy degrades to one margin of latency, never a hang;
  * **repair sweep**: the plane still calls `resync()` on the old
    30 s cadence, now only to catch divergence (it normally diffs to
    zero events).

Failure accounting rides `WatchStreamMetrics`
(``foremast_watch_stream_events`` / ``foremast_watch_stream_restarts``,
docs/observability.md) plus a local counter dict for /debug/state.
"""

from __future__ import annotations

import logging
import time

from foremast_tpu.watch.kubeapi import WatchGone
from foremast_tpu.watch.plane import DeploymentInformer, _key

log = logging.getLogger("foremast_tpu.watch.stream")

_EVENT_TYPES = ("added", "modified", "deleted", "error")
_RESTART_REASONS = ("gone", "stall", "error", "end")


class WatchStreamMetrics:
    """The two watch-stream families, on the default or an injected
    registry (the same `counter` sharing discipline the controller's
    transition counter uses)."""

    def __init__(self, registry=None):
        from foremast_tpu.observe.spans import counter

        self.events = counter(
            "foremast_watch_stream_events_total",
            "deployment watch-stream events dispatched, by event type "
            "(added/modified/deleted/error)",
            ("type",),
            registry,
        )
        self.restarts = counter(
            "foremast_watch_stream_restarts_total",
            "watch-stream reconnects, by cause (gone=410 re-list, "
            "stall=read timeout, error=transport/breaker, end=server "
            "closed the window)",
            ("reason",),
            registry,
        )


class StreamingInformer(DeploymentInformer):
    """Event-driven deployment informer over a streaming kube client.

    Same handler contract as `DeploymentInformer` (add/update/delete
    with the previous object); `resync()` stays the repair/recovery
    path and additionally captures the list resourceVersion when the
    client exposes `list_deployments_rv`."""

    def __init__(
        self,
        kube,
        handler,
        namespace: str | None = None,
        metrics: WatchStreamMetrics | None = None,
        clock=time.monotonic,
    ) -> None:
        super().__init__(kube, handler)
        self.namespace = namespace
        self.metrics = metrics
        self._clock = clock
        self._rv = ""  # last resourceVersion APPLIED (resume point)
        # /debug/state mirror of the metric families (single-threaded
        # mutation: the plane loop owns this informer)
        self.counts = {
            "events": 0,
            **{f"restart_{r}": 0 for r in _RESTART_REASONS},
        }

    # -- repair / recovery (list+diff) ----------------------------------

    def resync(self) -> None:
        lister = getattr(self.kube, "list_deployments_rv", None)
        if lister is None:
            return super().resync()
        items, rv = lister(self.namespace)
        if rv:
            self._rv = rv
        self._apply_list({_key(d): d for d in items})

    # -- the stream -----------------------------------------------------

    def consume(self, seconds: float, stall_margin: float = 5.0) -> int:
        """Hold the watch open for ~`seconds`, dispatching each event
        on arrival. Returns #events dispatched. Never raises: stream
        failures are counted restarts and the next call reconnects
        (a 410 triggers the re-list + diff recovery HERE, so no caller
        can forget it)."""
        if not self._primed or not self._rv:
            # not yet primed, OR the resume point was invalidated by a
            # 410 whose recovery re-list ALSO failed (apiserver still
            # down at that instant): keep retrying the list on every
            # window — detection must come back the moment the server
            # does, not at the next 30 s repair sweep
            try:
                self.resync()
            except Exception:  # noqa: BLE001 — next window retries
                self._count_restart("error")
                log.warning(
                    "watch list failed; retrying on the next window"
                )
                return 0
        if not self._rv:
            # a client that lists without a resourceVersion cannot
            # resume; the resync above already delivered the state
            return 0
        n = 0
        try:
            for etype, obj in self.kube.watch_deployments(
                namespace=self.namespace,
                resource_version=self._rv,
                timeout_seconds=seconds,
                stall_margin=stall_margin,
            ):
                self._dispatch(etype, obj)
                n += 1
            self._count_restart("end")
        except WatchGone:
            self._count_restart("gone")
            log.info(
                "watch resume point %s expired (410); re-listing", self._rv
            )
            self._rv = ""
            try:
                self.resync()  # diff emits whatever the stream lost
            except Exception:  # noqa: BLE001 - next window retries
                log.exception("re-list after 410 failed")
        except TimeoutError:
            # the server stopped writing without closing: a stall
            self._count_restart("stall")
            log.warning(
                "watch stream stalled (> %.1fs without data); "
                "reconnecting from rv %s", seconds + stall_margin, self._rv,
            )
        except OSError as e:
            # disconnects, refused connections, open breakers
            # (BreakerOpen ⊂ ConnectionError) — reconnect next window
            self._count_restart("error")
            log.warning(
                "watch stream error (%s: %s); reconnecting from rv %s",
                type(e).__name__, e, self._rv,
            )
        return n

    def _dispatch(self, etype: str, obj: dict) -> None:
        rv = str(obj.get("metadata", {}).get("resourceVersion") or "")
        key = _key(obj)
        low = etype.lower()
        self.counts["events"] += 1
        if self.metrics is not None:
            self.metrics.events.labels(
                type=low if low in _EVENT_TYPES else "error"
            ).inc()
        if etype == "DELETED":
            self._snapshot.pop(key, None)
            self._emit("delete", obj, None)
        elif etype in ("ADDED", "MODIFIED"):
            old = self._snapshot.get(key)
            self._snapshot[key] = obj
            if old is None:
                self._emit("add", obj, None)
            elif rv and rv != str(
                old.get("metadata", {}).get("resourceVersion") or ""
            ):
                self._emit("update", obj, old)
        else:
            log.debug("ignoring watch event type %r for %s", etype, key)
        if rv:
            # advance the resume point only AFTER the event is applied:
            # a crash between read and apply must re-deliver, not skip
            self._rv = rv

    def _count_restart(self, reason: str) -> None:
        self.counts[f"restart_{reason}"] += 1
        if self.metrics is not None:
            self.metrics.restarts.labels(reason=reason).inc()

    def debug_state(self) -> dict:
        return {
            "resource_version": self._rv,
            "deployments_cached": len(self._snapshot),
            **self.counts,
        }
