"""Per-tenant accounting (ISSUE 20).

One leaf-locked ledger per registry: sheds (receiver admission +
decode-pool pressure), evictions (ring LRU + arena row recycling,
charged to the tenant CAUSING the eviction, not the one losing the
row), claims (docs scheduled into sweep slices / micro-ticks) and
resident ring bytes. The collector exports these as the
``foremast_tenant_*`` families; ``/debug/state`` renders the same
snapshot.

Tenant names are folded through the registry's metric-label cap before
they become ledger keys, so the ledger is bounded by the same
cardinality bound as the exported labels (cap + ``other`` overflow
bucket).

Lock order: the registry's resolution lock is taken and released while
folding the tenant name BEFORE the ledger lock is acquired — the two
leaf locks never nest.
"""

from __future__ import annotations

import threading

from foremast_tpu.tenant.registry import TenantRegistry

_COUNTER_KINDS = ("shed", "evictions", "claims")


class TenantAccounting:
    """Thread-safe per-tenant counters. Counter kinds are monotonic;
    ``ring_bytes`` is a gauge maintained by byte deltas from the ring
    shards (clamped at zero: a shard restart must not export negative
    residency)."""

    def __init__(self, registry: TenantRegistry):
        self.registry = registry
        self._lock = threading.Lock()  # tenant.accounting (leaf)
        self.shed: dict[str, int] = {}
        self.evictions: dict[str, int] = {}
        self.claims: dict[str, int] = {}
        self.ring_bytes: dict[str, int] = {}

    def _bump(self, table: dict[str, int], tenant: str, n: int) -> None:
        # fold through the cardinality cap OUTSIDE the ledger lock
        # (registry lock and ledger lock are both leaves, never nested)
        name = self.registry.metric_tenant(tenant)
        with self._lock:
            table[name] = table.get(name, 0) + n

    def count_shed(self, tenant: str, n: int = 1) -> None:
        self._bump(self.shed, tenant, n)

    def count_eviction(self, tenant: str, n: int = 1) -> None:
        self._bump(self.evictions, tenant, n)

    def count_claims(self, tenant: str, n: int = 1) -> None:
        self._bump(self.claims, tenant, n)

    def add_ring_bytes(self, tenant: str, delta: int) -> None:
        if not delta:
            return
        name = self.registry.metric_tenant(tenant)
        with self._lock:
            cur = self.ring_bytes.get(name, 0) + delta
            self.ring_bytes[name] = cur if cur > 0 else 0

    def snapshot(self) -> dict[str, dict[str, int]]:
        """``{tenant: {shed, evictions, claims, ring_bytes}}`` over
        every tenant any table has seen, sorted for stable rendering
        (/debug/state, bench reports)."""
        with self._lock:
            tenants = (
                set(self.shed)
                | set(self.evictions)
                | set(self.claims)
                | set(self.ring_bytes)
            )
            return {
                t: {
                    "shed": self.shed.get(t, 0),
                    "evictions": self.evictions.get(t, 0),
                    "claims": self.claims.get(t, 0),
                    "ring_bytes": self.ring_bytes.get(t, 0),
                }
                for t in sorted(tenants)
            }


# One ledger per registry: the ring, arena, receiver and worker must
# all charge into the same tables or /debug/state and the collector
# would each see a partial picture.
_ACCT_LOCK = threading.Lock()  # tenant.accounting-factory (leaf)


def accounting_for(registry: TenantRegistry) -> TenantAccounting:
    acct = getattr(registry, "_accounting", None)
    if acct is None:
        with _ACCT_LOCK:
            acct = getattr(registry, "_accounting", None)
            if acct is None:
                acct = TenantAccounting(registry)
                registry._accounting = acct
    return acct
