"""Ingest byte-rate envelopes (ISSUE 20).

The receiver half of tenant isolation: a token bucket per tenant that
has an ``ingest_bytes_per_s`` envelope configured. Admission runs
inside ``decode_apply`` — AFTER decode (tenant identity lives in the
series labels, so it cannot exist before the frame is parsed) and
BEFORE the ring apply, on both the JSON and FMW1 binary codecs by
construction (they share that one path). A batch whose dominant tenant
is over its envelope is shed whole with 429 + a computed Retry-After:
re-pushing is idempotent at the ring (same timestamps re-apply to the
same points), so atomically rejecting the batch is safe and keeps the
"which bytes were accepted" contract trivial.

Tenants without an envelope always admit — the global inflight cap and
decode-pool depth remain the backstops they are today, so an
unconfigured fleet sheds exactly as it did before ISSUE 20.

``blame()`` attributes pre-decode sheds (decode-pool busy, where no
tenant can be known yet): the most-over-budget governed tenant is
overwhelmingly the source of queue pressure, and charging it keeps the
``decode-shed included`` promise without decoding anything.
"""

from __future__ import annotations

import math
import threading

from foremast_tpu.tenant.registry import TenantRegistry

RETRY_AFTER_MIN = 1
RETRY_AFTER_MAX = 60


class IngestGovernor:
    """Per-tenant token buckets over decoded push bytes. Thread-safe
    behind one leaf lock; only tenants with a configured
    ``ingest_bytes_per_s`` are governed."""

    def __init__(self, registry: TenantRegistry):
        self.registry = registry
        self._lock = threading.Lock()  # tenant.governor (leaf)
        # tenant -> [tokens, last_refill_monotonic]
        self._buckets: dict[str, list[float]] = {}

    def _burst(self, spec) -> float:
        # default burst = 2 s of envelope: one fat batch from a
        # well-behaved agent must not trip the governor
        return float(spec.burst_bytes or 2 * spec.ingest_bytes_per_s)

    def admit(self, tenant: str, nbytes: int, now: float) -> float:
        """0.0 = admitted (tokens burned); > 0 = shed, the value being
        the Retry-After seconds until the bucket can cover ``nbytes``.
        Ungoverned tenants always admit."""
        spec = self.registry.spec(tenant)
        rate = spec.ingest_bytes_per_s
        if rate <= 0:
            return 0.0
        burst = self._burst(spec)
        with self._lock:
            bucket = self._buckets.get(tenant)
            if bucket is None:
                bucket = [burst, now]
                self._buckets[tenant] = bucket
            tokens, last = bucket
            tokens = min(burst, tokens + (now - last) * rate)
            bucket[1] = now
            if tokens >= nbytes:
                bucket[0] = tokens - nbytes
                return 0.0
            bucket[0] = tokens
            retry = (nbytes - tokens) / rate
        return float(
            min(max(math.ceil(retry), RETRY_AFTER_MIN), RETRY_AFTER_MAX)
        )

    def blame(self, now: float) -> str | None:
        """The governed tenant deepest over its envelope right now
        (fullest bucket deficit relative to its rate), or None when
        every bucket has headroom — the attribution target for sheds
        that fire before decode can name a tenant."""
        worst = None
        worst_wait = 0.0
        with self._lock:
            for tenant, bucket in self._buckets.items():
                spec = self.registry.spec(tenant)
                rate = spec.ingest_bytes_per_s
                if rate <= 0:
                    continue
                burst = self._burst(spec)
                tokens = min(burst, bucket[0] + (now - bucket[1]) * rate)
                # seconds until this tenant's bucket is half-full again:
                # > 0 only when it has been draining faster than it
                # refills
                wait = (burst / 2 - tokens) / rate
                if wait > worst_wait:
                    worst_wait = wait
                    worst = tenant
        return worst

    def debug_state(self, now: float) -> dict:
        with self._lock:
            return {
                tenant: {
                    "tokens": int(
                        min(
                            self._burst(self.registry.spec(tenant)),
                            bucket[0]
                            + (now - bucket[1])
                            * self.registry.spec(tenant).ingest_bytes_per_s,
                        )
                    ),
                    "burst": int(self._burst(self.registry.spec(tenant))),
                }
                for tenant, bucket in sorted(self._buckets.items())
            }
