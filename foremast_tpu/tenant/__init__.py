"""Multi-tenant QoS plane (ISSUE 20).

Tenancy was only a label until now: PR 15 proved routing is
label-shape-invariant, but one tenant's push flood shed every tenant's
samples, one tenant's 100k-service sweep delayed every tenant's
micro-ticks, and ring/arena budgets were global. This package makes the
tenant a scheduling and accounting dimension everywhere a shared
resource is contended:

- :mod:`registry` — tenant resolution from the canonical series/doc
  label plus per-tenant weights and budget envelopes
  (``FOREMAST_TENANTS``, inline JSON or ``@path``).
- :mod:`accounting` — per-tenant shed/eviction/claim/ring-byte
  counters behind one leaf lock, cardinality-capped.
- :mod:`fairness` — deficit-weighted round-robin used by the sweep
  pool's slice ordering and the dirty-set drain.
- :mod:`envelopes` — ingest byte-rate governor (token buckets) whose
  429s + Retry-After target the flooding tenant's pushes.
- :mod:`collector` — the ``foremast_tenant_*`` metric families and the
  ``/debug/state`` tenants section.

The contract throughout: tenancy reorders claims and redirects
eviction/shed pressure; it never changes a verdict. With one (or zero)
tenants configured every seam keeps its zero-cost ``None`` check and
behavior is byte-identical to an untenanted build.
"""

from foremast_tpu.tenant.accounting import TenantAccounting, accounting_for
from foremast_tpu.tenant.collector import (
    TenantCollector,
    debug_tenants,
    register_collector,
)
from foremast_tpu.tenant.envelopes import IngestGovernor
from foremast_tpu.tenant.fairness import DeficitRoundRobin
from foremast_tpu.tenant.registry import (
    DEFAULT_TENANT,
    OTHER_TENANT,
    TenantRegistry,
    TenantSpec,
    get_tenancy,
    set_tenancy,
    tenancy_from_env,
)

__all__ = [
    "DEFAULT_TENANT",
    "OTHER_TENANT",
    "DeficitRoundRobin",
    "IngestGovernor",
    "TenantAccounting",
    "TenantCollector",
    "TenantRegistry",
    "TenantSpec",
    "accounting_for",
    "debug_tenants",
    "register_collector",
    "get_tenancy",
    "set_tenancy",
    "tenancy_from_env",
]
