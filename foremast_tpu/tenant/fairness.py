"""Deficit-weighted round-robin (ISSUE 20).

The scheduling half of the QoS plane: given per-tenant backlogs, emit
the order in which queued work should be served so that over any
window each backlogged tenant receives service proportional to its
weight. Used by the sweep pool's slice ordering (``_SweepPool.take``)
and the dirty-set drain (``DirtySet.take``) — the two seams where a
whale tenant's backlog could otherwise starve a quiet tenant, because
both drain strictly FIFO today.

Classic DRR with unit-cost items: each round every backlogged tenant
earns its weight in credits and is served while its deficit covers the
next item. Deficits persist across calls (a tenant that got less than
its share this slice catches up on the next), but a tenant with no
backlog banks nothing — idle credit must not turn into a burst that
starves everyone else later.

The picker is deterministic (tenants rotate in sorted-name order) and
carries NO locks: each consumer calls it under its own lock.
"""

from __future__ import annotations

from typing import Mapping


class DeficitRoundRobin:
    def __init__(
        self, weights: Mapping[str, float], default_weight: float = 1.0
    ):
        self._weights = dict(weights)
        self._default = max(float(default_weight), 1e-9)
        self._deficit: dict[str, float] = {}

    def weight(self, tenant: str) -> float:
        w = self._weights.get(tenant, self._default)
        return w if w > 0 else self._default

    def pick(self, queued: Mapping[str, int], n: int) -> list[str]:
        """A tenant name per service slot: serve up to ``n`` items from
        the given per-tenant backlog counts, weight-proportionally.
        The result's length is ``min(n, sum(queued))``; consumers pop
        their per-tenant FIFOs in this order."""
        remaining = {t: int(c) for t, c in queued.items() if c > 0}
        # empty tenants bank no credit; drop their stale deficits so
        # the dict stays bounded by the active-tenant set
        for t in list(self._deficit):
            if t not in remaining:
                del self._deficit[t]
        out: list[str] = []
        if n <= 0 or not remaining:
            return out
        rotation = sorted(remaining)
        # weights are normalized so the lightest backlogged tenant
        # earns ~1 credit per round: every round serves at least one
        # item and heavy tenants get proportionally more
        min_w = min(self.weight(t) for t in rotation)
        while len(out) < n and remaining:
            for t in rotation:
                if t not in remaining:
                    continue
                self._deficit[t] = self._deficit.get(t, 0.0) + (
                    self.weight(t) / min_w
                )
                while (
                    self._deficit.get(t, 0.0) >= 1.0
                    and t in remaining
                    and len(out) < n
                ):
                    self._deficit[t] -= 1.0
                    remaining[t] -= 1
                    if remaining[t] <= 0:
                        del remaining[t]
                        # served dry: surplus credit is forfeited, not
                        # banked (see module docstring)
                        self._deficit.pop(t, None)
                    out.append(t)
                if len(out) >= n:
                    break
        return out
