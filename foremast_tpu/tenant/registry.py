"""Tenant resolution + budget envelopes (ISSUE 20).

A tenant is resolved from the canonical series/doc label named by
``FOREMAST_TENANT_LABEL`` (default ``tenant``); anything unlabeled maps
to ``default``, so an unlabeled fleet keeps today's semantics exactly.
Per-tenant weights and budget envelopes come from ``FOREMAST_TENANTS``
(inline JSON, or ``@path`` to a JSON file — the ``FOREMAST_CHAOS_PLAN``
convention). Unset means no registry: every seam keeps its zero-cost
``None`` check and nothing changes.

Envelope JSON — either a bare ``{name: spec}`` map or
``{"tenants": {name: spec}}``::

    {"acme": {"weight": 4, "ring_bytes": 4194304,
              "arena_rows": 512, "ingest_bytes_per_s": 262144},
     "default": {"weight": 1}}

All spec fields are optional; ``0`` means "no envelope" for budgets and
weights default to 1.0. Malformed JSON raises at startup — a QoS plane
that silently protects nothing is worse than a crash.

Metric-label capping is BrainGauges-style: configured tenants always
get their own label value; unconfigured-but-labeled tenants claim label
slots up to ``FOREMAST_TENANT_LABEL_MAX`` distinct values, after which
they fold into the ``other`` overflow bucket (dropped names counted
once each, warn-once).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import re
import threading
import urllib.parse

from foremast_tpu.ingest.wire import canonical_series

log = logging.getLogger("foremast_tpu.tenant")

DEFAULT_TENANT = "default"
OTHER_TENANT = "other"
DEFAULT_LABEL = "tenant"
DEFAULT_LABEL_MAX = 64

# label extraction from a CANONICAL selector (label values are escaped
# and sorted by wire.canonical_series, so a plain scan for
# `label="value"` is exact, not heuristic — mesh/routing._label_re)
_LABEL_RE_CACHE: dict[str, re.Pattern] = {}


def _label_re(label: str) -> re.Pattern:
    pat = _LABEL_RE_CACHE.get(label)
    if pat is None:
        pat = re.compile(r'[{,]\s*%s="((?:[^"\\]|\\.)*)"' % re.escape(label))
        _LABEL_RE_CACHE[label] = pat
    return pat


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One tenant's scheduling weight and budget envelopes. A budget of
    0 means "no envelope" — the tenant competes under the global caps
    only, exactly as every tenant did before ISSUE 20."""

    name: str
    weight: float = 1.0
    ring_bytes: int = 0
    arena_rows: int = 0
    ingest_bytes_per_s: int = 0
    burst_bytes: int = 0

    @classmethod
    def from_json(cls, name: str, obj) -> "TenantSpec":
        if not isinstance(obj, dict):
            raise ValueError(f"tenant {name!r}: spec must be an object")
        known = {
            "weight",
            "ring_bytes",
            "arena_rows",
            "ingest_bytes_per_s",
            "burst_bytes",
        }
        bad = set(obj) - known
        if bad:
            raise ValueError(f"tenant {name!r}: unknown fields {sorted(bad)}")
        weight = float(obj.get("weight", 1.0))
        if weight <= 0:
            raise ValueError(f"tenant {name!r}: weight must be > 0")
        return cls(
            name=name,
            weight=weight,
            ring_bytes=int(obj.get("ring_bytes", 0)),
            arena_rows=int(obj.get("arena_rows", 0)),
            ingest_bytes_per_s=int(obj.get("ingest_bytes_per_s", 0)),
            burst_bytes=int(obj.get("burst_bytes", 0)),
        )


_DEFAULT_SPEC = TenantSpec(name=DEFAULT_TENANT)


class TenantRegistry:
    """Tenant resolution + spec lookup. Resolution caches are bounded
    and guarded by one leaf lock; spec data is immutable after
    construction so reads need no lock."""

    def __init__(
        self,
        specs: dict[str, TenantSpec],
        label: str | None = None,
        label_max: int = DEFAULT_LABEL_MAX,
    ):
        self.label = label or tenant_label()
        self.specs = dict(specs)
        self.label_max = max(int(label_max), 1)
        # >= 2 configured tenants activates fair scheduling; with one
        # (or zero) the claim order must stay byte-identical to an
        # untenanted build (the ISSUE 20 parity pin)
        self.fair = len(self.specs) >= 2
        self._lock = threading.Lock()  # tenant.registry (leaf)
        self._doc_cache: dict[str, str] = {}
        self._series_cache: dict[str, str] = {}
        self._key_cache: dict = {}
        self._cache_max = 65536
        # metric-label cardinality cap (BrainGauges-style): configured
        # tenants are always exported; unknown labels claim slots up to
        # label_max, then fold into the `other` overflow bucket
        self._metric_names: set[str] = set(self.specs) | {DEFAULT_TENANT}
        # the cap budget covers UNCONFIGURED values only — configured
        # tenants (+ default) are the operator's own bounded set and
        # must never crowd the observation budget (or vice versa)
        self._configured_names = len(self._metric_names)
        self._dropped_names: set[str] = set()
        self._dropped_track_limit = max(4 * self.label_max, 1024)
        self.dropped_label_values = 0
        self._cap_warned = False

    # -- spec lookup ----------------------------------------------------

    def spec(self, tenant: str) -> TenantSpec:
        s = self.specs.get(tenant)
        if s is not None:
            return s
        return _DEFAULT_SPEC

    def weight(self, tenant: str) -> float:
        return self.spec(tenant).weight

    def weights(self) -> dict[str, float]:
        return {name: s.weight for name, s in self.specs.items()}

    # -- resolution -----------------------------------------------------

    def _extract(self, text: str) -> str:
        m = _label_re(self.label).search(text)
        if m:
            return m.group(1)
        return DEFAULT_TENANT

    def tenant_of_series(self, key: str) -> str:
        """Tenant of a pushed/stored series, from its canonical
        selector. Unlabeled series -> ``default``."""
        with self._lock:
            t = self._series_cache.get(key)
            if t is not None:
                return t
        t = self._extract(canonical_series(key))
        with self._lock:
            if len(self._series_cache) >= self._cache_max:
                self._series_cache.clear()
            self._series_cache[key] = t
        return t

    def tenant_of_doc(self, doc) -> str:
        """Tenant of a document, from the tenant label inside its query
        config strings (URL-encoded PromQL selectors included — the
        config is unquoted before the scan). Cached by doc id: ids are
        content-addressed, so the resolution is immutable per id."""
        doc_id = getattr(doc, "id", None)
        if doc_id is not None:
            with self._lock:
                t = self._doc_cache.get(doc_id)
                if t is not None:
                    return t
        text = "%s\n%s" % (
            getattr(doc, "current_config", "") or "",
            getattr(doc, "historical_config", "") or "",
        )
        t = self._extract(urllib.parse.unquote(text))
        if doc_id is not None:
            with self._lock:
                if len(self._doc_cache) >= self._cache_max:
                    self._doc_cache.clear()
                self._doc_cache[doc_id] = t
        return t

    def tenant_of_key(self, key) -> str:
        """Tenant of an arena fit key. Univariate keys embed the
        history URL (URL-encoded selector, tenant label included);
        joint keys carry only app/alias names and resolve to
        ``default`` unless an alias happens to carry the label."""
        try:
            hash(key)
            hashable = True
        except TypeError:
            hashable = False
        if hashable:
            with self._lock:
                t = self._key_cache.get(key)
                if t is not None:
                    return t
        t = self._extract(urllib.parse.unquote(str(key)))
        if hashable:
            with self._lock:
                if len(self._key_cache) >= self._cache_max:
                    self._key_cache.clear()
                self._key_cache[key] = t
        return t

    # -- metric-label capping -------------------------------------------

    def metric_tenant(self, tenant: str) -> str:
        """The label value to export for ``tenant``: itself while under
        the cardinality cap, ``other`` past it. Configured tenants and
        ``default`` always export; the cap only bounds unconfigured
        label values (a tenant-shaped cardinality attack on the
        registry's histograms)."""
        with self._lock:
            if tenant in self._metric_names:
                return tenant
            if (
                len(self._metric_names) - self._configured_names
                < self.label_max
            ):
                self._metric_names.add(tenant)
                return tenant
            if tenant not in self._dropped_names:
                if len(self._dropped_names) < self._dropped_track_limit:
                    self._dropped_names.add(tenant)
                self.dropped_label_values += 1
                if not self._cap_warned:
                    self._cap_warned = True
                    log.warning(
                        "tenant label cardinality cap (%d) reached; "
                        "folding new tenant label values into %r",
                        self.label_max,
                        OTHER_TENANT,
                    )
            return OTHER_TENANT

    # -- introspection --------------------------------------------------

    def debug_state(self) -> dict:
        with self._lock:
            exported = len(self._metric_names)
            dropped = self.dropped_label_values
        return {
            "label": self.label,
            "configured": {
                name: dataclasses.asdict(s)
                for name, s in sorted(self.specs.items())
            },
            "fair": self.fair,
            "label_max": self.label_max,
            "label_values_exported": exported,
            "label_values_dropped": dropped,
        }


# -- env wiring ---------------------------------------------------------


def tenant_label(env=None) -> str:
    e = os.environ if env is None else env
    return e.get("FOREMAST_TENANT_LABEL", "") or DEFAULT_LABEL


def _label_max(env=None) -> int:
    e = os.environ if env is None else env
    raw = e.get("FOREMAST_TENANT_LABEL_MAX", "")
    if not raw:
        return DEFAULT_LABEL_MAX
    try:
        return max(int(raw), 1)
    except ValueError:
        log.warning(
            "FOREMAST_TENANT_LABEL_MAX=%r is not an int; using %d",
            raw,
            DEFAULT_LABEL_MAX,
        )
        return DEFAULT_LABEL_MAX


def tenancy_from_env(env=None) -> TenantRegistry | None:
    """Build the registry from ``FOREMAST_TENANTS`` (inline JSON, or
    ``@path`` to a JSON file); None when unset — the caller then wires
    NO seams and every client keeps its zero-cost None check. Malformed
    envelopes raise: a QoS plane that silently protects nothing is
    worse than a crash at startup."""
    e = os.environ if env is None else env
    raw = e.get("FOREMAST_TENANTS", "")
    if not raw:
        return None
    if raw.startswith("@"):
        with open(raw[1:], encoding="utf-8") as fh:
            raw = fh.read()
    obj = json.loads(raw)
    if not isinstance(obj, dict):
        raise ValueError("FOREMAST_TENANTS: top level must be an object")
    tenants = obj.get("tenants", obj)
    if not isinstance(tenants, dict):
        raise ValueError("FOREMAST_TENANTS: 'tenants' must be an object")
    specs = {
        str(name): TenantSpec.from_json(str(name), spec)
        for name, spec in tenants.items()
    }
    reg = TenantRegistry(
        specs, label=tenant_label(e), label_max=_label_max(e)
    )
    log.info(
        "tenant QoS plane active: %d tenant(s) on label %r, fair=%s",
        len(specs),
        reg.label,
        reg.fair,
    )
    return reg


# Process-global registry: the worker, receiver, ring and arena all see
# one resolution + accounting view. Lazily built from env on first use;
# tests/benches swap it with set_tenancy().
_GLOBAL_LOCK = threading.Lock()  # tenant.global (leaf)
_GLOBAL: TenantRegistry | None = None
_GLOBAL_SET = False


def get_tenancy() -> TenantRegistry | None:
    global _GLOBAL, _GLOBAL_SET
    with _GLOBAL_LOCK:
        if _GLOBAL_SET:
            return _GLOBAL
    # build OUTSIDE the lock: an @path envelope opens a file, and the
    # global lock is a leaf that must never wrap I/O. A racing second
    # builder is harmless — construction is deterministic from env and
    # only the first install wins.
    reg = tenancy_from_env()
    with _GLOBAL_LOCK:
        if not _GLOBAL_SET:
            _GLOBAL = reg
            _GLOBAL_SET = True
        return _GLOBAL


def set_tenancy(reg: TenantRegistry | None) -> TenantRegistry | None:
    """Install (or clear) the process-global registry; returns the
    previous one so tests can restore it."""
    global _GLOBAL, _GLOBAL_SET
    with _GLOBAL_LOCK:
        prev = _GLOBAL if _GLOBAL_SET else None
        _GLOBAL = reg
        _GLOBAL_SET = True
        return prev
