"""Tenant attribution export (ISSUE 20).

`TenantCollector` is a prometheus_client custom collector over one
`TenantAccounting` ledger — the ``foremast_tenant_*`` families
(docs/observability.md), materialized at scrape time so none of the
charging seams (receiver admission, ring eviction, arena recycling,
claim scheduling) ever touch prometheus_client on a hot path.

Every family's ``tenant`` label is bounded by the registry's
cardinality cap (``FOREMAST_TENANT_LABEL_MAX`` + the ``other``
overflow bucket): the ledger folds names BEFORE they become keys, so
the exported label set can never exceed cap + 1 values.

`debug_tenants` renders the same ledger (plus the registry's envelope
config and the ingest governor's live buckets) as the ``tenants``
section of ``/debug/state``.
"""

from __future__ import annotations

import time

from foremast_tpu.tenant.accounting import TenantAccounting


class TenantCollector:
    def __init__(self, accounting: TenantAccounting):
        self._accounting = accounting

    def collect(self):
        from prometheus_client.core import (
            CounterMetricFamily,
            GaugeMetricFamily,
        )

        snap = self._accounting.snapshot()
        shed = CounterMetricFamily(
            "foremast_tenant_shed",
            "pushes shed charged to the tenant over its ingest envelope "
            "(receiver admission 429s + decode-pool sheds blamed on the "
            "deepest-over-budget tenant); tenant label bounded by "
            "FOREMAST_TENANT_LABEL_MAX + the `other` overflow bucket",
            labels=["tenant"],
        )
        evictions = CounterMetricFamily(
            "foremast_tenant_evictions",
            "ring series + arena row evictions charged to the tenant "
            "CAUSING the pressure (the pusher/allocator, not the "
            "victim); tenant label bounded by FOREMAST_TENANT_LABEL_MAX "
            "+ the `other` overflow bucket",
            labels=["tenant"],
        )
        claims = CounterMetricFamily(
            "foremast_tenant_claims",
            "documents scheduled into sweep slices and micro-ticks, by "
            "tenant (the deficit-weighted fair share actually served); "
            "tenant label bounded by FOREMAST_TENANT_LABEL_MAX + the "
            "`other` overflow bucket",
            labels=["tenant"],
        )
        ring_bytes = GaugeMetricFamily(
            "foremast_tenant_ring_bytes",
            "resident ring bytes by tenant (the live share of the "
            "FOREMAST_RING_BYTES budget); tenant label bounded by "
            "FOREMAST_TENANT_LABEL_MAX + the `other` overflow bucket",
            labels=["tenant"],
        )
        for tenant, row in snap.items():
            shed.add_metric([tenant], row["shed"])
            evictions.add_metric([tenant], row["evictions"])
            claims.add_metric([tenant], row["claims"])
            ring_bytes.add_metric([tenant], row["ring_bytes"])
        yield shed
        yield evictions
        yield claims
        yield ring_bytes


def register_collector(prom_registry, accounting) -> bool:
    """Idempotently join ``prom_registry``'s exposition with the
    ``foremast_tenant_*`` families over ``accounting``. Safe to call
    from every worker construction: prometheus_client rejects a second
    collector exporting the same family names with ValueError, which
    here just means an earlier worker (or the lint harness) already
    wired this registry — not an error."""
    if prom_registry is None:
        return False
    try:
        prom_registry.register(TenantCollector(accounting))
        return True
    except ValueError:
        return False


def debug_tenants(
    registry,
    accounting: TenantAccounting | None = None,
    governor=None,
    now: float | None = None,
) -> dict:
    """The ``tenants`` section of ``/debug/state``: envelope config,
    the per-tenant attribution ledger, and (when the receiver wired a
    governor) the live ingest buckets."""
    out = {"registry": registry.debug_state()}
    if accounting is not None:
        out["accounting"] = accounting.snapshot()
    if governor is not None:
        out["ingest_buckets"] = governor.debug_state(
            time.monotonic() if now is None else now
        )
    return out
