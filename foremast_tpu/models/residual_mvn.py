"""Seasonal-residual multivariate Gaussian — the joint contextual detector.

Companion to the learned LSTM detector for 3+ metric jobs (reference model
zoo: "3+ metrics: Deep Learning (LSTM)", `docs/guides/design.md:84`). Pure
reconstruction scoring has a structural blind spot: an autoencoder that
*sees* an in-window anomaly can reproduce ("copy") it, and a plain
marginal check misses contextual anomalies (a spike at a seasonal trough
lands near the marginal mean). This detector closes both gaps with two
closed-form, TPU-native pieces:

  1. per-metric causal Holt-Winters residuals — `hw_continue` predictions
     never see the point they score, so an anomaly cannot be copied, and
     the seasonal state removes the cycle, so trough-masked spikes stand
     out;
  2. a full-covariance Gaussian over the F-dimensional residual vector —
     co-movement between metrics is learned from historical residuals, so
     a single metric deviating from the pack (correlation break) scores a
     large Mahalanobis distance even when its marginal z-score is modest.

Threshold calibration: the reference's thresholds are "number of sigmas"
(`foremast-brain.yaml:26-27`). A fixed d^2 > thr^2 rule would get tighter
with F (chi^2_F mass grows with F), so the cutoff is the chi^2_F quantile
whose tail mass equals the two-sided normal tail P(|z| > thr) — the same
false-positive rate as the univariate detectors at the same configured
threshold, at any metric count.
"""

from __future__ import annotations

import dataclasses
import functools
from functools import partial

import jax
import jax.numpy as jnp

from foremast_tpu.ops.forecasters import Forecast, holt_winters, hw_continue

# Holt-Winters smoothing used for residual extraction (fixed, not
# grid-fit: residual covariance absorbs model error, and fixed params keep
# the fit cacheable per job without a per-metric grid search).
HW_PARAMS = (0.3, 0.05, 0.1)
SEASON_LENGTH = 24


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class MVNState:
    """Fitted residual model for a batch of F-metric jobs.

    hw:    Forecast with [B*F]-flattened leaves (terminal HW state per
           (job, metric) series; season [B*F, m])
    mu:    [B, F]    historical residual means
    cov:   [B, F, F] historical residual covariance (ridge-regularized)
    valid: [B]       enough history + well-conditioned covariance
    """

    hw: Forecast
    mu: jax.Array
    cov: jax.Array
    valid: jax.Array


@functools.lru_cache(maxsize=256)
def chi2_quantile(threshold: float, dof: int) -> float:
    """chi^2_dof cutoff with the same tail mass as P(|z| > threshold).

    Host-side (scipy), called once per judgment batch with static dof."""
    from scipy import stats

    p_tail = 2.0 * stats.norm.sf(threshold)
    p_tail = min(max(p_tail, 1e-300), 1.0)
    return float(stats.chi2.ppf(1.0 - p_tail, dof))


@partial(jax.jit, static_argnames=("season_length", "min_points", "ridge"))
def fit_residual_mvn(
    hist: jax.Array,
    mask: jax.Array | None = None,
    season_length: int = SEASON_LENGTH,
    min_points: int = 10,
    ridge: float = 1e-6,
) -> MVNState:
    """Fit per-metric HW + residual covariance.

    hist: [B, F, Th] aligned joint histories (joint observations are
    intersected upstream, `multivariate._align`, so every metric of a job
    shares one validity pattern); mask: [B, Th] valid-prefix mask for
    bucket-padded batches (None = all valid).

    Identifiability guard (the same 2-cycle rule as `fit_holt_winters`
    and the auto screen): a batch whose static length holds fewer than
    two full seasons fits with season length 1 instead — the HW
    degenerates to Holt's linear method, residuals stay causal and the
    covariance still learns co-movement; only the (unidentifiable) cycle
    is dropped. Without this, a daily-configured engine (m=1440) would
    either disable the MVN outright on sub-2-day histories (empty warm
    region -> valid=False) or score against a season memorized from one
    partial cycle. That m=1 degradation is also the short-history entry
    point for cold-start admission (ISSUE 10): a newcomer's 1-2 pushed
    days fit a valid Holt-residual Gaussian immediately, and background
    refinement refits at the full season once coverage clears two
    cycles."""
    b, f, th = hist.shape
    a, bt, g = HW_PARAMS
    m_eff = int(season_length) if th >= 2 * int(season_length) else 1
    if mask is None:
        mask = jnp.ones((b, th), bool)
    flat = hist.reshape(b * f, th)
    mflat = jnp.repeat(mask, f, axis=0)
    fc = holt_winters(flat, mflat, m_eff, a, bt, g)
    resid = (flat - fc.pred).reshape(b, f, th)
    # drop the first season: those predictions come from init state
    warm_mask = mask & (jnp.arange(th)[None, :] >= m_eff)  # [B, Th]
    n = jnp.maximum(jnp.sum(warm_mask, axis=-1), 1)  # [B]
    w = warm_mask[:, None, :].astype(resid.dtype)  # [B, 1, Th]
    mu = jnp.sum(resid * w, axis=-1) / n[:, None]  # [B, F]
    rc = (resid - mu[:, :, None]) * w
    # full-precision accumulation: the 10k-term residual outer products
    # feed a solve — TPU default-bf16 matmul accumulation would quantize
    # the covariance (same hazard as the seasonal Gram, seasonal._design)
    cov = (
        jnp.einsum("bft,bgt->bfg", rc, rc, precision=jax.lax.Precision.HIGHEST)
        / n[:, None, None]
    )
    # scale-aware ridge keeps tiny-magnitude metrics invertible without
    # distorting their geometry
    tr = jnp.trace(cov, axis1=-2, axis2=-1) / f  # [B]
    eye = jnp.eye(f, dtype=cov.dtype)
    cov = cov + (ridge * tr + 1e-12)[:, None, None] * eye
    # conditioning: det of the ridged cov must be positive and finite
    sign, logdet = jnp.linalg.slogdet(cov)
    valid = (n >= min_points) & (sign > 0) & jnp.isfinite(logdet)
    return MVNState(hw=fc, mu=mu, cov=cov, valid=valid)


@partial(jax.jit, static_argnames=("season_length", "min_points", "ridge"))
def fit_residual_mvn_bf16_delta(
    anchor: jax.Array,
    delta: jax.Array,
    mask: jax.Array | None = None,
    season_length: int = SEASON_LENGTH,
    min_points: int = 10,
    ridge: float = 1e-6,
) -> MVNState:
    """`fit_residual_mvn` from an anchor-shifted bf16-delta upload.

    hist ships as (f32 anchor [B, F], bf16 delta [B, F, Th]) — the same
    2 B/point wire layout as `scoring.fit_forecast_bf16_delta`; f32
    values are reconstructed in-program (transient HBM, the saving is
    the H2D bound of cold joint fleet ticks). Deltas are packed masked
    (exact zeros), so masked slots reconstruct to exact zero like the
    f32 pack path."""
    values = anchor[:, :, None] + delta.astype(jnp.float32)
    if mask is not None:
        values = values * mask[:, None, :]
    return fit_residual_mvn(
        values,
        mask,
        season_length=season_length,
        min_points=min_points,
        ridge=ridge,
    )


def _d2(state: MVNState, cur: jax.Array, upd: jax.Array) -> jax.Array:
    """d^2 [B, Tc] with per-(job, t) state-update gating.

    upd [B, Tc] False carries HW state THROUGH a point (it is still
    scored — the residual is measured against the un-updated prediction
    — but cannot contaminate later predictions); the phase advances
    either way (hw_continue mask semantics).

    Mesh contract (ISSUE 13; gathered-state layouts in ISSUE 19):
    per-row independent along [B] — the [B*F] reshape below multiplies
    the leading axis, which a data-axis sharding of `cur` follows
    cleanly (B a multiple of the axis), and the per-job `linalg.solve`
    batches row-locally. The MVNState rows arrive already gathered per
    batch position — from a replicated arena via a global take, or from
    a data-axis-SHARDED arena via the shard_map local gather in
    `multivariate.lstm_joint_score_from_rows_sharded` — either way the
    state leading axis shards exactly like `cur`. Nothing here may
    reduce across [B]."""
    b, f, tc = cur.shape
    a, bt, g = HW_PARAMS
    flat = cur.reshape(b * f, tc)
    pred, _ = hw_continue(
        state.hw,
        flat,
        jnp.repeat(upd, f, axis=0),
        state.hw.season.shape[-1],
        a,
        bt,
        g,
    )
    resid = (flat - pred).reshape(b, f, tc)
    d = resid - state.mu[:, :, None]  # [B, F, Tc]
    # solve per job: cov [B,F,F] x X = d  -> d^T cov^-1 d per time step
    sol = jnp.linalg.solve(state.cov, d)  # [B, F, Tc]
    return jnp.sum(d * sol, axis=1)  # [B, Tc]


@jax.jit
def residual_mvn_d2(state: MVNState, cur: jax.Array) -> jax.Array:
    """Mahalanobis d^2 [B, Tc] for aligned joint current windows
    [B, F, Tc]: causal HW residual per metric against the historical
    residual Gaussian. The season length is the STATE's own (its buffer
    width): a short-history fit that degenerated to m=1 (see
    `fit_residual_mvn`) must be continued at m=1, not zeroed against
    the configured length."""
    return _d2(state, cur, jnp.ones(cur.shape[::2], bool))


@jax.jit
def residual_mvn_d2_robust(
    state: MVNState, cur: jax.Array, gate_cutoff: jax.Array | float
) -> jax.Array:
    """Two-pass outlier-robust d^2 (the judge's scoring path).

    The plain pass lets every observed point update the HW state, so an
    anomalous spike at t contaminates the t+1 prediction and manufactures
    an ECHO — a false borderline d^2 right after every true anomaly.
    Robust filtering: pass 1 computes plain d^2; pass 2 recomputes it
    with state updates gated OFF at every point pass 1 put over
    `gate_cutoff` [B]. Echoes vanish (the spike never enters the state)
    while a sustained true shift keeps scoring high — the state can no
    longer absorb it, which strictly helps recall."""
    d2 = _d2(state, cur, jnp.ones(cur.shape[::2], bool))
    gate = jnp.asarray(gate_cutoff, d2.dtype)
    if gate.ndim == 1:
        gate = gate[:, None]
    return _d2(state, cur, ~(d2 > gate))


@jax.jit
def score_residual_mvn(
    state: MVNState,
    cur: jax.Array,
    d2_cutoff: jax.Array | float,
) -> jax.Array:
    """Anomaly flags [B, Tc]: d^2 (`residual_mvn_d2`) exceeding the
    calibrated cutoff (see `chi2_quantile`). Invalid fits flag nothing."""
    d2 = residual_mvn_d2(state, cur)
    cutoff = jnp.asarray(d2_cutoff, d2.dtype)
    if cutoff.ndim == 1:
        cutoff = cutoff[:, None]
    return (d2 > cutoff) & state.valid[:, None]
