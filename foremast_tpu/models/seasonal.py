"""Seasonal trend model — the Prophet substitute.

The reference model zoo lists Prophet for single-metric seasonal series
(`docs/guides/design.md:73`). Prophet itself (Stan-based MAP fitting) is a
poor fit for XLA; per SURVEY.md section 7.6 the substitution — documented here —
is a *piecewise-linear trend + Fourier seasonality* ridge regression,
which is the core of Prophet's additive model (trend with automatic
changepoints + seasonality, no holiday terms) and fits in closed form:

    y(t) ~ w0 + w1*t + sum_j d_j * max(t - c_j, 0)
                + sum_k [a_k sin(2 pi k t / P) + b_k cos(2 pi k t / P)]

The hinge features at evenly spaced interior knots c_j are Prophet's
changepoint mechanism: a redeploy-style level shift fits as a local ramp
instead of corrupting the global slope and mis-centering the band at the
horizon. Capacity control is primarily the SPARSE knot grid (8 knots
over the history), not the ridge: even with normalized O(1) columns the
masked Gram diagonal (~n/3) dwarfs the default Tikhonov term, so
`cp_ridge` (the analog of Prophet's changepoint prior) only bites for
extreme values — measured: cp_ridge in {1, 100, 1e4} yields identical
fits on both shift and clean seasonal series at T=1008, with spurious
terminal trend already bounded at noise level (~1e-4/step) by the grid
alone.

Batched masked normal equations: the design matrix X [T, K] is shared
across the batch; per-series masked Gram matrices are one einsum, solved by
`jnp.linalg.solve` on [B, K, K] — all MXU work, no per-series loops.

Returns the standard `Forecast` contract: the fitted seasonal cycle is
materialized into the `season` buffer (one full period), so `horizon()`
extrapolates trend + repeating seasonality exactly like Holt-Winters.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from foremast_tpu.ops.forecasters import Forecast
from foremast_tpu.ops.windows import masked_std


def _knots(t_len: int, n_changepoints: int) -> list[float]:
    """Evenly spaced interior changepoint positions over the first 90% of
    the history (Prophet places its grid over the first 80-90% so the
    tail trend is extrapolation-stable)."""
    if n_changepoints <= 0 or t_len < 4:
        return []
    hi = 0.9 * (t_len - 1)
    return [hi * (j + 1) / (n_changepoints + 1) for j in range(n_changepoints)]


def _design(
    t_idx: jax.Array,
    period: int,
    order: int,
    dtype,
    knots: list[float] = (),
    t_scale: float = 1.0,
) -> jax.Array:
    """Feature matrix [len(t_idx), 2 + len(knots) + 2*order]:
    [1, t/t_scale, hinge((t - c_j)/t_scale)..., sin/cos harmonics...].

    `t_scale` normalizes the trend/hinge columns to O(1) — with raw time
    indices the Gram matrix carries O(T^3) entries, which the TPU's
    default-bf16 matmul accumulation cannot represent (measured: the
    shift-scenario F1 drops 0.998 -> 0.979 and the trend scenario
    0.999 -> 0.92 on-chip with raw columns)."""
    t = t_idx.astype(dtype) / float(t_scale)
    cols = [jnp.ones_like(t), t]
    for c in knots:
        cols.append(jnp.maximum(t - float(c / t_scale), 0.0))
    for k in range(1, order + 1):
        w = 2.0 * jnp.pi * k / (period / float(t_scale))
        cols.append(jnp.sin(w * t))
        cols.append(jnp.cos(w * t))
    return jnp.stack(cols, axis=-1)


@partial(jax.jit, static_argnames=("period", "order", "n_changepoints"))
def fit_seasonal(
    values: jax.Array,
    mask: jax.Array,
    period: int = 1440,
    order: int = 3,
    ridge: float = 1e-3,
    n_changepoints: int = 8,
    cp_ridge: float = 1.0,
) -> Forecast:
    """Fit piecewise-trend+Fourier model per series. values/mask: [B, T].

    `period` in time steps (1440 = daily seasonality at the reference's
    60 s PromQL step, `metricsquery.go:43`); `order` harmonics;
    `n_changepoints` hinge knots for the piecewise trend (Prophet's
    automatic-changepoint core, `design.md:73`); `cp_ridge` scales the
    hinge columns' share of the ridge (directionally Prophet's
    changepoint prior, though the sparse knot grid is the effective
    capacity control — see the module docstring's measurement). The
    terminal `trend` is the LAST segment's slope (base slope plus every
    activated hinge), so the horizon extrapolates the post-shift regime,
    not a bogus global average slope.

    Histories shorter than two full periods are seasonally
    unidentifiable — the harmonics are near-collinear with the trend
    over a partial cycle, so the "fitted" cycle would be noise that the
    horizon then extrapolates; such SERIES get the global-mean model
    (same two-layer rule as `fit_holt_winters`: a static early-out for
    all-short batches plus a per-series select for short real histories
    riding a long padded bucket). This two-layer rule is also the
    short-history entry point for cold-start admission (ISSUE 10): a
    newcomer admitted on 1-2 days of ring coverage under a daily
    season fits the honest mean model with real historical-std bands —
    verdict-capable immediately — and picks up the seasonal cycle when
    background refinement refits it past two periods.
    """
    from foremast_tpu.ops.forecasters import (
        _guard_unidentifiable,
        moving_average_all,
    )

    b, t_len = values.shape
    if t_len < 2 * int(period):
        return moving_average_all(values, mask)
    dtype = values.dtype
    knots = _knots(t_len, n_changepoints)
    n_cp = len(knots)
    # normalized trend/hinge columns + full-precision accumulation: the
    # Gram solve is numerically load-bearing (see _design docstring)
    t_scale = float(t_len)
    hi = jax.lax.Precision.HIGHEST
    x = _design(jnp.arange(t_len), period, order, dtype, knots, t_scale)
    k = x.shape[-1]
    m = mask.astype(dtype)  # [B, T]
    # per-series masked Gram: G[b] = X^T diag(m_b) X   -> [B, K, K]
    xm = x[None, :, :] * m[:, :, None]  # [B, T, K]
    gram = jnp.einsum("btk,tl->bkl", xm, x, precision=hi)
    rhs = jnp.einsum("btk,bt->bk", xm, values, precision=hi)
    # per-column ridge: hinge (slope-change) weights carry the stronger
    # penalty — Prophet's changepoint prior as a diagonal Tikhonov term
    ridge_diag = jnp.asarray(
        [ridge, ridge] + [ridge * cp_ridge] * n_cp + [ridge] * (2 * order),
        dtype,
    )
    w = jnp.linalg.solve(
        gram + jnp.diag(ridge_diag)[None], rhs[..., None]
    )[..., 0]  # [B, K]

    pred = jnp.einsum("tk,bk->bt", x, w, precision=hi)
    scale = masked_std((values - pred) * m, mask)

    # Materialize one full seasonal cycle over ABSOLUTE phases (season[:, j]
    # = seasonal value at any step ≡ j mod P) so `horizon` can start at
    # each series' own continuation point: the forecast resumes right after
    # the last VALID step (n_valid), not after the bucket-padded array end
    # — a [288]-valid history in a [512] bucket must not shift the cycle.
    xf = _design(
        jnp.arange(period), period, order, dtype, t_scale=t_scale
    )  # [P, 2+2*order]
    # last valid absolute index per series (consistent with the absolute
    # positions the regression itself uses, including interior gaps)
    last_valid = jnp.max(
        jnp.where(mask, jnp.arange(t_len)[None, :], -1), axis=-1
    )
    # trend value + slope AT each series' last valid step: base line plus
    # every hinge active there (the post-changepoint regime). Weights act
    # on the NORMALIZED time axis (t/t_scale), so per-step slopes divide
    # by t_scale and hinge activations compare normalized positions.
    lv = last_valid.astype(dtype) / t_scale
    level = w[:, 0] + w[:, 1] * lv
    trend = w[:, 1] / t_scale
    for j, c in enumerate(knots):
        d_j = w[:, 2 + j]
        cn = c / t_scale
        level = level + d_j * jnp.maximum(lv - cn, 0.0)
        trend = trend + d_j * (lv > cn).astype(dtype) / t_scale
    seas_f = jnp.einsum(
        "pk,bk->bp", xf[:, 2:], w[:, 2 + n_cp :], precision=hi
    )  # [B, P] harmonics only
    fc = Forecast(
        pred=pred,
        scale=scale,
        level=level,
        trend=trend,
        season=seas_f,
        season_phase=((last_valid + 1) % period).astype(jnp.int32),
    )
    return _guard_unidentifiable(fc, values, mask, int(period))
