"""Seasonal trend model — the Prophet substitute.

The reference model zoo lists Prophet for single-metric seasonal series
(`docs/guides/design.md:73`). Prophet itself (Stan-based MAP fitting) is a
poor fit for XLA; per SURVEY.md section 7.6 the substitution — documented here —
is a *linear trend + Fourier seasonality* ridge regression, which is the
core of Prophet's additive model (trend + seasonality, no holiday terms)
and fits in closed form:

    y(t) ~ w0 + w1 * t + sum_k [a_k sin(2 pi k t / P) + b_k cos(2 pi k t / P)]

Batched masked normal equations: the design matrix X [T, K] is shared
across the batch; per-series masked Gram matrices are one einsum, solved by
`jnp.linalg.solve` on [B, K, K] — all MXU work, no per-series loops.

Returns the standard `Forecast` contract: the fitted seasonal cycle is
materialized into the `season` buffer (one full period), so `horizon()`
extrapolates trend + repeating seasonality exactly like Holt-Winters.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from foremast_tpu.ops.forecasters import Forecast
from foremast_tpu.ops.windows import masked_std


def _design(t_idx: jax.Array, period: int, order: int, dtype) -> jax.Array:
    """Feature matrix [len(t_idx), 2 + 2*order]: [1, t, sin/cos harmonics]."""
    t = t_idx.astype(dtype)
    cols = [jnp.ones_like(t), t]
    for k in range(1, order + 1):
        w = 2.0 * jnp.pi * k / period
        cols.append(jnp.sin(w * t))
        cols.append(jnp.cos(w * t))
    return jnp.stack(cols, axis=-1)


@partial(jax.jit, static_argnames=("period", "order"))
def fit_seasonal(
    values: jax.Array,
    mask: jax.Array,
    period: int = 1440,
    order: int = 3,
    ridge: float = 1e-3,
) -> Forecast:
    """Fit trend+Fourier model per series. values/mask: [B, T].

    `period` in time steps (1440 = daily seasonality at the reference's
    60 s PromQL step, `metricsquery.go:43`); `order` harmonics.

    Histories shorter than two full periods are seasonally
    unidentifiable — the harmonics are near-collinear with the trend
    over a partial cycle, so the "fitted" cycle would be noise that the
    horizon then extrapolates; such SERIES get the global-mean model
    (same two-layer rule as `fit_holt_winters`: a static early-out for
    all-short batches plus a per-series select for short real histories
    riding a long padded bucket).
    """
    from foremast_tpu.ops.forecasters import (
        _guard_unidentifiable,
        moving_average_all,
    )

    b, t_len = values.shape
    if t_len < 2 * int(period):
        return moving_average_all(values, mask)
    dtype = values.dtype
    x = _design(jnp.arange(t_len), period, order, dtype)  # [T, K]
    k = x.shape[-1]
    m = mask.astype(dtype)  # [B, T]
    # per-series masked Gram: G[b] = X^T diag(m_b) X   -> [B, K, K]
    xm = x[None, :, :] * m[:, :, None]  # [B, T, K]
    gram = jnp.einsum("btk,tl->bkl", xm, x)
    rhs = jnp.einsum("btk,bt->bk", xm, values)
    eye = jnp.eye(k, dtype=dtype)
    w = jnp.linalg.solve(gram + ridge * eye[None], rhs[..., None])[..., 0]  # [B, K]

    pred = jnp.einsum("tk,bk->bt", x, w)
    scale = masked_std((values - pred) * m, mask)

    # Materialize one full seasonal cycle over ABSOLUTE phases (season[:, j]
    # = seasonal value at any step ≡ j mod P) so `horizon` can start at
    # each series' own continuation point: the forecast resumes right after
    # the last VALID step (n_valid), not after the bucket-padded array end
    # — a [288]-valid history in a [512] bucket must not shift the cycle.
    xf = _design(jnp.arange(period), period, order, dtype)  # [P, K]
    # last valid absolute index per series (consistent with the absolute
    # positions the regression itself uses, including interior gaps)
    last_valid = jnp.max(
        jnp.where(mask, jnp.arange(t_len)[None, :], -1), axis=-1
    )
    level = w[:, 0] + w[:, 1] * last_valid.astype(dtype)  # trend at last step
    trend = w[:, 1]
    seas_f = jnp.einsum("pk,bk->bp", xf[:, 2:], w[:, 2:])  # [B, P]
    fc = Forecast(
        pred=pred,
        scale=scale,
        level=level,
        trend=trend,
        season=seas_f,
        season_phase=((last_valid + 1) % period).astype(jnp.int32),
    )
    return _guard_unidentifiable(fc, values, mask, int(period))
