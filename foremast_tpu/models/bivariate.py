"""Bivariate normal detector for 2-metric jobs.

Reference model zoo: "2 metrics: Bivariate Normal Distribution"
(`docs/guides/design.md:78`). The historical joint distribution of two
metrics (e.g. latency x tps) is fit as a 2-D Gaussian; current points are
scored by Mahalanobis distance, anomalous where d^2 exceeds the chi^2(2)
quantile implied by the configured threshold.

Batched closed-form fit — means/covariances are masked moment sums over the
[B, T] history, the 2x2 inverse is explicit (no linalg solve inside jit),
so the whole detector is a handful of fused VPU ops.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from foremast_tpu.ops.windows import masked_mean


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class BivariateFit:
    """mean: [B, 2]; cov: [B, 2, 2]; valid: [B] (enough points, non-singular)."""

    mean: jax.Array
    cov: jax.Array
    valid: jax.Array


def fit_bivariate(
    x: jax.Array, y: jax.Array, mask: jax.Array, min_points: int = 10
) -> BivariateFit:
    """Fit a 2-D Gaussian to paired histories. x/y/mask: [B, T].

    Short-history entry point (ISSUE 10 admission): the fit is moment-
    based, so any history clearing `min_points` yields a VALID,
    verdict-capable Gaussian — a newcomer admitted on 1-2 days of ring
    coverage fits exactly like a 7-day history, just with wider moment
    uncertainty; below the floor `valid=False` degrades the job to
    UNKNOWN, never to a fragile fit."""
    mx = masked_mean(x, mask)
    my = masked_mean(y, mask)
    m = mask.astype(x.dtype)
    n = jnp.sum(m, axis=-1)
    dx = (x - mx[:, None]) * m
    dy = (y - my[:, None]) * m
    denom = jnp.maximum(n, 1.0)
    sxx = jnp.sum(dx * dx, axis=-1) / denom
    syy = jnp.sum(dy * dy, axis=-1) / denom
    sxy = jnp.sum(dx * dy, axis=-1) / denom
    mean = jnp.stack([mx, my], axis=-1)
    cov = jnp.stack(
        [jnp.stack([sxx, sxy], -1), jnp.stack([sxy, syy], -1)], axis=-2
    )
    det = sxx * syy - sxy * sxy
    # relative conditioning test: scale-free, so low-magnitude metric pairs
    # (e.g. error rates ~1e-4) stay valid while truly degenerate
    # (perfectly-correlated or zero-variance) fits are rejected
    valid = (n >= min_points) & (det > 1e-6 * sxx * syy) & (sxx * syy > 0)
    return BivariateFit(mean=mean, cov=cov, valid=valid)


def mahalanobis2(fit: BivariateFit, x: jax.Array, y: jax.Array) -> jax.Array:
    """Squared Mahalanobis distance of current points. x/y: [B, T] -> [B, T]."""
    dx = x - fit.mean[:, 0:1]
    dy = y - fit.mean[:, 1:2]
    sxx = fit.cov[:, 0, 0][:, None]
    syy = fit.cov[:, 1, 1][:, None]
    sxy = fit.cov[:, 0, 1][:, None]
    det = jnp.maximum(sxx * syy - sxy * sxy, 1e-30)
    # explicit 2x2 inverse
    return (syy * dx * dx - 2.0 * sxy * dx * dy + sxx * dy * dy) / det


def detect_bivariate(
    fit: BivariateFit,
    x: jax.Array,
    y: jax.Array,
    mask: jax.Array,
    threshold: jax.Array | float = 2.0,
) -> jax.Array:
    """Anomaly flags [B, T]: d^2 > threshold^2 per-axis-sigma equivalent.

    `threshold` keeps the reference's "number of sigmas" semantics
    (`foremast-brain.yaml:26-27`): a point is anomalous when it lies outside
    the ellipsoid whose per-axis radius is threshold sigmas, i.e.
    d^2 > threshold^2 (chi^2(2) generalization of |z| > threshold).
    Windows with an invalid fit flag nothing (unknown, not unhealthy).
    """
    threshold = jnp.asarray(threshold, x.dtype)
    if threshold.ndim == 1:
        threshold = threshold[:, None]
    d2 = mahalanobis2(fit, x, y)
    return mask & (d2 > threshold * threshold) & fit.valid[:, None]


@partial(jax.jit, static_argnames=("min_points",))
def fit_bivariate_bf16_delta(
    anchor_x: jax.Array,
    delta_x: jax.Array,
    anchor_y: jax.Array,
    delta_y: jax.Array,
    mask: jax.Array,
    min_points: int = 10,
) -> BivariateFit:
    """`fit_bivariate` from an anchor-shifted bf16-delta history upload.

    Mirrors `scoring.fit_forecast_bf16_delta`: the paired histories ship
    as (f32 anchor [B], bf16 delta [B, T]) per metric — 2 B/point on the
    wire instead of f32's 4 — and f32 values are reconstructed
    in-program (transient HBM; the saving is the H2D, which bounds cold
    joint fleet ticks over a degraded tunnel). Deltas are packed masked
    (exact zeros in masked slots), so reconstruction multiplies the mask
    back in to keep masked slots at exact zero like the f32 pack."""
    m = mask.astype(jnp.float32)
    x = (anchor_x[:, None] + delta_x.astype(jnp.float32)) * m
    y = (anchor_y[:, None] + delta_y.astype(jnp.float32)) * m
    return fit_bivariate(x, y, mask, min_points=min_points)


@jax.jit
def detect_bivariate_from_rows(
    mean: jax.Array,
    cov: jax.Array,
    rows: jax.Array,
    x: jax.Array,
    y: jax.Array,
    mask: jax.Array,
    threshold: jax.Array,
) -> jax.Array:
    """`detect_bivariate` against ARENA-resident fits (engine.arena
    .TreeArena): `mean` [capacity, 2] / `cov` [capacity, 2, 2] hold one
    fitted Gaussian per arena row and `rows` [B] indexes the batch's
    fits, so a warm re-check tick ships only the current windows and a
    row-index vector — the joint counterpart of
    `scoring.score_from_arena`. Only VALID fits are ever admitted to the
    arena (the judge caches invalid fits nowhere), so the gathered state
    carries no validity flag.

    Mesh contract (ISSUE 13): per-row independent along [B] — `x`/`y`/
    `mask` may arrive with their leading axis sharded over a data axis
    (B a multiple of it) with `mean`/`cov` replicated; the gather then
    reads each device's local arena replica, zero collectives."""
    fit = BivariateFit(
        mean=jnp.take(mean, rows, axis=0),
        cov=jnp.take(cov, rows, axis=0),
        valid=jnp.ones(rows.shape, bool),
    )
    return detect_bivariate(fit, x, y, mask, threshold)


@partial(jax.jit, static_argnames=("mesh",))
def detect_bivariate_from_rows_sharded(
    mean: jax.Array,
    cov: jax.Array,
    rows: jax.Array,
    x: jax.Array,
    y: jax.Array,
    mask: jax.Array,
    threshold: jax.Array,
    mesh=None,
) -> jax.Array:
    """`detect_bivariate_from_rows` against a DATA-AXIS-SHARDED arena
    (ISSUE 19): `mean`/`cov` block-shard their [capacity] leading axis
    over `mesh`'s data axis and `rows` [B] carries LOCAL (per-shard)
    indices — the judge's block placement rule guarantees each batch
    position's fit lives on the device holding that position, so the
    gather runs as a shard_map against each device's OWN block: zero
    cross-chip transfer, without replication's per-device HBM copy."""
    from foremast_tpu.parallel import mesh as meshlib

    g = meshlib.shard_rows_take({"mean": mean, "cov": cov}, rows, mesh)
    fit = BivariateFit(
        mean=g["mean"],
        cov=g["cov"],
        valid=jnp.ones(rows.shape, bool),
    )
    return detect_bivariate(fit, x, y, mask, threshold)
