"""Bounded per-(service, metric) model cache with optional checkpointing.

The reference brain holds fitted models in a bounded in-memory cache
(`MAX_CACHE_SIZE`, `foremast-brain/README.md:30`) and recomputes on miss —
durable state lives in ES so any node can resume any job (SURVEY.md section 5,
checkpoint/resume). This keeps those semantics and adds what the reference
lacks: an optional orbax checkpoint of trained params (e.g. LSTM-AE
weights) keyed by (service, metric), so warm-starting after restart skips
retraining (SURVEY.md section 5 "new build" note).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Hashable

import jax


class ModelCache:
    """Thread-safe LRU of fitted model state."""

    def __init__(self, max_size: int = 1000):
        self.max_size = max_size
        self._d: OrderedDict[Hashable, Any] = OrderedDict()
        self._lock = threading.Lock()
        # bumped on every mutation (put/pop/clear/eviction, including
        # batch forms) — lets callers cache derived views of entries and
        # revalidate with ONE integer compare per tick instead of
        # re-reading every key (worker admission caching)
        self.version = 0

    def get(self, key: Hashable):
        with self._lock:
            if key not in self._d:
                return None
            self._d.move_to_end(key)
            return self._d[key]

    def peek(self, key: Hashable):
        """Lock-free read that does NOT refresh LRU order. Safe under
        the GIL (a plain dict read); callers that rely on entries
        staying resident must pair peeks with a periodic batched
        get_many to keep the LRU honest, or size the cache for the
        working set."""
        # deliberate lock-free fast path (per-tick hot lookup); the GIL
        # makes the single dict read atomic
        return self._d.get(key)  # foremast: ignore[lock-discipline]

    def put(self, key: Hashable, value) -> None:
        with self._lock:
            self.version += 1
            self._d[key] = value
            self._d.move_to_end(key)
            while len(self._d) > self.max_size:
                self._d.popitem(last=False)

    def get_many(self, keys) -> list:
        """Batched get: ONE lock acquisition for a whole tick's key list
        (a fleet tick looks up 40k+ fit keys; a per-key lock round trip
        is measurable on the worker's single host core). None keys (and
        misses) yield None."""
        with self._lock:
            d = self._d
            out = []
            for k in keys:
                if k is not None and k in d:
                    d.move_to_end(k)
                    out.append(d[k])
                else:
                    out.append(None)
            return out

    def put_many(self, items) -> None:
        """Batched put of (key, value) pairs under one lock."""
        with self._lock:
            self.version += 1
            d = self._d
            for k, v in items:
                d[k] = v
                d.move_to_end(k)
            while len(d) > self.max_size:
                d.popitem(last=False)

    def pop(self, key: Hashable) -> None:
        """Drop an entry if present (e.g. warmup fits that must not
        occupy real capacity)."""
        with self._lock:
            self.version += 1
            self._d.pop(key, None)

    def clear(self) -> None:
        with self._lock:
            self.version += 1
            self._d.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._d)

    def snapshot(self) -> dict:
        """Point-in-time copy of the contents (lock-guarded) — e.g. the
        pod-mode leader broadcasting its restored cache."""
        with self._lock:
            return dict(self._d)

    # -- optional durability (orbax) ------------------------------------

    def save(self, path: str) -> None:
        """Checkpoint the cache contents (pytree values only) via orbax."""
        import orbax.checkpoint as ocp

        with self._lock:
            items = dict(self._d)
        keys = sorted(items, key=str)
        tree = {"keys": [str(k) for k in keys], "values": [items[k] for k in keys]}
        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(path, tree, force=True)

    def load(self, path: str, key_parser=None) -> int:
        """Restore a checkpoint; keys round-trip as strings unless a
        `key_parser` maps them back. Returns number of entries loaded."""
        import orbax.checkpoint as ocp

        ckptr = ocp.PyTreeCheckpointer()
        tree = ckptr.restore(path)
        keys, values = tree["keys"], tree["values"]
        for k, v in zip(keys, values):
            self.put(key_parser(k) if key_parser else k, v)
        return len(keys)

    # -- host-local durability (multi-host workers) ---------------------

    def save_local(self, path: str) -> None:
        """Host-local checkpoint (pickle, atomic rename): unlike save(),
        performs NO cross-process coordination. Under jax.distributed,
        orbax's save is a collective (its sync barrier would deadlock
        processes that checkpoint at different tick cadences) — so the
        pod-mode worker's LEADER writes the single `model_cache.pod`
        file with this (restored entries are broadcast so every process
        starts from the identical cache; cli.cmd_worker)."""
        import os
        import pickle
        import tempfile

        with self._lock:
            items = dict(self._d)
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".model_cache.")
        try:
            with os.fdopen(fd, "wb") as f:
                pickle.dump(items, f, protocol=pickle.HIGHEST_PROTOCOL)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def load_local(self, path: str) -> int:
        """Restore a save_local checkpoint (keys round-trip natively).
        Returns the number of entries loaded."""
        import pickle

        with open(path, "rb") as f:
            items = pickle.load(f)
        self.put_many(items.items())
        return len(items)
