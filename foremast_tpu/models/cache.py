"""Bounded per-(service, metric) model cache with optional checkpointing.

The reference brain holds fitted models in a bounded in-memory cache
(`MAX_CACHE_SIZE`, `foremast-brain/README.md:30`) and recomputes on miss —
durable state lives in ES so any node can resume any job (SURVEY.md section 5,
checkpoint/resume). This keeps those semantics and adds what the reference
lacks: an optional orbax checkpoint of trained params (e.g. LSTM-AE
weights) keyed by (service, metric), so warm-starting after restart skips
retraining (SURVEY.md section 5 "new build" note).
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Any, Hashable

import jax


_MISS = object()  # sentinel: "not in the restored overlay"


class ModelCache:
    """Thread-safe LRU of fitted model state.

    Two optional durability hooks (ISSUE 7, both wired by
    `FitJournal`):

      * ``journal`` — a write-through callback invoked AFTER every
        mutation with the changed items (puts as ``(key, value)``,
        deletions as ``(key, None)`` with ``deleted=True``), outside
        the lock so journal I/O never extends lock holds on the tick
        path;
      * ``restore_lazy(items)`` — stages a restored dict BESIDE the
        LRU: entries rehydrate one by one on their first lookup miss
        (the restarted worker's first claim of each document), so a
        restore larger than ``max_size`` never blows the LRU — only
        the working set actually claimed re-enters the cache.
    """

    def __init__(self, max_size: int = 1000):
        self.max_size = max_size
        self._d: OrderedDict[Hashable, Any] = OrderedDict()
        # reentrant: the lazy-rehydration helper takes the lock itself
        # so it is safe from both locked callers (get/get_many) and the
        # lock-free peek fast path
        self._lock = threading.RLock()
        # bumped on every mutation (put/pop/clear/eviction/rehydrate,
        # including batch forms) — lets callers cache derived views of
        # entries and revalidate with ONE integer compare per tick
        # instead of re-reading every key (worker admission caching)
        self.version = 0
        self.journal = None  # optional write-through hook (FitJournal)
        # restored-but-not-yet-claimed overlay; None = nothing staged,
        # so the hot paths pay a single attribute read when durability
        # is off
        self._restored: dict | None = None

    def restore_lazy(self, items) -> int:
        """Stage restored entries for lazy rehydration; returns how
        many were staged. Entries already resident (or later put) win
        over their restored versions."""
        with self._lock:
            staged = {
                k: v for k, v in dict(items).items() if k not in self._d
            }
            self._restored = staged if staged else None
            self.version += 1
            return len(staged)

    def restored_pending(self) -> int:
        with self._lock:
            return len(self._restored) if self._restored else 0

    def _rehydrate(self, key):
        """Move one staged entry into the LRU; returns the value or
        _MISS. Takes the (reentrant) lock itself so locked callers and
        the peek fast path share one implementation. Deliberately NOT
        journaled — restored entries came FROM the journal, and
        re-appending them would double the log on every restart."""
        with self._lock:
            r = self._restored
            if r is None:
                return _MISS
            v = r.pop(key, _MISS)
            if not r:
                self._restored = None
            if v is _MISS:
                return _MISS
            self.version += 1
            self._d[key] = v
            self._d.move_to_end(key)
            while len(self._d) > self.max_size:
                self._d.popitem(last=False)
            return v

    def get(self, key: Hashable):
        with self._lock:
            if key in self._d:
                self._d.move_to_end(key)
                return self._d[key]
            v = self._rehydrate(key)
            return None if v is _MISS else v

    def peek(self, key: Hashable):
        """Lock-free read that does NOT refresh LRU order. Safe under
        the GIL (a plain dict read); callers that rely on entries
        staying resident must pair peeks with a periodic batched
        get_many to keep the LRU honest, or size the cache for the
        working set. The restored overlay is also probed lock-free:
        only a key ACTUALLY staged there pays the one locked
        rehydration, so an overlay of never-again-claimed entries (a
        restore outliving its fleet) cannot degrade the miss path of
        every later lookup."""
        # deliberate lock-free fast path (per-tick hot lookup); the GIL
        # makes each single dict read atomic, and a racing pop from the
        # overlay just falls through to the locked get()
        v = self._d.get(key)  # foremast: ignore[lock-discipline]
        if v is None:
            r = self._restored  # foremast: ignore[lock-discipline]
            if r is not None and key in r:
                return self.get(key)
        return v

    def put(self, key: Hashable, value) -> None:
        with self._lock:
            self.version += 1
            self._d[key] = value
            self._d.move_to_end(key)
            if self._restored is not None:
                # a fresh fit shadows (and must outlive) the restored one
                self._restored.pop(key, None)
            while len(self._d) > self.max_size:
                self._d.popitem(last=False)
        if self.journal is not None:
            self.journal([(key, value)])

    def get_many(self, keys) -> list:
        """Batched get: ONE lock acquisition for a whole tick's key list
        (a fleet tick looks up 40k+ fit keys; a per-key lock round trip
        is measurable on the worker's single host core). None keys (and
        misses) yield None."""
        with self._lock:
            d = self._d
            out = []
            for k in keys:
                if k is not None and k in d:
                    d.move_to_end(k)
                    out.append(d[k])
                elif k is not None and self._restored is not None:
                    v = self._rehydrate(k)
                    out.append(None if v is _MISS else v)
                else:
                    out.append(None)
            return out

    def put_many(self, items) -> None:
        """Batched put of (key, value) pairs under one lock."""
        items = list(items)
        with self._lock:
            self.version += 1
            d = self._d
            for k, v in items:
                d[k] = v
                d.move_to_end(k)
                if self._restored is not None:
                    self._restored.pop(k, None)
            while len(d) > self.max_size:
                d.popitem(last=False)
        if self.journal is not None and items:
            self.journal(items)

    def pop(self, key: Hashable) -> None:
        """Drop an entry if present (e.g. warmup fits that must not
        occupy real capacity)."""
        with self._lock:
            self.version += 1
            self._d.pop(key, None)
            if self._restored is not None:
                self._restored.pop(key, None)
        if self.journal is not None:
            self.journal([(key, None)], deleted=True)

    def pop_where(self, pred) -> int:
        """Drop every entry (resident and restored-overlay) whose key
        satisfies `pred`; returns how many were dropped. One lock
        acquisition, one version bump, journaled as deletions — the
        refinement planner uses this to invalidate joint fits by app
        when it has no exact cache key to pop."""
        with self._lock:
            doomed = [k for k in self._d if pred(k)]
            for k in doomed:
                del self._d[k]
            if self._restored is not None:
                staged = [k for k in self._restored if pred(k)]
                for k in staged:
                    del self._restored[k]
                if not self._restored:
                    self._restored = None
                doomed += staged
            if doomed:
                self.version += 1
        if self.journal is not None and doomed:
            self.journal([(k, None) for k in doomed], deleted=True)
        return len(doomed)

    def clear(self) -> None:
        with self._lock:
            self.version += 1
            self._d.clear()
            self._restored = None
        if self.journal is not None:
            self.journal((), cleared=True)

    def __len__(self) -> int:
        with self._lock:
            return len(self._d)

    def snapshot(self) -> dict:
        """Point-in-time copy of the contents (lock-guarded) — e.g. the
        pod-mode leader broadcasting its restored cache."""
        with self._lock:
            return dict(self._d)

    def persistable_snapshot(self) -> dict:
        """Resident entries PLUS the not-yet-rehydrated restored
        overlay — what a journal compaction must keep (an entry the
        restarted worker has not claimed yet is still warm state)."""
        with self._lock:
            out = dict(self._restored) if self._restored else {}
            out.update(self._d)
            return out

    # -- optional durability (orbax) ------------------------------------

    def save(self, path: str) -> None:
        """Checkpoint the cache contents (pytree values only) via orbax."""
        import orbax.checkpoint as ocp

        with self._lock:
            items = dict(self._d)
        keys = sorted(items, key=str)
        tree = {"keys": [str(k) for k in keys], "values": [items[k] for k in keys]}
        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(path, tree, force=True)

    def load(self, path: str, key_parser=None) -> int:
        """Restore a checkpoint; keys round-trip as strings unless a
        `key_parser` maps them back. Returns number of entries loaded."""
        import orbax.checkpoint as ocp

        ckptr = ocp.PyTreeCheckpointer()
        tree = ckptr.restore(path)
        keys, values = tree["keys"], tree["values"]
        for k, v in zip(keys, values):
            self.put(key_parser(k) if key_parser else k, v)
        return len(keys)

    # -- host-local durability (multi-host workers) ---------------------

    def save_local(self, path: str) -> None:
        """Host-local checkpoint (pickle, atomic rename): unlike save(),
        performs NO cross-process coordination. Under jax.distributed,
        orbax's save is a collective (its sync barrier would deadlock
        processes that checkpoint at different tick cadences) — so the
        pod-mode worker's LEADER writes the single `model_cache.pod`
        file with this (restored entries are broadcast so every process
        starts from the identical cache; cli.cmd_worker)."""
        import os
        import pickle
        import tempfile

        with self._lock:
            items = dict(self._d)
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".model_cache.")
        try:
            with os.fdopen(fd, "wb") as f:
                pickle.dump(items, f, protocol=pickle.HIGHEST_PROTOCOL)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def load_local(self, path: str) -> int:
        """Restore a save_local checkpoint (keys round-trip natively).
        Returns the number of entries loaded."""
        import pickle

        with open(path, "rb") as f:
            items = pickle.load(f)
        self.put_many(items.items())
        return len(items)


# ---------------------------------------------------------------------------
# write-through fit persistence (ISSUE 7)
# ---------------------------------------------------------------------------


# Batch-padding fit keys (ISSUE 13 satellite): the judges pad batch
# leading axes to bucket/data-axis multiples with constant-key empty
# tasks — "__pad__" on the object path (engine/judge._PAD_TASK,
# parallel/batch.ShardedJudge), "__pad__col__" on the columnar path.
# Their empty-history "fits" deliberately live in the in-memory caches
# (one cached pad fit keeps warm ticks fit-free), but they are
# PROCESS-LOCAL DISPATCH ARTIFACTS, not fleet state: journaling them
# would replay phantom fits into every restart, and a refine-book or
# provisional-fit record for one would chase a document that does not
# exist. Every sink that records fits filters through this predicate.
PAD_FIT_MARKERS = frozenset({"__pad__", "__pad__col__"})
# the whole family is prefix-matched: sharded arenas (ISSUE 19) qualify
# pad keys per data-axis block ("__pad__@3", "__pad__col__@3") so each
# shard keeps one stable pad row — still dispatch artifacts, never state
_PAD_FIT_PREFIX = "__pad__"


def is_pad_fit_key(key) -> bool:
    """True when `key` is (or wraps) a judge batch-padding fit key."""
    if isinstance(key, tuple):
        return bool(key) and is_pad_fit_key(key[-1])
    return isinstance(key, str) and key.startswith(_PAD_FIT_PREFIX)


class FitJournal:
    """Crash-durable write-through log for one ModelCache.

    Two files under the snapshot directory per journaled cache:
    ``<base>.snap`` (a compacted pickle dict, atomic-renamed) and
    ``<base>.log`` (crc-framed records, one per mutation batch, flushed
    at write time — page cache survives SIGKILL). Terminal fit states
    are appended the moment the judge `put_many`s them (write-through
    on fit completion), so the history scan that produced them is never
    re-paid after a restart: `restore()` loads snap + healthy log
    prefix and the cache rehydrates entries lazily on first claim
    (`ModelCache.restore_lazy`).

    Damage tolerance mirrors the ring snapshotter: an unreadable snap
    or a torn log tail degrades the affected entries to cold fits and
    a `foremast_snapshot_discards` count (reasons ``fit_unreadable`` /
    ``fit_torn``), never a crash.
    """

    def __init__(self, base_path: str, log_max_bytes: int = 8 * 1024 * 1024):
        self.base_path = base_path
        self.log_max_bytes = int(log_max_bytes)
        self._lock = threading.Lock()
        self._fh = None
        self._log_bytes = 0
        self._cache: "ModelCache | None" = None
        self.counters = {
            "appended_entries": 0,
            "restored_entries": 0,
            "compactions": 0,
            "discards": {"fit_unreadable": 0, "fit_torn": 0},
        }

    @property
    def snap_path(self) -> str:
        return self.base_path + ".snap"

    @property
    def log_path(self) -> str:
        return self.base_path + ".log"

    # -- write side ------------------------------------------------------

    def attach(self, cache: "ModelCache") -> None:
        """Start journaling the cache's mutations. Call after
        `restore()` — the overlay staged there must not re-journal."""
        self._cache = cache
        cache.journal = self.append

    def append(self, items, deleted: bool = False, cleared: bool = False) -> None:
        """The ModelCache write-through hook. Records are
        ("put", key, value) / ("del", key) / ("clear",) tuples."""
        import pickle

        from foremast_tpu.ingest.snapshot import append_record

        if cleared:
            records = [("clear",)]
        elif deleted:
            # pad fit keys never reach disk (see is_pad_fit_key): a
            # journaled pad entry would replay a phantom fit into every
            # restart and bloat the log linearly with pad-bearing ticks
            records = [
                ("del", k) for k, _ in items if not is_pad_fit_key(k)
            ]
        else:
            records = [
                ("put", k, v) for k, v in items if not is_pad_fit_key(k)
            ]
        if not records:
            return
        # the lock serializes the file handle between the judge's
        # write-through and compaction's handle swap — held page-cache
        # appends are its purpose (mirrors _ShardLog.append)
        with self._lock:
            if self._fh is None:
                d = os.path.dirname(os.path.abspath(self.log_path))
                os.makedirs(d, exist_ok=True)
                # foremast: ignore[blocking-under-lock]
                self._fh = open(self.log_path, "ab")
                self._log_bytes = self._fh.tell()
            for rec in records:
                # foremast: ignore[blocking-under-lock]
                self._log_bytes += append_record(
                    self._fh,
                    pickle.dumps(rec, protocol=pickle.HIGHEST_PROTOCOL),
                )
            self.counters["appended_entries"] += len(records)

    # -- restore ---------------------------------------------------------

    def restore(self) -> dict:
        """Load snap + replay the log's healthy prefix; returns the
        merged dict (pass to `ModelCache.restore_lazy`)."""
        import pickle

        from foremast_tpu.ingest.snapshot import read_records

        out: dict = {}
        discards = {"fit_unreadable": 0, "fit_torn": 0}
        if os.path.exists(self.snap_path):
            try:
                with open(self.snap_path, "rb") as fh:
                    out.update(pickle.load(fh))
            except Exception:  # noqa: BLE001 — torn/corrupt snap
                discards["fit_unreadable"] += 1
                out = {}
        for payload, reason in read_records(self.log_path):
            if reason is not None:
                discards["fit_torn"] += 1
                break
            try:
                rec = pickle.loads(payload)
                if rec[0] == "put":
                    out[rec[1]] = rec[2]
                elif rec[0] == "del":
                    out.pop(rec[1], None)
                elif rec[0] == "clear":
                    out.clear()
            except Exception:  # noqa: BLE001 — undecodable record
                discards["fit_torn"] += 1
                break
        # a log written before the pad exclusion may carry pad entries;
        # drop them on the way in so they cannot out-survive the fix
        for k in [k for k in out if is_pad_fit_key(k)]:
            del out[k]
        with self._lock:
            for k, v in discards.items():
                self.counters["discards"][k] += v
            self.counters["restored_entries"] = len(out)
        return out

    # -- compaction ------------------------------------------------------

    def compact(self) -> int:
        """Rewrite the snap from the cache's persistable state and
        truncate the log; returns entries written. Crash between the
        rename and the truncate only re-replays already-compacted
        records (idempotent puts)."""
        import pickle

        from foremast_tpu.ingest.snapshot import atomic_write

        if self._cache is None:
            return 0
        items = {
            k: v
            for k, v in self._cache.persistable_snapshot().items()
            if not is_pad_fit_key(k)
        }
        atomic_write(
            self.snap_path,
            pickle.dumps(items, protocol=pickle.HIGHEST_PROTOCOL),
        )
        with self._lock:
            if self._fh is not None:
                self._fh.close()
            # truncate-and-swap must be atomic against append() writing
            # through the old handle — the held open is the swap itself
            # foremast: ignore[blocking-under-lock]
            self._fh = open(self.log_path, "wb")
            self._log_bytes = 0
            self.counters["compactions"] += 1
        return len(items)

    def maybe_compact(self) -> bool:
        """Tick-cadence trigger: compact when the log outgrew its
        budget (bounds restart replay time)."""
        with self._lock:
            due = self._log_bytes > self.log_max_bytes
        if due:
            self.compact()
        return due

    def close(self) -> None:
        if self._cache is not None and self._cache.journal is self.append:
            self._cache.journal = None
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def stats(self) -> dict:
        """Locked copy of the counters (scrape-thread safe)."""
        with self._lock:
            out = dict(self.counters)
            out["discards"] = dict(self.counters["discards"])
            return out

    def debug_state(self) -> dict:
        with self._lock:
            log_bytes = self._log_bytes
            counters = dict(self.counters)
            counters["discards"] = dict(self.counters["discards"])
        return {
            "appended_entries": counters["appended_entries"],
            "restored_entries": counters["restored_entries"],
            "restored_pending": (
                self._cache.restored_pending() if self._cache else 0
            ),
            "compactions": counters["compactions"],
            "log_bytes": log_bytes,
            "discards": counters["discards"],
        }
