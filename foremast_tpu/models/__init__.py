"""Learned / structural detectors beyond the core forecasters.

Registers the seasonal (Prophet-substitute) model into the engine's
AI_MODEL registry; the LSTM-AE and bivariate detectors have train/fit
interfaces of their own and are dispatched by metric count in
`engine/multivariate.MultivariateJudge` (the worker's default judge).
"""

from functools import partial

from foremast_tpu.engine.scoring import register_model
from foremast_tpu.models.bivariate import (
    BivariateFit,
    detect_bivariate,
    fit_bivariate,
    mahalanobis2,
)
from foremast_tpu.models.cache import ModelCache
from foremast_tpu.models.lstm_ae import (
    AEParams,
    LSTMAEConfig,
    LSTMParams,
    ae_cutoff,
    fit_many,
    init,
    init_many,
    recon_error,
    reconstruct,
    score_many,
    score_many_cutoff,
    train_step,
    train_step_many,
)
from foremast_tpu.models.lstm_ae import shardings as _lstm_ae_shardings
from foremast_tpu.models.seasonal import fit_seasonal


def lstm_ae_shardings(mesh, params, opt_state):
    """Shardings for stacked LSTM-AE params (hidden inferred from w_h)."""
    hidden = params.enc.w_h.shape[1]
    return _lstm_ae_shardings(mesh, params, opt_state, hidden)

register_model("seasonal", fit_seasonal)
register_model("prophet", fit_seasonal)  # documented substitution, see seasonal.py
# hourly seasonality variant (60 steps at the 60 s PromQL step)
register_model("seasonal_hourly", partial(fit_seasonal, period=60, order=2))

__all__ = [
    "BivariateFit",
    "detect_bivariate",
    "fit_bivariate",
    "mahalanobis2",
    "ModelCache",
    "AEParams",
    "LSTMAEConfig",
    "LSTMParams",
    "fit_many",
    "init",
    "init_many",
    "recon_error",
    "reconstruct",
    "score_many",
    "score_many_cutoff",
    "ae_cutoff",
    "train_step",
    "train_step_many",
    "fit_seasonal",
    "lstm_ae_shardings",
]
