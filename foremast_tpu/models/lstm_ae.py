"""LSTM-autoencoder multivariate anomaly detector — the flagship learned
model (reference model zoo: "3+ metrics: Deep Learning (LSTM)",
`docs/guides/design.md:84`; BASELINE.md config 4: "LSTM-autoencoder
multivariate detector (train + score)").

TPU-first design:
  * pure-JAX parameters (a pytree of arrays) instead of a framework module,
    so the *service* axis can be a leading array dimension: `init_many`
    creates `[S, ...]`-stacked params and `train_step_many` vmaps one
    compiled train step over all services at once — "train many small
    models cheaply" (SURVEY.md section 7 hard part (e));
  * time runs inside `lax.scan` (one fused loop, static shapes); masked
    steps carry state through unchanged so ragged windows batch cleanly;
  * all matmuls are [B, F]x[F, 4H] / [B, H]x[H, 4H] — MXU-shaped, and the
    4H gate axis is the natural tensor-parallel shard axis (see
    `parallel/mesh.py` and `__graft_entry__.dryrun_multichip`).

Scoring: per-step reconstruction error; a window is anomalous where the
error exceeds `threshold x` the model's training-time error scale — the
same threshold/bound semantics every other detector uses.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax


class LSTMParams(NamedTuple):
    """One LSTM cell: gates stacked [i, f, g, o] along the last axis."""

    w_x: jax.Array  # [F_in, 4H]
    w_h: jax.Array  # [H, 4H]
    b: jax.Array  # [4H]


class AEParams(NamedTuple):
    enc: LSTMParams  # features -> hidden
    dec: LSTMParams  # zeros-input decoder conditioned on encoder state
    w_out: jax.Array  # [H, F]
    b_out: jax.Array  # [F]


@dataclasses.dataclass(frozen=True)
class LSTMAEConfig:
    features: int = 4  # metrics per service (latency/err4xx/err5xx/tps)
    hidden: int = 32
    learning_rate: float = 1e-2


def init(key: jax.Array, cfg: LSTMAEConfig) -> AEParams:
    f, h = cfg.features, cfg.hidden
    k1, k2, k3, k4, k5 = jax.random.split(key, 5)
    glorot = jax.nn.initializers.glorot_uniform()

    def cell(kx, kh, fan_in):
        return LSTMParams(
            w_x=glorot(kx, (fan_in, 4 * h), jnp.float32),
            w_h=glorot(kh, (h, 4 * h), jnp.float32),
            # forget-gate bias 1.0 (standard stable-training init)
            b=jnp.zeros((4 * h,)).at[h : 2 * h].set(1.0),
        )

    return AEParams(
        enc=cell(k1, k2, f),
        dec=cell(k3, k4, f),
        w_out=glorot(k5, (h, f), jnp.float32),
        b_out=jnp.zeros((f,)),
    )


def init_many(key: jax.Array, n: int, cfg: LSTMAEConfig) -> AEParams:
    """[S, ...]-stacked params: one small model per service."""
    return jax.vmap(lambda k: init(k, cfg))(jax.random.split(key, n))


def _cell_step(p: LSTMParams, h, c, x, m):
    """One masked LSTM step. x: [B, F_in], m: [B] validity."""
    gates = x @ p.w_x + h @ p.w_h + p.b  # [B, 4H]
    hid = p.w_h.shape[0]
    i, f, g, o = jnp.split(gates, (hid, 2 * hid, 3 * hid), axis=-1)
    c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
    h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
    keep = m[:, None].astype(h.dtype)
    return keep * h_new + (1 - keep) * h, keep * c_new + (1 - keep) * c


def reconstruct(params: AEParams, x: jax.Array, mask: jax.Array) -> jax.Array:
    """Encode-decode a window. x: [B, T, F], mask: [B, T] -> recon [B, T, F]."""
    b, t, f = x.shape
    h0 = jnp.zeros((b, params.enc.w_h.shape[0]), x.dtype)

    def enc_step(carry, xs):
        h, c = carry
        xt, mt = xs
        h, c = _cell_step(params.enc, h, c, xt, mt)
        return (h, c), None

    (h_enc, c_enc), _ = jax.lax.scan(
        enc_step, (h0, h0), (jnp.swapaxes(x, 0, 1), mask.T)
    )

    zeros_in = jnp.zeros((b, f), x.dtype)
    ones = jnp.ones((b,), bool)

    def dec_step(carry, _):
        h, c = carry
        h, c = _cell_step(params.dec, h, c, zeros_in, ones)
        y = h @ params.w_out + params.b_out
        return (h, c), y

    _, ys = jax.lax.scan(dec_step, (h_enc, c_enc), None, length=t)
    return jnp.swapaxes(ys, 0, 1)  # [B, T, F]


def recon_error(params: AEParams, x: jax.Array, mask: jax.Array) -> jax.Array:
    """Per-step reconstruction error (mean squared over features), [B, T]."""
    r = reconstruct(params, x, mask)
    e = jnp.mean((r - x) ** 2, axis=-1)
    return jnp.where(mask, e, 0.0)


def loss_fn(params: AEParams, x: jax.Array, mask: jax.Array) -> jax.Array:
    e = recon_error(params, x, mask)
    n = jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.sum(e) / n


def make_optimizer(cfg: LSTMAEConfig):
    return optax.adam(cfg.learning_rate)


@partial(jax.jit, static_argnames=("cfg",))
def train_step(params, opt_state, x, mask, cfg: LSTMAEConfig):
    """One SGD step for one service's model. x: [B, T, F]."""
    loss, grads = jax.value_and_grad(loss_fn)(params, x, mask)
    updates, opt_state = make_optimizer(cfg).update(grads, opt_state, params)
    return optax.apply_updates(params, updates), opt_state, loss


@partial(jax.jit, static_argnames=("cfg",))
def train_step_many(params, opt_state, x, mask, cfg: LSTMAEConfig):
    """vmapped train step over the service axis.

    params/opt_state: [S, ...]-stacked pytrees; x: [S, B, T, F],
    mask: [S, B, T]. One compiled program trains every service's model —
    this is the program `__graft_entry__.dryrun_multichip` shards over the
    device mesh (service axis = data-parallel, gate axis = tensor-parallel).
    """

    def one(p, o, xs, ms):
        loss, grads = jax.value_and_grad(loss_fn)(p, xs, ms)
        updates, o = make_optimizer(cfg).update(grads, o, p)
        return optax.apply_updates(p, updates), o, loss

    return jax.vmap(one)(params, opt_state, x, mask)


def fit_many(
    key: jax.Array,
    x: jax.Array,
    mask: jax.Array,
    cfg: LSTMAEConfig | None = None,
    steps: int = 100,
):
    """Train S per-service models on [S, B, T, F] windows.

    Returns (params [S,...], err_mean [S], err_std [S], losses [steps, S]):
    the trained model's in-sample reconstruction-error moments.
    score_many's bound is err_mean + threshold * err_std — the same
    mean + threshold*sigma semantics every other detector uses.

    Short-history admission (ISSUE 10) feeds this the same way a full
    history does, but the caller MUST hold the PR-7 min-history gate
    (`multivariate._judge_lstm`: >= 2 training windows of the job's own
    bucket) — a single-window "distribution" degenerates its cutoff
    calibration and flags clean noise. Jobs under the gate stay
    UNKNOWN until refinement grows their coverage past it.
    """
    if cfg is None:
        cfg = LSTMAEConfig(features=x.shape[-1])
    s = x.shape[0]
    params = init_many(key, s, cfg)
    opt_state = jax.vmap(make_optimizer(cfg).init)(params)

    def body(carry, _):
        p, o = carry
        p, o, loss = train_step_many(p, o, x, mask, cfg)
        return (p, o), loss

    (params, _), losses = jax.lax.scan(body, (params, opt_state), None, length=steps)
    err = jax.vmap(lambda p, xs, ms: recon_error(p, xs, ms))(params, x, mask)
    n = jnp.maximum(jnp.sum(mask, axis=(1, 2)), 1.0)
    mean_e = jnp.sum(err, axis=(1, 2)) / n
    var_e = jnp.sum(jnp.where(mask, (err - mean_e[:, None, None]) ** 2, 0.0), axis=(1, 2)) / n
    return params, mean_e, jnp.sqrt(var_e), losses


def shardings(mesh, params, opt_state, hidden: int):
    """NamedShardings for stacked params/opt_state on a (data, model) mesh.

    Rule: the leading service axis shards over `data` (one slice of the
    fleet's models per chip group); any 4H gate axis shards over `model`
    (tensor parallelism inside each LSTM cell — the gate matmul
    [B,F]x[F,4H] column-partitions cleanly, XLA inserts the reduce where
    the hidden state feeds back). Everything else is replicated.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    gate = 4 * hidden

    def spec(leaf):
        # the gate axis is always the LAST axis of w_x/w_h/b; only the last
        # dim is considered so a coincidental inner dim == 4H (e.g.
        # features == 4*hidden) can't produce a duplicated mesh axis
        dims = ["data"] + [None] * (leaf.ndim - 2)
        if leaf.ndim >= 2:
            dims.append("model" if leaf.shape[-1] == gate else None)
        return NamedSharding(mesh, P(*dims))

    return jax.tree.map(spec, params), jax.tree.map(spec, opt_state)


def ae_cutoff(err_mean, err_std, threshold) -> "np.ndarray":
    """Quantile-matched reconstruction-error cutoff, [S] (host-side).

    Reconstruction error is a squared quantity — right-skewed, never
    Gaussian — so mean + threshold*sigma underestimates its tail and the
    naive bound pays false positives at exactly the configured-sigma
    rates the other detectors hold (VERDICT r2 item 4). Instead the
    training-error moments fit a gamma (k = mean^2/var, theta =
    var/mean; chi^2-family, the natural model for squared errors), and
    the cutoff is the gamma quantile with the SAME tail mass as the
    two-sided normal tail P(|z| > threshold) — the calibration
    `residual_mvn.chi2_quantile` already applies to the MVN. Never
    returns less than the classic mean + threshold*sigma bound, so
    recalibration can only tighten precision. `threshold` may be scalar
    or [S] (per-job canary lowering)."""
    import numpy as np
    from scipy import stats

    mean = np.maximum(np.asarray(err_mean, np.float64), 1e-300)
    std = np.asarray(err_std, np.float64)
    var = np.maximum(std * std, 0.0)
    p_tail = np.clip(2.0 * stats.norm.sf(np.asarray(threshold, np.float64)), 1e-300, 1.0)
    with np.errstate(divide="ignore", invalid="ignore"):
        k = np.where(var > 0, mean * mean / np.maximum(var, 1e-300), 1.0)
        theta = np.where(var > 0, var / mean, 0.0)
        gq = stats.gamma.ppf(1.0 - p_tail, k, scale=theta)
    gq = np.where((var > 0) & np.isfinite(gq), gq, mean)
    return np.maximum(gq, np.asarray(err_mean) + np.asarray(threshold) * std).astype(
        np.float32
    )


@jax.jit
def score_many(params, x, mask, err_mean, err_std, threshold):
    """Anomaly flags for [S, B, T, F] windows against trained models.

    Classic mean + threshold*sigma bound on the reconstruction error —
    kept for benchmarks and as the simple API; the shipped judge uses
    `score_many_cutoff` with the quantile-matched `ae_cutoff` instead
    (squared errors are right-skewed, so this bound's tail is heavier
    than the configured sigmas imply). Returns (flags [S, B, T],
    errors [S, B, T])."""
    err = jax.vmap(recon_error)(params, x, mask)
    thr = (err_mean + threshold * err_std)[:, None, None]  # [S, 1, 1]
    flags = mask & (err > thr)
    return flags, err


@jax.jit
def score_many_cutoff(params, x, mask, cutoff):
    """Anomaly flags for [S, B, T, F] windows against per-model error
    cutoffs [S] (see `ae_cutoff`). Returns (flags [S, B, T], errors)."""
    err = jax.vmap(recon_error)(params, x, mask)
    flags = mask & (err > cutoff[:, None, None])
    return flags, err


@jax.jit
def score_rows_cutoff(params, rows, x, mask, cutoff):
    """`score_many_cutoff` against ARENA-resident models (engine.arena
    .TreeArena): `params` leaves are [capacity, ...]-stacked and `rows`
    [S] indexes the batch's models, gathered ON DEVICE — the LSTM-AE
    counterpart of `scoring.score_from_arena`, so a warm joint re-check
    tick ships only the current windows and a row-index vector, never
    the ~60 KB/model parameter stack. Returns (flags [S, B, T], errors)."""
    gathered = jax.tree.map(lambda leaf: jnp.take(leaf, rows, axis=0), params)
    return score_many_cutoff(gathered, x, mask, cutoff)


# Mesh-placement contract for the from-rows entry points (ISSUE 13,
# arena layout updated by ISSUE 19): every computation above is per-row
# independent along the leading [S] axis (vmapped scoring, axis-0
# gathers), so callers may pass `x`/`mask` with their leading axis
# sharded over a mesh's data axis and XLA partitions the program with
# zero collectives. The arena `params` stack arrives in one of two
# layouts:
#   - sharded (default): each leaf's [capacity] axis block-shards over
#     the SAME data axis and `rows` carries LOCAL (per-shard) indices —
#     the judge's block placement rule puts every batch position's row
#     on the device holding that position, so the gather runs inside
#     shard_map against each device's own block (see
#     multivariate.lstm_joint_score_from_rows_sharded).
#   - replicated (FOREMAST_ARENA_SHARDED=0, pod mode): `params` fully
#     replicated, `rows` global — the gather reads each device's local
#     replica (the ISSUE 13 layout).
# Either way: zero cross-chip transfer per warm tick. S must be a
# multiple of the data axis (the judge's batch rounding guarantees it).
# Nothing here may ever reduce ACROSS the [S] axis, or the contract
# breaks.
