"""`ChaosCollector` — the chaos plane's Prometheus exposition.

A custom collector over the live `Degradation` bundle (the same
pattern as `ingest.IngestCollector`): the hot paths only bump plain
lock-guarded counters; families are materialized at scrape time. Every
family here is registered through the PR-8 metrics-contract gate
(`observe/metrics_lint.py` ALLOWED_LABELS + FAMILY_DOCS, generated
table in docs/observability.md).
"""

from __future__ import annotations

from foremast_tpu.chaos.breaker import STATE_CODES
from foremast_tpu.chaos.degrade import Degradation


class ChaosCollector:
    """prometheus_client custom collector over a `Degradation` bundle."""

    def __init__(self, degradation: Degradation):
        self._d = degradation

    def collect(self):
        from prometheus_client.core import (
            CounterMetricFamily,
            GaugeMetricFamily,
        )

        d = self._d
        injections = CounterMetricFamily(
            "foremast_chaos_injections",
            "faults injected by the active FOREMAST_CHAOS_PLAN, by "
            "dependency edge and fault kind (latency sleeps count too)",
            labels=["edge", "kind"],
        )
        if d.chaos_plan is not None:
            for (edge, kind), n in sorted(
                d.chaos_plan.injections_snapshot().items()
            ):
                injections.add_metric([edge, kind], n)
        yield injections

        state = GaugeMetricFamily(
            "foremast_breaker_state",
            "circuit-breaker state per dependency edge "
            "(0=closed, 1=half-open, 2=open)",
            labels=["edge"],
        )
        transitions = CounterMetricFamily(
            "foremast_breaker_transitions",
            "circuit-breaker state transitions, by edge and target state",
            labels=["edge", "state"],
        )
        shorts = CounterMetricFamily(
            "foremast_breaker_short_circuits",
            "calls rejected without touching the dependency because "
            "its breaker was open",
            labels=["edge"],
        )
        for edge, br in sorted(d.breakers.all().items()):
            snap = br.debug_state()
            state.add_metric([edge], STATE_CODES[snap["state"]])
            for to, n in sorted(snap["transitions"].items()):
                transitions.add_metric([edge, to], n)
            shorts.add_metric([edge], snap["short_circuits"])
        yield state
        yield transitions
        yield shorts

        docs = CounterMetricFamily(
            "foremast_degraded_docs",
            "documents handled by degradation machinery instead of the "
            "healthy path (released un-judged, buffered/replayed/"
            "dropped write-backs), by reason",
            labels=["reason"],
        )
        for reason, n in sorted(d.stats.docs_snapshot().items()):
            docs.add_metric([reason], n)
        yield docs

        events = CounterMetricFamily(
            "foremast_degraded_events",
            "degradation events that are not per-document (claim "
            "errors survived, receiver overload sheds, replay flushes), "
            "by dependency edge and action",
            labels=["edge", "action"],
        )
        for (edge, action), n in sorted(d.stats.events_snapshot().items()):
            events.add_metric([edge, action], n)
        yield events
