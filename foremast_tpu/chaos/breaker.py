"""A small shared circuit breaker, per dependency edge.

Closed → (``failure_threshold`` consecutive transient failures) →
open → (``open_seconds`` cooldown) → half-open → one probe call:
success re-closes, failure re-opens. The point is FAIL-FAST
degradation: once an endpoint is known-down, every further call costs
one exception instead of a full client timeout — a dead Prometheus
stops stalling the tick pipeline behind per-doc timeouts, and the
write-behind buffer takes store writes the moment the store breaker
opens instead of after another round of retries.

Classification reuses `PrometheusSource`'s transient set (connection /
timeout errors, HTTP 429/5xx): only failures that *could* heal trip
the breaker — a 400 means the endpoint is alive and the request is
wrong, which no amount of breaking fixes.

`BreakerOpen` subclasses ConnectionError so every existing
transient-failure net (fetch-failure isolation, resilient store
writes) treats a short-circuited call exactly like a refused
connection — no new exception plumbing in product code.

Thread-safety: one Lock per breaker; the guarded section is a handful
of compares (the dependency call itself NEVER runs under the lock).
"""

from __future__ import annotations

import threading
import time

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

# gauge encoding for foremast_breaker_state (docs/observability.md)
STATE_CODES = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}

DEFAULT_FAILURE_THRESHOLD = 5
DEFAULT_OPEN_SECONDS = 10.0


class BreakerOpen(ConnectionError):
    """Short-circuited call: the edge's breaker is open."""

    def __init__(self, edge: str, retry_in: float):
        super().__init__(
            f"circuit breaker open for {edge!r} "
            f"(retry in {max(retry_in, 0.0):.1f}s)"
        )
        self.edge = edge
        self.retry_in = retry_in


class CircuitBreaker:
    """One edge's breaker. ``allow()`` before the call, then exactly one
    of ``record_success()`` / ``record_failure()`` after it."""

    def __init__(
        self,
        edge: str,
        failure_threshold: int = DEFAULT_FAILURE_THRESHOLD,
        open_seconds: float = DEFAULT_OPEN_SECONDS,
        clock=time.monotonic,
    ):
        self.edge = edge
        self.failure_threshold = max(1, int(failure_threshold))
        self.open_seconds = float(open_seconds)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probing = False  # half-open: exactly one probe in flight
        self._probe_started = 0.0
        self.transitions: dict[str, int] = {}
        self.short_circuits = 0

    # -- state machine (all under _lock) --------------------------------

    def _transition(self, to: str) -> None:
        self._state = to
        self.transitions[to] = self.transitions.get(to, 0) + 1

    def allow(self) -> None:
        """Raise `BreakerOpen` when the call must not go out; otherwise
        reserve the call (in half-open, only one probe passes)."""
        with self._lock:
            if self._state == OPEN:
                remaining = (
                    self._opened_at + self.open_seconds - self._clock()
                )
                if remaining > 0.0:
                    self.short_circuits += 1
                    raise BreakerOpen(self.edge, remaining)
                self._transition(HALF_OPEN)
                self._probing = False
            if self._state == HALF_OPEN:
                # the probe reservation SELF-HEALS: a probe whose caller
                # died without record_success/record_failure (an
                # unclassified exception between allow() and the record —
                # say a truncated response parsing error) must not
                # short-circuit this edge forever. Past one cooldown the
                # reservation is considered abandoned and taken over.
                if self._probing and (
                    self._clock() - self._probe_started < self.open_seconds
                ):
                    self.short_circuits += 1
                    raise BreakerOpen(self.edge, 0.0)
                self._probing = True
                self._probe_started = self._clock()

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probing = False
            if self._state != CLOSED:
                self._transition(CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._probing = False
            if self._state == HALF_OPEN:
                # the probe failed: straight back to open, fresh cooldown
                self._opened_at = self._clock()
                self._transition(OPEN)
                return
            self._failures += 1
            if self._state == CLOSED and (
                self._failures >= self.failure_threshold
            ):
                self._opened_at = self._clock()
                self._transition(OPEN)

    @property
    def state(self) -> str:
        with self._lock:
            if self._state == OPEN and (
                self._clock() - self._opened_at >= self.open_seconds
            ):
                return HALF_OPEN  # would probe on the next allow()
            return self._state

    def debug_state(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "consecutive_failures": self._failures,
                "short_circuits": self.short_circuits,
                "transitions": dict(self.transitions),
            }


class BreakerRegistry:
    """Edge-name → breaker, shared across clients so varz/metrics see
    every breaker in the process from one place."""

    def __init__(
        self,
        failure_threshold: int = DEFAULT_FAILURE_THRESHOLD,
        open_seconds: float = DEFAULT_OPEN_SECONDS,
        clock=time.monotonic,
    ):
        self.failure_threshold = failure_threshold
        self.open_seconds = open_seconds
        self._clock = clock
        self._lock = threading.Lock()
        self._breakers: dict[str, CircuitBreaker] = {}

    @staticmethod
    def from_env(env=None) -> "BreakerRegistry":
        import os

        e = os.environ if env is None else env
        return BreakerRegistry(
            failure_threshold=int(
                e.get("FOREMAST_BREAKER_FAILURES", "")
                or DEFAULT_FAILURE_THRESHOLD
            ),
            open_seconds=float(
                e.get("FOREMAST_BREAKER_OPEN_SECONDS", "")
                or DEFAULT_OPEN_SECONDS
            ),
        )

    def get(self, edge: str) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(edge)
            if br is None:
                br = CircuitBreaker(
                    edge,
                    failure_threshold=self.failure_threshold,
                    open_seconds=self.open_seconds,
                    clock=self._clock,
                )
                self._breakers[edge] = br
        return br

    def all(self) -> dict[str, CircuitBreaker]:
        with self._lock:
            return dict(self._breakers)

    def debug_state(self) -> dict:
        return {e: b.debug_state() for e, b in sorted(self.all().items())}
