"""Graceful-degradation machinery: transient classification, the
write-behind buffer, tick budgets, and the shared counters.

The worker holds ONE `Degradation` object bundling all of it, so the
call sites stay one-liners and `/debug/state` has a single
``degradation`` section to render.

Semantics (docs/operations.md "Failure modes & degradation"):

* **Transient classification** is the one the repo already standardized
  in `PrometheusSource`: connection/timeout exceptions (requests' and
  builtins'), HTTP 429/5xx (via a ``response.status_code`` or
  ``.code`` attribute), and `BreakerOpen`/`InjectedFault` by
  inheritance. Everything else is a permanent error and PROPAGATES —
  degrading on a programming error would hide bugs behind resilience.
* **Write-behind**: a store write that fails transiently parks its
  docs in a bounded buffer; every tick retries the backlog FIRST (the
  store may have healed). Entries older than ``max_age_seconds``
  (wired to MAX_STUCK_IN_SECONDS) are DROPPED, not replayed: past the
  stuck window another worker's claim-CAS takeover has re-judged those
  docs, and a late replay would double-write the verdict — the drop
  plus the takeover is the exactly-once net. The worker therefore
  stamps entries at the CLAIM instant, not the write-failure instant
  (`BrainWorker._tick_claim_mono`): takeover eligibility runs off the
  claim's ``modified_at``, so age must be measured from the same
  moment or a slow fetch/judge would stretch the replay window past
  the takeover boundary. Past the entry cap the OLDEST entries drop
  (counted), because an unbounded buffer against a store that never
  heals is a slow OOM.
* **Tick budget** (``FOREMAST_TICK_BUDGET_SECONDS``, 0 = off): docs
  whose fetch/judge did not start before the deadline are RELEASED
  un-judged — status back to ``preprocess_completed``, claimable next
  tick — instead of wedging the tick behind a slow dependency. Counted
  per reason; never silent. A SLICED sweep (ISSUE 15) accounts the
  same budget at slice granularity: the deadline is set once at sweep
  start, every slice's prepare stage checks it before fetching, and
  on expiry the still-pooled remainder releases as ONE bulk write
  (``deadline_released``) instead of judging over budget — so the
  budget bounds sweep wall clock with at most one slice of overshoot.
"""

from __future__ import annotations

import os
import threading
import time

from foremast_tpu.chaos.breaker import BreakerRegistry

DEFAULT_WRITE_BEHIND_DOCS = 65_536
DEFAULT_WRITE_BEHIND_AGE_SECONDS = 90.0

# write-behind / release reasons (foremast_degraded_docs{reason})
REASON_DEADLINE = "deadline_released"
REASON_FETCH = "fetch_released"
# a fast-tick admitted doc the columnar program could no longer score
# (joint window-bucket drift, and any future admission invariant that
# breaks mid-tick): re-routed to the slow path for a refit — counted
# here so demotions never ride the slow leftovers silently (ISSUE 14)
REASON_DEMOTED = "fast_demoted"
# a sliced sweep aborted mid-flight (judge/write stage death): slices
# that were claimed + prepared but never judged give their docs back
# un-judged instead of parking them behind the stuck-takeover window
# (ISSUE 15 — the bounded-slice philosophy applied to the abort path)
REASON_ABORT = "sweep_aborted"
REASON_BUFFERED = "write_buffered"
REASON_REPLAYED = "write_replayed"
REASON_DROPPED_CAP = "write_dropped_cap"
REASON_DROPPED_AGE = "write_dropped_age"


def is_transient_error(e: BaseException) -> bool:
    """The shared could-this-heal classification (see module doc)."""
    from foremast_tpu.metrics.source import (
        RETRY_STATUSES,
        _transient_exceptions,
    )

    if isinstance(e, _transient_exceptions()):
        return True
    # urllib wraps socket-level transport failures (connection refused/
    # reset, DNS, timeouts) in URLError with the original OSError as
    # `.reason` — classify by that cause, or a refused peer would read
    # as a programming error and crash the caller's loop. HTTPError (a
    # URLError subclass) carries a string reason and falls through to
    # the status check below.
    import urllib.error

    if isinstance(e, urllib.error.URLError) and isinstance(
        getattr(e, "reason", None), OSError
    ):
        return True
    # requests.HTTPError carries .response; urllib's HTTPError has .code
    status = getattr(getattr(e, "response", None), "status_code", None)
    if status is None:
        status = getattr(e, "code", None)
    return isinstance(status, int) and status in RETRY_STATUSES


class DegradeStats:
    """Lock-guarded degradation counters (mutated from the tick thread,
    the pipeline writer thread, and receiver handler threads)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._docs: dict[str, int] = {}
        self._events: dict[tuple[str, str], int] = {}

    def count_docs(self, reason: str, n: int = 1) -> None:
        if n <= 0:
            return
        with self._lock:
            self._docs[reason] = self._docs.get(reason, 0) + n

    def count_event(self, edge: str, action: str, n: int = 1) -> None:
        if n <= 0:
            return
        with self._lock:
            key = (edge, action)
            self._events[key] = self._events.get(key, 0) + n

    def docs_snapshot(self) -> dict[str, int]:
        with self._lock:
            return dict(self._docs)

    def events_snapshot(self) -> dict[tuple[str, str], int]:
        with self._lock:
            return dict(self._events)

    def debug_state(self) -> dict:
        return {
            "docs": dict(sorted(self.docs_snapshot().items())),
            "events": {
                f"{e}/{a}": n
                for (e, a), n in sorted(self.events_snapshot().items())
            },
        }


class WriteBehindBuffer:
    """Bounded FIFO of (buffered_at, doc) awaiting store replay.

    The buffer holds Document OBJECTS (the worker finalized their
    statuses already); replay re-sends them through the store's normal
    ``update_many``. One lock guards the deque; the store round trip
    never runs under it (``drain``/``requeue`` hand batches out)."""

    def __init__(
        self,
        max_docs: int = DEFAULT_WRITE_BEHIND_DOCS,
        max_age_seconds: float = DEFAULT_WRITE_BEHIND_AGE_SECONDS,
        stats: DegradeStats | None = None,
        clock=time.monotonic,
    ):
        self.max_docs = max(1, int(max_docs))
        self.max_age_seconds = float(max_age_seconds)
        self.stats = stats or DegradeStats()
        self._clock = clock
        self._lock = threading.Lock()
        self._entries: list[tuple[float, object]] = []

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def add(self, docs, now: float | None = None) -> None:
        now = self._clock() if now is None else now
        docs = list(docs)
        dropped = 0
        with self._lock:
            # newest entries win under the cap: the claim lease on the
            # oldest is closest to expiring into a CAS takeover anyway
            self._entries.extend((now, d) for d in docs)
            overflow = len(self._entries) - self.max_docs
            if overflow > 0:
                del self._entries[:overflow]
                dropped = overflow
        self.stats.count_docs(REASON_BUFFERED, len(docs))
        self.stats.count_docs(REASON_DROPPED_CAP, dropped)

    def drain(
        self, now: float | None = None, margin: float = 0.0
    ) -> list[tuple[float, object]]:
        """Take every (buffered_at, doc) entry still inside the age
        window (expired entries drop + count: claim-CAS takeover owns
        them now). The caller replays the docs and `requeue`s the SAME
        entries on another failure — original stamps preserved, so a
        store that stays down still ages entries out instead of
        replaying them forever.

        `margin` shrinks the window: the age check runs at DRAIN time
        but the replay write lands one store round trip later — without
        headroom for that RPC, an entry kept at age max_age-ε could
        land after the takeover boundary and double-write a doc a peer
        re-judged. Callers pass their store's timeout (bounded)."""
        now = self._clock() if now is None else now
        cutoff = now + margin - self.max_age_seconds
        with self._lock:
            entries, self._entries = self._entries, []
        live = [(at, d) for at, d in entries if at >= cutoff]
        self.stats.count_docs(REASON_DROPPED_AGE, len(entries) - len(live))
        return live

    def requeue(self, entries: list[tuple[float, object]]) -> None:
        """Put a failed replay back at the FRONT with its original
        buffered_at stamps (see `drain`)."""
        if not entries:
            return
        overflow = 0
        with self._lock:
            self._entries[:0] = list(entries)
            overflow = len(self._entries) - self.max_docs
            if overflow > 0:
                del self._entries[:overflow]
        self.stats.count_docs(REASON_DROPPED_CAP, max(overflow, 0))

    def debug_state(self) -> dict:
        with self._lock:
            n = len(self._entries)
            oldest = self._entries[0][0] if self._entries else None
        return {
            "buffered_docs": n,
            "max_docs": self.max_docs,
            "max_age_seconds": self.max_age_seconds,
            "oldest_age_seconds": (
                round(self._clock() - oldest, 3) if oldest is not None else None
            ),
        }


class Degradation:
    """Everything the worker needs to degrade instead of die, bundled:
    the write-behind buffer, the tick budget, the breaker registry, and
    the stats they all report through."""

    def __init__(
        self,
        stats: DegradeStats | None = None,
        breakers: BreakerRegistry | None = None,
        write_behind: WriteBehindBuffer | None = None,
        tick_budget_seconds: float = 0.0,
        chaos_plan=None,
    ):
        self.stats = stats or DegradeStats()
        self.breakers = breakers or BreakerRegistry()
        self.write_behind = write_behind or WriteBehindBuffer(
            stats=self.stats
        )
        self.tick_budget_seconds = float(tick_budget_seconds)
        self.chaos_plan = chaos_plan

    @staticmethod
    def from_env(
        max_stuck_seconds: float = DEFAULT_WRITE_BEHIND_AGE_SECONDS,
        chaos_plan=None,
        env=None,
    ) -> "Degradation":
        e = os.environ if env is None else env
        stats = DegradeStats()
        return Degradation(
            stats=stats,
            breakers=BreakerRegistry.from_env(e),
            write_behind=WriteBehindBuffer(
                max_docs=int(
                    e.get("FOREMAST_WRITE_BEHIND_DOCS", "")
                    or DEFAULT_WRITE_BEHIND_DOCS
                ),
                # the exactly-once net: never replay past the stuck
                # window another worker may have taken the claim over
                max_age_seconds=max_stuck_seconds,
                stats=stats,
            ),
            tick_budget_seconds=float(
                e.get("FOREMAST_TICK_BUDGET_SECONDS", "") or 0.0
            ),
            chaos_plan=chaos_plan,
        )

    def deadline(self, t0: float) -> float | None:
        """The tick's wall deadline (perf_counter domain), or None."""
        if self.tick_budget_seconds <= 0.0:
            return None
        return t0 + self.tick_budget_seconds

    def debug_state(self) -> dict:
        return {
            "tick_budget_seconds": self.tick_budget_seconds or None,
            "write_behind": self.write_behind.debug_state(),
            "breakers": self.breakers.debug_state(),
            "chaos": (
                self.chaos_plan.debug_state()
                if self.chaos_plan is not None
                else None
            ),
            **self.stats.debug_state(),
        }
