"""Chaos plane: deterministic fault injection + graceful degradation.

The system's whole value proposition is staying sane while the cluster
around it misbehaves (the reference is a *health manager*), yet nothing
in the repo could *inject* dependency failures on demand. This package
is both halves of ISSUE 9:

* **Injection** (`plan.py`): a seeded `FaultPlan` — latency, error
  rate, blackhole, slow-drip, clock skew — threaded through ONE
  interception seam in each dependency client (`PrometheusSource`,
  `ElasticsearchStore`, `HttpKube`, the ingest receiver,
  `RoutingPusher`, the bench `HttpFleetStore` server). Activated by
  `FOREMAST_CHAOS_PLAN` (off in production: every seam is a
  `None`-check pass-through) or by direct injection in tests.
* **Degradation** (`breaker.py`, `degrade.py`): a small shared
  circuit breaker (closed/open/half-open, per dependency edge) reusing
  `PrometheusSource`'s transient classification, a bounded write-behind
  buffer so a store outage degrades write-back instead of failing the
  tick, per-tick deadlines with partial-tick release semantics, and the
  shared `DegradeStats` counters every piece reports through.
* **Proof**: `benchmarks/chaos_bench.py` (`make bench-chaos`) soaks a
  3-worker mesh + receivers + fault-injected store/Prometheus under a
  scheduled plan and asserts exactly-once judgment, breaker re-close,
  and bounded recovery in-run.

Metric families (`foremast_chaos_*` / `foremast_breaker_*` /
`foremast_degraded_*`) export via `ChaosCollector` and are registered
through the PR-8 metrics-contract gate (docs/observability.md).
"""

from foremast_tpu.chaos.breaker import (
    BreakerOpen,
    BreakerRegistry,
    CircuitBreaker,
)
from foremast_tpu.chaos.collector import ChaosCollector
from foremast_tpu.chaos.degrade import (
    DegradeStats,
    Degradation,
    WriteBehindBuffer,
    is_transient_error,
)
from foremast_tpu.chaos.guard import GuardedSession
from foremast_tpu.chaos.plan import (
    EdgeChaos,
    FaultPlan,
    FaultRule,
    InjectedFault,
    chaos_from_env,
)

__all__ = [
    "BreakerOpen",
    "BreakerRegistry",
    "ChaosCollector",
    "CircuitBreaker",
    "DegradeStats",
    "Degradation",
    "EdgeChaos",
    "FaultPlan",
    "FaultRule",
    "GuardedSession",
    "InjectedFault",
    "WriteBehindBuffer",
    "chaos_from_env",
    "is_transient_error",
]
