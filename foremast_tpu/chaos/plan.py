"""Seeded, scheduled fault plans — the injection half of the chaos plane.

A `FaultPlan` is a list of `FaultRule`s, each scoped to one dependency
EDGE (``prometheus`` / ``store`` / ``kube`` / ``receiver`` / ``pusher``
/ ``transfer`` — the peer→peer planned-handoff stream, mesh/handoff.py
— plus whatever a harness invents) and optionally to a time window
relative to plan activation. Clients hold an `EdgeChaos` view and call
``perturb(op)`` at their single request choke point; with no plan
attached (`chaos is None`, the production default) the seam is a plain
attribute check and nothing else.

Determinism: every probabilistic decision draws from a per-edge
`random.Random` seeded from (plan seed, edge name), so a chaos test
replays identically given the same call order — no global RNG, no
wall-clock dependence beyond the injectable plan clock.

Fault kinds (per rule):
  * ``latency_seconds``   sleep before the real call (slow dependency;
                          a large value vs the client timeout is the
                          classic slow-drip);
  * ``error_rate``        probability of raising an `InjectedFault`
                          per call (1.0 = hard outage);
  * ``kind``              what the fault looks like: ``connection``
                          (refused/reset), ``timeout`` (client-side
                          read timeout), or ``status`` with ``status``
                          (servers we control answer that HTTP code;
                          pure clients raise — see InjectedFault);
  * ``blackhole``         shorthand: hold the call for the rule's
                          latency (default: the edge's typical client
                          timeout is expected to fire first), then
                          raise a timeout — packets leave, nothing
                          returns;
  * ``skew_seconds``      clock skew served by ``EdgeChaos.clock()``
                          for components reading leases/watermarks.

`FOREMAST_CHAOS_PLAN` holds the plan as inline JSON or ``@/path/to``
a JSON file; `chaos_from_env()` returns None when unset so callers wire
seams only when chaos is actually requested.
"""

from __future__ import annotations

import json
import logging
import os
import random
import threading
import time
import zlib
from dataclasses import dataclass, field

log = logging.getLogger("foremast_tpu.chaos")

KIND_CONNECTION = "connection"
KIND_TIMEOUT = "timeout"
KIND_STATUS = "status"
_KINDS = (KIND_CONNECTION, KIND_TIMEOUT, KIND_STATUS)


class InjectedFault(ConnectionError):
    """A chaos-synthesized dependency failure.

    Subclasses ConnectionError on purpose: every transient-failure net
    in the repo (PrometheusSource retries, the resilient store writes,
    breaker classification) already treats ConnectionError as
    transient, so injected faults exercise exactly the degradation
    paths a real outage would — no special-casing in product code.
    ``status`` carries the HTTP code for servers that can ANSWER the
    fault (receiver, fake kube, bench store) instead of raising it.
    """

    def __init__(self, edge: str, kind: str, status: int = 503):
        super().__init__(f"chaos[{edge}]: injected {kind}")
        self.edge = edge
        self.kind = kind
        self.status = status


class InjectedTimeout(InjectedFault, TimeoutError):
    """Injected client-side timeout (also a TimeoutError so timeout
    classification paths fire)."""


@dataclass(frozen=True)
class FaultRule:
    """One scheduled fault on one dependency edge."""

    edge: str
    op: str = ""  # substring match on the call's op/url ("" = all)
    after: float = 0.0  # seconds since plan activation
    duration: float | None = None  # None = until the plan ends
    latency_seconds: float = 0.0
    error_rate: float = 0.0
    kind: str = KIND_CONNECTION
    status: int = 503
    blackhole: bool = False
    skew_seconds: float = 0.0

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}")

    def active(self, elapsed: float) -> bool:
        if elapsed < self.after:
            return False
        if self.duration is None:
            return True
        return elapsed < self.after + self.duration

    @staticmethod
    def from_json(d: dict) -> "FaultRule":
        known = {
            "edge", "op", "after", "duration", "latency_seconds",
            "error_rate", "kind", "status", "blackhole", "skew_seconds",
        }
        extra = set(d) - known
        if extra:
            raise ValueError(f"unknown fault-rule fields {sorted(extra)}")
        return FaultRule(**d)


@dataclass
class FaultPlan:
    """The scheduled fault set plus its activation clock and counters."""

    rules: tuple[FaultRule, ...] = ()
    seed: int = 0
    clock: object = field(default=time.monotonic, repr=False)

    def __post_init__(self):
        self.rules = tuple(
            r if isinstance(r, FaultRule) else FaultRule.from_json(r)
            for r in self.rules
        )
        # construction-time writes: dataclasses run __post_init__ before
        # the instance is published to any other thread, so these three
        # are the same pre-publication case as a plain __init__ body
        # (the lock they are guarded by is born on the next line)
        self._epoch: float | None = None  # foremast: ignore[lock-discipline] — pre-publication init
        self._lock = threading.Lock()
        # (edge, kind) -> count; mutated under _lock (perturb runs on
        # receiver handler threads AND worker fetch pools concurrently)
        self.injections: dict[tuple[str, str], int] = {}  # foremast: ignore[lock-discipline] — pre-publication init
        self._edges: dict[str, EdgeChaos] = {}  # foremast: ignore[lock-discipline] — pre-publication init

    # -- lifecycle ------------------------------------------------------

    def activate(self, now: float | None = None) -> "FaultPlan":
        """Start the schedule clock; idempotent (first activation wins,
        so every edge shares one epoch)."""
        with self._lock:
            if self._epoch is None:
                self._epoch = self.clock() if now is None else now
        return self

    def elapsed(self, now: float | None = None) -> float:
        with self._lock:
            epoch = self._epoch
        if epoch is None:
            return -1.0  # not yet activated: nothing fires
        return (self.clock() if now is None else now) - epoch

    # -- edge views -----------------------------------------------------

    def edge(self, name: str) -> "EdgeChaos":
        """The client-facing view for one dependency edge (memoized so
        perturb's rule scan is precomputed per edge)."""
        with self._lock:
            ec = self._edges.get(name)
            if ec is None:
                rng = random.Random(
                    (self.seed << 32) ^ zlib.crc32(name.encode())
                )
                ec = EdgeChaos(self, name, rng)
                self._edges[name] = ec
        return ec

    def active_rules(self, edge: str, op: str = "") -> list[FaultRule]:
        elapsed = self.elapsed()
        return [
            r
            for r in self.rules
            if r.edge == edge
            and r.active(elapsed)
            and (not r.op or r.op in op)
        ]

    def count(self, edge: str, kind: str) -> None:
        with self._lock:
            key = (edge, kind)
            self.injections[key] = self.injections.get(key, 0) + 1

    def injections_snapshot(self) -> dict[tuple[str, str], int]:
        with self._lock:
            return dict(self.injections)

    def debug_state(self) -> dict:
        return {
            "seed": self.seed,
            "rules": len(self.rules),
            "elapsed_seconds": round(self.elapsed(), 3),
            "injections": {
                f"{e}/{k}": n
                for (e, k), n in sorted(self.injections_snapshot().items())
            },
        }

    # -- parsing --------------------------------------------------------

    @staticmethod
    def from_json(obj: dict, clock=time.monotonic) -> "FaultPlan":
        return FaultPlan(
            rules=tuple(obj.get("rules", ())),
            seed=int(obj.get("seed", 0)),
            clock=clock,
        )


class EdgeChaos:
    """One dependency edge's injection seam.

    Clients call ``perturb(op)`` at their single request choke point;
    the op string (usually the URL or RPC op name) scopes rules with an
    ``op`` substring. Servers that can ANSWER faults call
    ``perturb(op, raise_faults=False)`` and get the fault back as a
    return value to turn into an HTTP status.
    """

    def __init__(self, plan: FaultPlan, edge: str, rng: random.Random):
        self.plan = plan
        self.edge = edge
        self._rng = rng
        # rng draws are not atomic across threads; serialize them so
        # the deterministic sequence survives concurrent handlers
        self._rng_lock = threading.Lock()

    def perturb(
        self, op: str = "", raise_faults: bool = True
    ) -> InjectedFault | None:
        """Apply every active rule for this edge: sleep the max latency,
        then (probabilistically) fault. Returns the fault instead of
        raising when ``raise_faults`` is False."""
        rules = self.plan.active_rules(self.edge, op)
        if not rules:
            return None
        delay = 0.0
        fault: InjectedFault | None = None
        for r in rules:
            delay = max(delay, r.latency_seconds)
            if fault is None and (r.blackhole or r.error_rate > 0.0):
                if r.blackhole:
                    hit = True
                else:
                    with self._rng_lock:
                        hit = self._rng.random() < r.error_rate
                if hit:
                    kind = KIND_TIMEOUT if r.blackhole else r.kind
                    cls = (
                        InjectedTimeout
                        if kind == KIND_TIMEOUT
                        else InjectedFault
                    )
                    fault = cls(self.edge, kind, status=r.status)
        if delay > 0.0:
            self.plan.count(self.edge, "latency")
            time.sleep(delay)
        if fault is not None:
            self.plan.count(self.edge, fault.kind)
            if raise_faults:
                raise fault
        return fault

    def skew_seconds(self) -> float:
        """The currently-active clock skew for this edge (0 outside any
        skew rule's window)."""
        skew = 0.0
        for r in self.plan.active_rules(self.edge):
            if r.skew_seconds:
                skew = r.skew_seconds
        return skew

    def clock(self, base=time.time):
        """A skew-applying wall clock for components that read leases /
        watermarks by their own clock (mesh membership)."""

        def skewed() -> float:
            return base() + self.skew_seconds()

        return skewed


def chaos_from_env(env=None) -> FaultPlan | None:
    """Build + activate the plan from `FOREMAST_CHAOS_PLAN` (inline
    JSON, or ``@path`` to a JSON file); None when unset — the caller
    then wires NO seams and every client keeps its zero-cost None
    check. Malformed plans raise: a chaos run that silently tests
    nothing is worse than a crash at startup."""
    e = os.environ if env is None else env
    raw = e.get("FOREMAST_CHAOS_PLAN", "")
    if not raw:
        return None
    if raw.startswith("@"):
        with open(raw[1:], encoding="utf-8") as fh:
            raw = fh.read()
    plan = FaultPlan.from_json(json.loads(raw))
    plan.activate()
    log.warning(
        "CHAOS PLAN ACTIVE: %d rule(s), seed %d — this process is "
        "deliberately injecting dependency faults",
        len(plan.rules), plan.seed,
    )
    return plan
