"""`GuardedSession` — chaos + breaker at a requests-Session choke point.

`ElasticsearchStore` (and any other requests-based client with many
call sites) issues every round trip through one Session object; rather
than threading seams through a dozen methods, the session itself is
wrapped once. The wrapper:

  * checks the edge's circuit breaker before the call (`BreakerOpen`
    short-circuits in microseconds while the dependency is known-down);
  * applies the edge's chaos perturbation (latency / injected faults);
  * records the call's outcome on the breaker with the shared
    transient classification (connection/timeout errors and HTTP
    429/5xx are failures that could heal; 4xx means the endpoint is
    alive and counts as breaker success).

Only the verbs the store uses are proxied (`get`/`post`/`put`);
everything else delegates via `__getattr__` so injected test doubles
keep working unwrapped-compatible.
"""

from __future__ import annotations

from foremast_tpu.chaos.degrade import is_transient_error


class GuardedSession:
    def __init__(self, inner, chaos=None, breaker=None):
        self.inner = inner
        self.chaos = chaos
        self.breaker = breaker

    def _call(self, verb: str, url: str, **kw):
        from foremast_tpu.metrics.source import RETRY_STATUSES

        breaker = self.breaker
        if breaker is not None:
            breaker.allow()
        try:
            if self.chaos is not None:
                self.chaos.perturb(url)
            resp = getattr(self.inner, verb)(url, **kw)
        except BaseException as e:
            if breaker is not None and is_transient_error(e):
                breaker.record_failure()
            raise
        if breaker is not None:
            if resp.status_code in RETRY_STATUSES:
                breaker.record_failure()
            else:
                breaker.record_success()
        return resp

    def get(self, url, **kw):
        return self._call("get", url, **kw)

    def post(self, url, **kw):
        return self._call("post", url, **kw)

    def put(self, url, **kw):
        return self._call("put", url, **kw)

    def __getattr__(self, name):
        return getattr(self.inner, name)
