"""Anomaly-join and panel shaping — server-side, pure, tested.

The reference UI joins anomaly timestamps onto the base series in the
browser (`foremast-browser/src/App.js:231-260`) so anomalies plot as dots
on the measured curve. Round 1 kept that in client JS, which left the one
piece of real logic in the dashboard unexecuted by any test (no JS
runtime in CI). It now lives here: the UI server's `/api/v1/panel`
endpoint fetches a panel's four series, scales them, and joins anomalies
in Python; `static/app.js` only renders what it is given.

Join semantics (matching the engine's gauge behavior): the
`foremastbrain:<metric>_anomaly` gauge is sticky — it holds the *last*
anomalous value and is never cleared — so the raw series repeats the value
at every scrape after an anomaly. An anomaly *event* is where the series
appears or its value changes; a series already present at the window's
left edge is an old sticky value, not an event inside this window. Events
are then joined onto base-series timestamps and plotted at the *measured*
value.
"""

from __future__ import annotations

Point = tuple[float, float]  # (unix seconds, value)

_UNSET = object()


def anomaly_events(
    anomaly: list[Point], start: float, step: float
) -> list[Point]:
    """Sticky-gauge series -> the anomaly events inside this window."""
    events: list[Point] = []
    prev: object = _UNSET
    for t, v in anomaly:
        at_left_edge = prev is _UNSET and t <= start + step
        if (prev is _UNSET and not at_left_edge) or (
            prev is not _UNSET and v != prev
        ):
            events.append((t, v))
        prev = v
    return events


def join_anomalies(
    base: list[Point], anomaly: list[Point], start: float, step: float
) -> list[Point]:
    """Anomaly events joined onto base timestamps, at the MEASURED value
    (the dot must land on the plotted curve, reference App.js:231-260)."""
    base_by_t = {t: v for t, v in base}
    return [
        (t, base_by_t[t])
        for t, _ in anomaly_events(anomaly, start, step)
        if t in base_by_t
    ]


def panel_payload(
    by_type: dict[str, list[Point]], scale: float, start: float, step: float
) -> dict:
    """The full per-panel data blob the dashboard renders: scaled series
    plus the anomaly join. Keys mirror what app.js previously computed."""
    scaled = {
        k: [{"t": t, "v": v * scale} for t, v in series]
        for k, series in by_type.items()
    }
    joined = join_anomalies(
        by_type.get("base", []), by_type.get("anomaly", []), start, step
    )
    scaled["anomalyJoined"] = [{"t": t, "v": v * scale} for t, v in joined]
    return scaled
