"""Dashboard plane (the foremast-browser equivalent)."""

from foremast_tpu.ui.metrics import DEFAULT_PANELS, Panel, dashboard_config

__all__ = ["DEFAULT_PANELS", "Panel", "dashboard_config"]
