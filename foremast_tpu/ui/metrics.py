"""Dashboard metric-panel configuration.

Role parity with the reference UI's `foremast-browser/src/config/metrics.js`
(`METRICS_MAP`): each panel charts four series — BASE (the measured
recording-rule series), UPPER/LOWER (the model band the engine publishes)
and ANOMALY (anomaly-timestamp gauge) — with per-panel scale/unit.

Differences from the reference, by design:
  * series names are *generated* from the base metric with the exact
    sanitization `observe.gauges.BrainGauges` uses when exporting
    (prometheus_client forbids ':' in names), so the dashboard can never
    drift from what the engine actually publishes;
  * the map is parameterized by (namespace, app) instead of hardcoding the
    demo's labels (`metrics.js` hardcodes foremast-examples/demo).
"""

from __future__ import annotations

import dataclasses

from foremast_tpu.observe.gauges import _san

BASE = "base"
UPPER = "upper"
LOWER = "lower"
ANOMALY = "anomaly"

GAUGE_NAMESPACE = "foremastbrain"


@dataclasses.dataclass(frozen=True)
class Panel:
    """One chart: a base recording-rule series + its model-band family."""

    metric: str  # base series, e.g. namespace_app_per_pod:http_server_requests_latency
    common_name: str
    scale: float = 1.0
    unit: str = "count"

    def series(self, namespace: str, app: str) -> list[dict]:
        base_sel = f'{{namespace="{namespace}",app="{app}"}}'
        # the engine exports with exported_namespace/app labels
        # (gauges.py BrainGauges.publish)
        gauge_sel = f'{{exported_namespace="{namespace}",app="{app}"}}'
        g = f"{GAUGE_NAMESPACE}_{_san(self.metric)}"
        return [
            {"type": BASE, "name": self.metric, "query": self.metric + base_sel},
            {"type": UPPER, "name": f"{g}_upper", "query": f"{g}_upper" + gauge_sel},
            {"type": LOWER, "name": f"{g}_lower", "query": f"{g}_lower" + gauge_sel},
            {
                "type": ANOMALY,
                "name": f"{g}_anomaly",
                "query": f"{g}_anomaly" + gauge_sel,
            },
        ]

    def to_json(self, namespace: str, app: str) -> dict:
        return {
            "metric": self.metric,
            "commonName": self.common_name,
            "scale": self.scale,
            "unit": self.unit,
            "series": self.series(namespace, app),
        }


# Default panel set — the reference's METRICS_MAP entries (5xx, latency,
# CPU, memory) plus 4xx/tps which its recording rules also record.
DEFAULT_PANELS: tuple[Panel, ...] = (
    Panel(
        "namespace_app_per_pod:http_server_requests_error_5xx",
        "5XX Errors",
    ),
    Panel(
        "namespace_app_per_pod:http_server_requests_error_4xx",
        "4XX Errors",
    ),
    Panel(
        "namespace_app_per_pod:http_server_requests_latency",
        "Latency",
        scale=1000,
        unit="ms",
    ),
    Panel(
        "namespace_app_per_pod:http_server_requests_count",
        "Request Rate",
        unit="req/s",
    ),
    Panel(
        "namespace_app_per_pod:cpu_usage_seconds_total",
        "CPU",
        unit="cores",
    ),
    Panel(
        "namespace_app_per_pod:memory_usage_bytes",
        "Memory",
        scale=1 / (1024 * 1024),
        unit="MiB",
    ),
)


def _validate_panels(panels: tuple[Panel, ...]) -> None:
    """Every panel's base metric must be a series the recording-rule
    generator actually records — the no-drift guarantee for the base
    curve (the gauge names already share the engine's sanitizer)."""
    from foremast_tpu.metrics.rules import rule_expr

    for p in panels:
        if rule_expr(p.metric) is None:
            raise ValueError(
                f"panel {p.common_name!r} charts {p.metric!r}, which is not "
                "a generated recording rule (metrics/rules.py)"
            )


_validate_panels(DEFAULT_PANELS)


def dashboard_config(
    service_endpoint: str,
    namespace: str = "foremast-examples",
    app: str = "demo",
    panels: tuple[Panel, ...] = DEFAULT_PANELS,
    poll_seconds: int = 15,
    window_seconds: int = 3600,
    step_seconds: int = 15,
) -> dict:
    """The JSON blob injected into index.html as window.FOREMAST_CONFIG.

    poll/step of 15 s match the reference UI (`App.js:20,78`)."""
    return {
        "serviceEndpoint": service_endpoint.rstrip("/"),
        "namespace": namespace,
        "app": app,
        "pollSeconds": poll_seconds,
        "windowSeconds": window_seconds,
        "stepSeconds": step_seconds,
        "panels": [p.to_json(namespace, app) for p in panels],
    }
