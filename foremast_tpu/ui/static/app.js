/* Foremast dashboard — rendering only.
 *
 * Role parity with the reference UI (foremast-browser/src/App.js): poll
 * every 15 s per panel, chart base / upper / lower / anomaly with anomaly
 * dots on the measured curve, a crosshair synchronized across all panels
 * (App.js:44-78) plus a scatter chart. No chart library: plain SVG.
 * Data shaping (series fetch, scaling, anomaly-event join — reference
 * App.js:231-260) happens server-side in ui/join.py via /api/v1/panel so
 * the logic is testable in Python; this file only draws the payload.
 */
"use strict";

const CFG = window.FOREMAST_CONFIG;
const PAD = { l: 44, r: 10, t: 8, b: 18 };
const panels = []; // {cfg, el, data, svg}
let tableMode = false;

/* ---------------- data ---------------- */

async function fetchPanel(p) {
  // the UI server fetches the panel's four series, scales them, and joins
  // anomaly events onto the base curve (ui/join.py — tested in Python;
  // reference semantics: App.js:231-260). This client only renders.
  try {
    const u = new URL("/api/v1/panel", location.origin);
    u.searchParams.set("i", p.idx);
    // the range presets mutate these; the server honors them per request
    u.searchParams.set("window", CFG.windowSeconds);
    u.searchParams.set("step", CFG.stepSeconds);
    const r = await fetch(u);
    if (!r.ok) throw new Error(`panel ${r.status}`);
    p.data = await r.json();
  } catch (e) {
    p.data = {};
  }
}

/* ---------------- scales / svg helpers ---------------- */

function svgEl(tag, attrs) {
  const el = document.createElementNS("http://www.w3.org/2000/svg", tag);
  for (const [k, v] of Object.entries(attrs || {})) el.setAttribute(k, v);
  return el;
}

function extent(seriesList, pick) {
  let lo = Infinity, hi = -Infinity;
  for (const s of seriesList)
    for (const d of s) {
      const x = pick(d);
      if (Number.isFinite(x)) { if (x < lo) lo = x; if (x > hi) hi = x; }
    }
  return lo <= hi ? [lo, hi] : null;
}

function niceTicks(lo, hi, n) {
  const span = hi - lo || 1;
  const step = Math.pow(10, Math.floor(Math.log10(span / n)));
  const err = span / n / step;
  const mult = err >= 7.5 ? 10 : err >= 3.5 ? 5 : err >= 1.5 ? 2 : 1;
  const s = step * mult;
  const ticks = [];
  for (let v = Math.ceil(lo / s) * s; v <= hi + 1e-9; v += s) ticks.push(v);
  return ticks;
}

/* Pure render-path geometry — every function below is PORTED to Python
 * and pinned in tests/test_ui_js.py (the executed spec): domain
 * computation, pixel scales, polyline/polygon point strings, anomaly-dot
 * placement, tick layout, nearest-point lookup. Rendering proper is
 * reduced to DOM calls over these outputs. */

function makeDomain(base, upper, lower) {
  // time domain from the measured curve; value domain over curve + band,
  // +-8% headroom; degenerate (flat) spans widen by 1 so Y never /0
  const tExt = extent([base], (x) => x.t);
  const vExt = extent([base, upper, lower], (x) => x.v);
  if (!tExt || !vExt) return null;
  const t0 = tExt[0], t1 = tExt[1];
  let v0 = vExt[0], v1 = vExt[1];
  if (v0 === v1) { v0 -= 1; v1 += 1; }
  const padV = (v1 - v0) * 0.08;
  return { t0, t1, v0: v0 - padV, v1: v1 + padV };
}

function xPix(t, dom, W) {
  return PAD.l + ((t - dom.t0) / (dom.t1 - dom.t0 || 1)) * (W - PAD.l - PAD.r);
}

function yPix(v, dom, H) {
  return H - PAD.b - ((v - dom.v0) / (dom.v1 - dom.v0)) * (H - PAD.t - PAD.b);
}

function pathPoints(series, dom, W, H) {
  return series.map((x) => `${xPix(x.t, dom, W)},${yPix(x.v, dom, H)}`).join(" ");
}

function bandPolygon(upper, lower, dom, W, H) {
  // fill between the band edges over their COMMON timestamps: forward
  // along upper, back along lower (reversed) closes the polygon
  const loByT = new Map(lower.map((x) => [x.t, x.v]));
  const pts = upper.filter((x) => loByT.has(x.t));
  if (!pts.length) return null;
  const fwd = pts.map((x) => `${xPix(x.t, dom, W)},${yPix(x.v, dom, H)}`);
  const back = pts.slice().reverse()
    .map((x) => `${xPix(x.t, dom, W)},${yPix(loByT.get(x.t), dom, H)}`);
  return fwd.concat(back).join(" ");
}

function anomalyDots(anoms, dom, W, H) {
  return anoms.map((a) => ({ cx: xPix(a.t, dom, W), cy: yPix(a.v, dom, H) }));
}

function tickLayout(dom, W, H) {
  const yTicks = niceTicks(dom.v0, dom.v1, 4)
    .map((v) => ({ v, y: yPix(v, dom, H) }));
  const nT = Math.max(2, Math.floor(W / 140));
  const xTicks = niceTicks(dom.t0, dom.t1, nT)
    .map((t) => ({ t, x: xPix(t, dom, W) }));
  return { yTicks, xTicks };
}

const fmtV = (v) =>
  Math.abs(v) >= 1e6 ? (v / 1e6).toFixed(1) + "M"
  : Math.abs(v) >= 1e3 ? (v / 1e3).toFixed(1) + "k"
  : Math.abs(v) >= 100 ? v.toFixed(0)
  : Math.abs(v) >= 1 ? v.toFixed(1) : v.toPrecision(2);
const fmtT = (t) => {
  const d = new Date(t * 1000);
  return `${String(d.getHours()).padStart(2, "0")}:${String(d.getMinutes()).padStart(2, "0")}`;
};

/* ---------------- panel rendering ---------------- */

function renderPanel(p) {
  const box = p.el.querySelector(".chartbox");
  box.innerHTML = "";
  const d = p.data || {};
  const base = d.base || [];
  if (!base.length) {
    const e = document.createElement("div");
    e.className = "empty";
    e.textContent = "no data";
    box.appendChild(e);
    return;
  }
  if (tableMode) return renderTable(p, box);

  const W = box.clientWidth || 440, H = 180;
  const svg = svgEl("svg", { viewBox: `0 0 ${W} ${H}` });
  const up = d.upper || [], lo = d.lower || [];
  const dom = makeDomain(base, up, lo);
  if (!dom) {  // all-NaN series (e.g. PromQL 0/0) — treat as empty
    const e = document.createElement("div");
    e.className = "empty";
    e.textContent = "no data";
    box.appendChild(e);
    return;
  }
  const X = (t) => xPix(t, dom, W);
  const Y = (v) => yPix(v, dom, H);
  p.X = X; p.Y = Y; p.t0 = dom.t0; p.t1 = dom.t1; p.W = W; p.H = H;

  const ticks = tickLayout(dom, W, H);
  for (const g of ticks.yTicks) {
    svg.appendChild(svgEl("line", { class: "gridline", x1: PAD.l, x2: W - PAD.r, y1: g.y, y2: g.y }));
    const txt = svgEl("text", { x: PAD.l - 6, y: g.y + 3, "text-anchor": "end" });
    txt.textContent = fmtV(g.v);
    svg.appendChild(txt);
  }
  for (const g of ticks.xTicks) {
    const txt = svgEl("text", { x: g.x, y: H - 4, "text-anchor": "middle" });
    txt.textContent = fmtT(g.t);
    svg.appendChild(txt);
  }
  svg.appendChild(svgEl("line", { class: "axisline", x1: PAD.l, x2: W - PAD.r, y1: H - PAD.b, y2: H - PAD.b }));

  // model band: fill between upper and lower where both exist
  if (up.length && lo.length) {
    const poly = bandPolygon(up, lo, dom, W, H);
    if (poly !== null)
      svg.appendChild(svgEl("polygon", { class: "band-area", points: poly }));
    for (const edge of [up, lo])
      svg.appendChild(svgEl("polyline", { class: "band-edge", points: pathPoints(edge, dom, W, H) }));
  }

  svg.appendChild(svgEl("polyline", { class: "baseline-path", points: pathPoints(base, dom, W, H) }));

  for (const a of anomalyDots(d.anomalyJoined || [], dom, W, H))
    svg.appendChild(svgEl("circle", { class: "anom", cx: a.cx, cy: a.cy, r: 4.5 }));

  // crosshair layer (populated by the shared hover handler)
  p.xhair = svgEl("line", { class: "xhair", y1: PAD.t, y2: H - PAD.b, visibility: "hidden" });
  p.hoverdot = svgEl("circle", { class: "hoverdot", r: 4, visibility: "hidden" });
  svg.appendChild(p.xhair);
  svg.appendChild(p.hoverdot);

  svg.addEventListener("mousemove", (ev) => {
    const rect = svg.getBoundingClientRect();
    const frac = (ev.clientX - rect.left) / rect.width;
    const t = dom.t0 + Math.max(0, Math.min(1, (frac * W - PAD.l) / (W - PAD.l - PAD.r))) * (dom.t1 - dom.t0);
    syncCrosshair(t, ev);
  });
  svg.addEventListener("mouseleave", () => syncCrosshair(null));
  p.svg = svg;
  box.appendChild(svg);
}

function renderTable(p, box) {
  const d = p.data;
  const wrap = document.createElement("div");
  wrap.className = "tablebox";
  const anomT = new Set((d.anomalyJoined || []).map((a) => a.t));
  const upByT = new Map((d.upper || []).map((x) => [x.t, x.v]));
  const loByT = new Map((d.lower || []).map((x) => [x.t, x.v]));
  const rows = d.base
    .map((x) =>
      `<tr><td>${new Date(x.t * 1000).toLocaleTimeString()}</td>` +
      `<td>${fmtV(x.v)}</td>` +
      `<td>${upByT.has(x.t) ? fmtV(upByT.get(x.t)) : ""}</td>` +
      `<td>${loByT.has(x.t) ? fmtV(loByT.get(x.t)) : ""}</td>` +
      `<td>${anomT.has(x.t) ? "⚠ anomaly" : ""}</td></tr>`
    )
    .join("");
  wrap.innerHTML = `<table class="data"><thead><tr><th>time</th><th>value</th><th>upper</th><th>lower</th><th>state</th></tr></thead><tbody>${rows}</tbody></table>`;
  box.appendChild(wrap);
}

/* ---------------- synchronized crosshair + tooltip ---------------- */

const tooltip = document.createElement("div");
tooltip.className = "tooltip";
document.body.appendChild(tooltip);

function nearest(series, t) {
  let best = null, bd = Infinity;
  for (const d of series) {
    const dd = Math.abs(d.t - t);
    if (dd < bd) { bd = dd; best = d; }
  }
  return best;
}

function syncCrosshair(t, ev) {
  let tipRows = [];
  for (const p of panels) {
    if (!p.svg || !p.X) continue;
    if (t == null) {
      p.xhair.setAttribute("visibility", "hidden");
      p.hoverdot.setAttribute("visibility", "hidden");
      continue;
    }
    const pt = nearest(p.data.base, t);
    if (!pt) continue;
    const x = p.X(pt.t);
    p.xhair.setAttribute("x1", x);
    p.xhair.setAttribute("x2", x);
    p.xhair.setAttribute("visibility", "visible");
    p.hoverdot.setAttribute("cx", x);
    p.hoverdot.setAttribute("cy", p.Y(pt.v));
    p.hoverdot.setAttribute("visibility", "visible");
    const isAnom = (p.data.anomalyJoined || []).some((a) => a.t === pt.t);
    tipRows.push(
      `<div class="row"><span>${p.cfg.commonName}</span>` +
      `<span class="v">${fmtV(pt.v)} ${p.cfg.unit}${isAnom ? ' <span class="anom-flag">⚠</span>' : ""}</span></div>`
    );
  }
  if (t == null || !ev || !tipRows.length) {
    tooltip.style.display = "none";
    return;
  }
  tooltip.innerHTML = `<div class="t">${new Date(t * 1000).toLocaleTimeString()}</div>` + tipRows.join("");
  tooltip.style.display = "block";
  const tw = tooltip.offsetWidth, th = tooltip.offsetHeight;
  let tx = ev.clientX + 14, ty = ev.clientY + 12;
  if (tx + tw > innerWidth - 8) tx = ev.clientX - tw - 14;
  if (ty + th > innerHeight - 8) ty = ev.clientY - th - 12;
  tooltip.style.left = tx + "px";
  tooltip.style.top = ty + "px";
}

/* ---------------- scatter (first two panels, joined on time) ---------------- */

function renderScatter() {
  const wrap = document.getElementById("scatterWrap");
  wrap.innerHTML = "";
  const [pa, pb] = panels;
  if (!pa?.data?.base?.length || !pb?.data?.base?.length) return;
  const bByT = new Map(pb.data.base.map((d) => [d.t, d.v]));
  const pts = pa.data.base.filter((d) => bByT.has(d.t)).map((d) => ({ x: d.v, y: bByT.get(d.t) }));
  if (!pts.length) return;

  const div = document.createElement("div");
  div.className = "panel";
  div.innerHTML = `<h2>${pa.cfg.commonName} vs ${pb.cfg.commonName}</h2>`;
  const W = 520, H = 220;
  const svg = svgEl("svg", { viewBox: `0 0 ${W} ${H}`, style: "height:220px" });
  const xExt = extent([pts], (d) => d.x);
  const yExt = extent([pts], (d) => d.y);
  if (!xExt || !yExt) return;
  let [x0, x1] = xExt;
  let [y0, y1] = yExt;
  if (x0 === x1) { x0 -= 1; x1 += 1; }
  if (y0 === y1) { y0 -= 1; y1 += 1; }
  const X = (v) => PAD.l + ((v - x0) / (x1 - x0)) * (W - PAD.l - PAD.r);
  const Y = (v) => H - PAD.b - ((v - y0) / (y1 - y0)) * (H - PAD.t - PAD.b);
  for (const v of niceTicks(y0, y1, 4)) {
    svg.appendChild(svgEl("line", { class: "gridline", x1: PAD.l, x2: W - PAD.r, y1: Y(v), y2: Y(v) }));
    const txt = svgEl("text", { x: PAD.l - 6, y: Y(v) + 3, "text-anchor": "end" });
    txt.textContent = fmtV(v);
    svg.appendChild(txt);
  }
  for (const v of niceTicks(x0, x1, 5)) {
    const txt = svgEl("text", { x: X(v), y: H - 4, "text-anchor": "middle" });
    txt.textContent = fmtV(v);
    svg.appendChild(txt);
  }
  for (const d of pts)
    svg.appendChild(svgEl("circle", { class: "scatter-dot", cx: X(d.x), cy: Y(d.y), r: 3.5 }));
  div.appendChild(svg);
  wrap.appendChild(div);
}

/* ---------------- bootstrap ---------------- */

function buildPanels() {
  const root = document.getElementById("panels");
  CFG.panels.forEach((cfg, idx) => {
    const el = document.createElement("div");
    el.className = "panel";
    el.innerHTML =
      `<h2>${cfg.commonName} <span style="font-weight:400;color:var(--text-muted)">(${cfg.unit})</span></h2>` +
      `<div class="legend">` +
      `<span><span class="key base"></span>measured</span>` +
      `<span><span class="key band"></span>model band</span>` +
      `<span><span class="dot"></span>anomaly</span>` +
      `</div><div class="chartbox"></div>`;
    root.appendChild(el);
    panels.push({ cfg, idx, el, data: null });
  });
}

async function refresh() {
  await Promise.all(panels.map(fetchPanel));
  for (const p of panels) renderPanel(p);
  renderScatter();
  document.getElementById("updated").textContent =
    "updated " + new Date().toLocaleTimeString();
}

/* ---------------- time-range presets ---------------- */

const RANGES = [
  { label: "1h", seconds: 3600, step: 15 },
  { label: "6h", seconds: 6 * 3600, step: 60 },
  { label: "24h", seconds: 24 * 3600, step: 300 },
];

function buildRanges() {
  const nav = document.getElementById("ranges");
  for (const r of RANGES) {
    const b = document.createElement("button");
    b.textContent = r.label;
    b.setAttribute(
      "aria-pressed", String(r.seconds === CFG.windowSeconds)
    );
    b.addEventListener("click", () => {
      CFG.windowSeconds = r.seconds;
      CFG.stepSeconds = r.step; // coarser step keeps point counts bounded
      for (const other of nav.children)
        other.setAttribute("aria-pressed", String(other === b));
      refresh();
    });
    nav.appendChild(b);
  }
}

document.getElementById("scope").textContent = `${CFG.namespace} / ${CFG.app}`;
document.getElementById("tableToggle").addEventListener("change", (e) => {
  tableMode = e.target.checked;
  for (const p of panels) renderPanel(p);
});
addEventListener("resize", () => { for (const p of panels) renderPanel(p); });

buildPanels();
buildRanges();
refresh();
setInterval(refresh, CFG.pollSeconds * 1000);
