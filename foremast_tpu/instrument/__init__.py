"""App instrumentation starter — the metrics-production leaf layer.

Python equivalent of `foremast-spring-boot-k8s-metrics-starter/` (SURVEY.md
section 2.4): standardizes how workloads emit HTTP metrics so foremast's
recording rules and scoring work out of the box. WSGI and aiohttp
middlewares, common tags, zero-initialized status counters, `/metrics`
aliasing, caller tagging, and runtime metric hiding.
"""

from foremast_tpu.instrument.starter import (
    HttpMetrics,
    K8sMetricsConfig,
    MetricsFilter,
    instrument_aiohttp,
    wsgi_middleware,
)

__all__ = [
    "HttpMetrics",
    "K8sMetricsConfig",
    "MetricsFilter",
    "instrument_aiohttp",
    "wsgi_middleware",
]
