"""HTTP metrics starter: middleware + filter + exposition.

Feature parity with the reference starter
(`foremast-spring-boot-k8s-metrics-starter/README.md:5-15`, classes under
`src/main/java/ai/foremast/metrics/k8s/starter/`):

1. common tags on every sample, including the ``app`` tag the recording
   rules aggregate by, from a ``name:value`` pair list with env fallback
   (K8sMetricsAutoConfiguration.java:66-103);
2. zero-initialized counters for configured HTTP statuses so Prometheus
   scrapes 0 instead of no-data (K8sMetricsAutoConfiguration.java:105-115);
3. the ``/metrics`` -> ``/actuator/prometheus`` URL alias — both paths
   serve the exposition here;
4. caller tag from a configurable request header
   (CallerWebMvcTagsProvider.java);
5. metric hiding with whitelist/blacklist/prefix plus the runtime
   ``/k8s-metrics/{enable|disable}/<metric>`` endpoint
   (CommonMetricsFilter.java:30-76, K8sMetricsEndpoint.java:14-35).

The emitted series is ``http_server_requests_seconds`` (count/sum/bucket)
with {app..., method, uri, status, caller} labels — the Micrometer name the
reference's recording rules consume (`metrics-rules-default.yaml:15-39`).
"""

from __future__ import annotations

import os
import time
from typing import Callable, Iterable, Mapping

from prometheus_client import CollectorRegistry, Histogram, generate_latest
from prometheus_client.exposition import CONTENT_TYPE_LATEST

METRICS_PATHS = ("/metrics", "/actuator/prometheus")
CONTROL_PREFIX = "/k8s-metrics/"


def _parse_pairs(spec: str) -> dict[str, str]:
    """``"app:demo,env:prod"`` -> {"app": "demo", "env": "prod"}."""
    out: dict[str, str] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        k, _, v = part.partition(":")
        if k and v:
            out[k.strip()] = v.strip()
    return out


class K8sMetricsConfig:
    """The ``k8s.metrics.*`` property surface (starter README:17-33).

    Tag resolution fallback chain (K8sMetricsAutoConfiguration.java:66-103):
    explicit pairs -> ``K8S_METRICS_COMMON_TAGS`` env -> ``{"app": $APP_NAME}``.
    """

    def __init__(
        self,
        common_tags: Mapping[str, str] | None = None,
        initialize_for_statuses: Iterable[int] = (),
        caller_header: str = "",
        whitelist: Iterable[str] = (),
        blacklist: Iterable[str] = (),
        hide_prefix: str = "",
    ) -> None:
        if common_tags is None:
            env = os.environ.get("K8S_METRICS_COMMON_TAGS", "")
            common_tags = _parse_pairs(env) if env else {}
            if "app" not in common_tags and os.environ.get("APP_NAME"):
                common_tags["app"] = os.environ["APP_NAME"]
        self.common_tags = dict(common_tags)
        self.initialize_for_statuses = tuple(initialize_for_statuses)
        self.caller_header = caller_header
        self.whitelist = frozenset(whitelist)
        self.blacklist = frozenset(blacklist)
        self.hide_prefix = hide_prefix


class MetricsFilter:
    """Exposition-time hiding with runtime toggles
    (CommonMetricsFilter.java:30-76).

    Precedence: whitelist (if set, only those families show) > runtime
    enable > blacklist/prefix/runtime-disable.
    """

    def __init__(self, config: K8sMetricsConfig) -> None:
        self.config = config
        self._disabled: set[str] = set()
        self._enabled: set[str] = set()

    def enable(self, metric: str) -> None:
        self._disabled.discard(metric)
        self._enabled.add(metric)

    def disable(self, metric: str) -> None:
        self._enabled.discard(metric)
        self._disabled.add(metric)

    def visible(self, family: str) -> bool:
        cfg = self.config
        if cfg.whitelist:
            return family in cfg.whitelist or family in self._enabled
        if family in self._enabled:
            return True
        if family in self._disabled or family in cfg.blacklist:
            return False
        if cfg.hide_prefix and family.startswith(cfg.hide_prefix):
            return False
        return True

    def render(self, registry) -> bytes:
        """generate_latest with hidden families stripped (block-wise:
        a family's # HELP/# TYPE/samples travel together)."""
        def base_family(name: str) -> str:
            # strip sample/companion-family suffixes (incl. the _created
            # gauge prometheus_client emits alongside counters/histograms)
            return (
                name.removesuffix("_total")
                .removesuffix("_count")
                .removesuffix("_sum")
                .removesuffix("_bucket")
                .removesuffix("_created")
            )

        out: list[bytes] = []
        keep = True
        for line in generate_latest(registry).splitlines(keepends=True):
            if line.startswith(b"# HELP ") or line.startswith(b"# TYPE "):
                keep = self.visible(base_family(line.split()[2].decode()))
            elif not line.startswith(b"#") and line.strip():
                name = line.split(b"{", 1)[0].split(b" ", 1)[0].decode()
                keep = self.visible(base_family(name))
            if keep:
                out.append(line)
        return b"".join(out)


class HttpMetrics:
    """The ``http_server_requests_seconds`` family with common tags."""

    def __init__(
        self,
        config: K8sMetricsConfig | None = None,
        registry: CollectorRegistry | None = None,
    ) -> None:
        self.config = config or K8sMetricsConfig()
        self.registry = registry if registry is not None else CollectorRegistry()
        self.filter = MetricsFilter(self.config)
        tag_names = sorted(self.config.common_tags)
        self._tag_values = [self.config.common_tags[k] for k in tag_names]
        labels = tag_names + ["method", "uri", "status", "caller"]
        self.requests = Histogram(
            "http_server_requests_seconds",
            "HTTP server request duration",
            labels,
            registry=self.registry,
        )
        # zero-init: a sample exists for each configured status before any
        # real traffic, so rate() sees 0 rather than absent data
        for status in self.config.initialize_for_statuses:
            self.requests.labels(
                *self._tag_values, "GET", "/", str(status), ""
            )

    def observe(
        self, method: str, uri: str, status: int, seconds: float, caller: str = ""
    ) -> None:
        self.requests.labels(
            *self._tag_values, method, uri, str(status), caller
        ).observe(seconds)

    # -- shared endpoint logic (both middlewares route through this) -----

    def handle_control(self, path: str) -> tuple[int, bytes] | None:
        """``/k8s-metrics/{enable|disable}/<metric>`` -> (status, body),
        or None when path is not a control path."""
        if not path.startswith(CONTROL_PREFIX):
            return None
        rest = path[len(CONTROL_PREFIX):]
        action, _, metric = rest.partition("/")
        if action not in ("enable", "disable") or not metric:
            return 404, b"unknown k8s-metrics action"
        (self.filter.enable if action == "enable" else self.filter.disable)(metric)
        return 200, f"{action}d {metric}".encode()

    def exposition(self) -> bytes:
        return self.filter.render(self.registry)


def wsgi_middleware(app: Callable, metrics: HttpMetrics) -> Callable:
    """Wrap any WSGI app: serves the exposition + control endpoints and
    times every other request into ``http_server_requests_seconds``."""

    def wrapped(environ, start_response):
        path = environ.get("PATH_INFO", "/")
        if path in METRICS_PATHS:
            body = metrics.exposition()
            start_response(
                "200 OK",
                [("Content-Type", CONTENT_TYPE_LATEST),
                 ("Content-Length", str(len(body)))],
            )
            return [body]
        ctl = metrics.handle_control(path)
        if ctl is not None:
            status, body = ctl
            start_response(
                f"{status} {'OK' if status == 200 else 'Not Found'}",
                [("Content-Type", "text/plain"),
                 ("Content-Length", str(len(body)))],
            )
            return [body]

        t0 = time.perf_counter()
        captured: dict[str, str] = {}

        def capturing_start_response(status_line, headers, exc_info=None):
            captured["status"] = status_line.split(" ", 1)[0]
            return start_response(status_line, headers, exc_info)

        caller = ""
        if metrics.config.caller_header:
            key = "HTTP_" + metrics.config.caller_header.upper().replace("-", "_")
            caller = environ.get(key, "")
        def record():
            metrics.observe(
                method=environ.get("REQUEST_METHOD", "GET"),
                uri=path,
                status=int(captured.get("status", 500) or 500),
                seconds=time.perf_counter() - t0,
                caller=caller,
            )

        try:
            result = app(environ, capturing_start_response)
        except BaseException:
            record()
            raise
        # PEP 3333 lets the app defer start_response until its result
        # iterable is consumed (streaming apps) — record after iteration,
        # not at call return, so status and duration cover the body
        return _RecordingIterable(result, record)

    return wrapped


class _RecordingIterable:
    """Wraps a WSGI result; fires the record callback exactly once, when
    the response body is exhausted or closed."""

    def __init__(self, result, record):
        self._result = result
        self._record = record
        self._done = False

    def _finish(self):
        if not self._done:
            self._done = True
            self._record()

    def __iter__(self):
        try:
            yield from self._result
        finally:
            self._finish()

    def close(self):
        try:
            close = getattr(self._result, "close", None)
            if close is not None:
                close()
        finally:
            self._finish()


def instrument_aiohttp(app, metrics: HttpMetrics) -> None:
    """Attach the same behavior to an aiohttp Application: middleware
    timing + /metrics alias + control routes."""
    from aiohttp import web

    @web.middleware
    async def timing(request, handler):
        t0 = time.perf_counter()
        caller = (
            request.headers.get(metrics.config.caller_header, "")
            if metrics.config.caller_header
            else ""
        )
        status = 500
        try:
            resp = await handler(request)
            status = resp.status
            return resp
        except web.HTTPException as e:
            # raising HTTPNotFound etc. is the idiomatic aiohttp response
            # path, not a server error
            status = e.status
            raise
        finally:
            if request.path not in METRICS_PATHS and not request.path.startswith(
                CONTROL_PREFIX
            ):
                metrics.observe(
                    request.method, request.path, status,
                    time.perf_counter() - t0, caller,
                )

    async def expo(request):
        # full exposition content type (incl. version param), same as WSGI
        return web.Response(
            body=metrics.exposition(),
            headers={"Content-Type": CONTENT_TYPE_LATEST},
        )

    async def control(request):
        status, body = metrics.handle_control(request.path)
        return web.Response(body=body, status=status, content_type="text/plain")

    app.middlewares.append(timing)
    for p in METRICS_PATHS:
        app.router.add_get(p, expo)
    app.router.add_get(CONTROL_PREFIX + "{action}/{metric}", control)
