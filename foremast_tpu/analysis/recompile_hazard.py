"""recompile-hazard: dispatch-cache blowups at jit call sites.

jit-hygiene (PR 2) guards the INSIDE of jitted functions; this rule
guards their CALL SITES — the other half of the dispatch-cache
contract. XLA keys the compiled-program cache on (shapes, static
values): a caller that feeds raw data-dependent sizes or unbounded
statics compiles a fresh program per distinct value, and the warm tick
becomes a recompile storm that the runtime witness
(`analysis/recompile_witness.py`) counts and the benches gate on.

Three checks, interprocedural over `interproc.Program`:

  * SHAPE — a buffer argument at a call site of a jit entry point or a
    columnar dispatch root (`judge_columnar`/`judge_columnar_async`)
    whose trailing dimension does not come from the pow2/bucket
    helpers (`bucket_length`, `_pow2`, `pad_to_multiple`,
    `_batch_multiple`) is a finding: the LEADING (batch) axis is
    re-bucketed by the callee, but trailing axes key the program —
    `np.zeros((n_rows, len(vals)))` compiles per distinct series
    length, `np.zeros((n_rows, bucket_length(n)))` compiles once per
    pow2 bucket;
  * STATIC — a ``static_argnames``/``static_argnums`` value at a call
    site must come from a bounded domain: constants, module constants,
    config/spec attribute reads, or bucket-helper results. A value
    derived from ``len(...)`` (or arithmetic over one) is a finding —
    every distinct size is a distinct cache entry;
  * PER-CALL JIT — ``jax.jit(...)`` / ``partial(jax.jit, ...)``
    evaluated inside a function body (other than ``__init__``, where
    caching the wrapper per instance is the idiom) builds a NEW
    callable with an empty cache on every call.

Resolution is name-based across the package (the same
over-approximation the concurrency rules use): a call whose bare or
attribute name matches a jitted def anywhere in the package is checked
against that def's statics.
"""

from __future__ import annotations

import ast
import dataclasses

from foremast_tpu.analysis.core import Finding
from foremast_tpu.analysis.interproc import (
    FunctionInfo,
    Program,
    dotted,
    own_body_walk,
)
from foremast_tpu.analysis.jit_hygiene import (
    _is_jax_jit,
    _jit_call_statics,
    _param_names,
)

RULE = "recompile-hazard"

DISPATCH_ENTRIES = frozenset({"judge_columnar", "judge_columnar_async"})
BUCKET_HELPERS = frozenset(
    {"bucket_length", "_pow2", "pad_to_multiple", "_batch_multiple"}
)
_NP_CONSTRUCTORS = frozenset({"zeros", "ones", "empty", "full"})
_ARITH_CALLS = frozenset({"max", "min", "int", "round", "abs"})

# classification lattice for size/static expressions
BOUNDED = "bounded"
RAW = "raw"
UNKNOWN = "unknown"


@dataclasses.dataclass
class _JitEntry:
    fn: FunctionInfo
    params: list[str]
    statics: frozenset[str]


def _module_consts(tree: ast.Module) -> dict[str, ast.AST]:
    consts: dict[str, ast.AST] = {}
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            t = stmt.targets[0]
            if isinstance(t, ast.Name):
                consts[t.id] = stmt.value
    return consts


def collect_jit_entries(program: Program) -> dict[str, list[_JitEntry]]:
    """Every jit-decorated def in the package, by simple name, with its
    static parameter set (``static_argnames`` + ``static_argnums``
    mapped through the parameter list, following the module-constant
    indirection jit-hygiene handles)."""
    out: dict[str, list[_JitEntry]] = {}
    consts_by_mod = {
        m.relpath: _module_consts(m.tree) for m in program.modules
    }
    for fn in program.functions:
        consts = consts_by_mod.get(fn.module.relpath, {})
        statics: set[str] = set()
        jitted = False
        for deco in fn.node.decorator_list:
            if _is_jax_jit(deco):
                jitted = True
            elif isinstance(deco, ast.Call):
                st = _jit_call_statics(deco, consts)
                if st is not None:
                    jitted = True
                    names, nums = st
                    params = _param_names(fn.node)
                    statics.update(names)
                    statics.update(
                        params[i] for i in nums if i < len(params)
                    )
        if jitted:
            out.setdefault(fn.name, []).append(
                _JitEntry(
                    fn=fn,
                    params=_param_names(fn.node),
                    statics=frozenset(statics),
                )
            )
    return out


class _SizeFlow:
    """Per-function classification of size-bearing expressions."""

    def __init__(self, fn: FunctionInfo, consts: dict[str, ast.AST]):
        self.fn = fn
        self.consts = consts
        self.bucketed: set[str] = set()
        self.raw: set[str] = set()
        # name -> the np constructor call it was assigned from
        self.constructed: dict[str, ast.Call] = {}
        self._scan()

    def _scan(self) -> None:
        changed = True
        while changed:
            changed = False
            for node in own_body_walk(self.fn.node):
                if not isinstance(node, ast.Assign):
                    continue
                names = [
                    t.id for t in node.targets if isinstance(t, ast.Name)
                ]
                if not names:
                    continue
                if _np_constructor(node.value) is not None:
                    for n in names:
                        self.constructed.setdefault(n, node.value)
                cls = self.classify(node.value)
                target_set = (
                    self.bucketed
                    if cls == BOUNDED
                    else self.raw if cls == RAW else None
                )
                if target_set is not None:
                    for n in names:
                        if n not in target_set:
                            target_set.add(n)
                            changed = True

    def classify(self, expr: ast.AST) -> str:
        """BOUNDED (constant / config attr / bucket-derived), RAW
        (data-dependent size: len() and arithmetic over one), or
        UNKNOWN (parameters, unresolved calls — never flagged)."""
        if isinstance(expr, ast.Constant):
            return BOUNDED
        if isinstance(expr, ast.Attribute):
            # config/spec field reads are bounded domains by contract
            return BOUNDED if dotted(expr) is not None else UNKNOWN
        if isinstance(expr, ast.Name):
            if expr.id in self.bucketed:
                return BOUNDED
            if expr.id in self.raw:
                return RAW
            if expr.id in self.consts:
                return BOUNDED
            return UNKNOWN
        if isinstance(expr, ast.Call):
            name = None
            if isinstance(expr.func, ast.Name):
                name = expr.func.id
            elif isinstance(expr.func, ast.Attribute):
                name = expr.func.attr
            if name in BUCKET_HELPERS:
                return BOUNDED
            if name == "len":
                return RAW
            if name in _ARITH_CALLS:
                kinds = {self.classify(a) for a in expr.args}
                if RAW in kinds:
                    return RAW
                if kinds <= {BOUNDED}:
                    return BOUNDED
                return UNKNOWN
            return UNKNOWN
        if isinstance(expr, ast.BinOp):
            kinds = {self.classify(expr.left), self.classify(expr.right)}
            if RAW in kinds:
                return RAW
            if kinds <= {BOUNDED}:
                return BOUNDED
            return UNKNOWN
        if isinstance(expr, ast.UnaryOp):
            return self.classify(expr.operand)
        if isinstance(expr, ast.IfExp):
            kinds = {self.classify(expr.body), self.classify(expr.orelse)}
            if RAW in kinds:
                return RAW
            if kinds <= {BOUNDED}:
                return BOUNDED
            return UNKNOWN
        if isinstance(expr, (ast.Compare, ast.BoolOp)):
            return BOUNDED  # bools: a two-value domain
        if isinstance(expr, ast.Subscript):
            # x.shape[i] is the shape of an already-bucketed value
            if (
                isinstance(expr.value, ast.Attribute)
                and expr.value.attr == "shape"
            ):
                return BOUNDED
            return UNKNOWN
        return UNKNOWN


def _np_constructor(expr: ast.AST) -> ast.Call | None:
    if not isinstance(expr, ast.Call):
        return None
    d = dotted(expr.func)
    if (
        d is not None
        and "." in d
        and d.split(".", 1)[0] in ("np", "numpy")
        and d.rsplit(".", 1)[1] in _NP_CONSTRUCTORS
    ):
        return expr
    return None


def _trailing_dim(ctor: ast.Call) -> ast.AST | None:
    """The last element of a multi-dim shape tuple, or None for 1-D
    constructions (the leading/batch axis is the callee's to bucket)."""
    if not ctor.args:
        return None
    shape = ctor.args[0]
    if isinstance(shape, (ast.Tuple, ast.List)) and len(shape.elts) >= 2:
        return shape.elts[-1]
    return None


def check_recompile_hazard(program: Program) -> list[Finding]:
    entries = collect_jit_entries(program)
    findings: list[Finding] = []
    for fn in program.functions:
        consts = _module_consts(fn.module.tree)
        flow = _SizeFlow(fn, consts)
        findings.extend(_check_call_sites(fn, flow, entries))
    for module in program.modules:
        findings.extend(_check_per_call_jit(module))
    return findings


def _check_call_sites(
    fn: FunctionInfo,
    flow: _SizeFlow,
    entries: dict[str, list[_JitEntry]],
) -> list[Finding]:
    module = fn.module
    out: list[Finding] = []
    for node in own_body_walk(fn.node):
        if not isinstance(node, ast.Call):
            continue
        name = None
        is_attr_call = isinstance(node.func, ast.Attribute)
        if isinstance(node.func, ast.Name):
            name = node.func.id
        elif is_attr_call:
            name = node.func.attr
        if name is None:
            continue
        for entry in entries.get(name, ()):
            if entry.fn is fn:
                continue  # a jitted wrapper calling itself recursively
            params = entry.params
            offset = 1 if is_attr_call and params[:1] == ["self"] else 0
            for i, arg in enumerate(node.args):
                pi = i + offset
                if pi < len(params) and params[pi] in entry.statics:
                    out.extend(
                        _static_finding(module, fn, node, params[pi], arg, flow)
                    )
            for kw in node.keywords:
                if kw.arg in entry.statics:
                    out.extend(
                        _static_finding(module, fn, node, kw.arg, kw.value, flow)
                    )
        if name in entries or name in DISPATCH_ENTRIES:
            out.extend(_shape_findings(module, fn, node, name, flow))
    return out


def _static_finding(module, fn, call, param, value, flow) -> list[Finding]:
    if flow.classify(value) != RAW:
        return []
    return [
        module.finding(
            RULE,
            call,
            f"unbounded static: `{param}` at this jit call site in "
            f"`{fn.name}` is a data-dependent size (len()/arithmetic) — "
            "every distinct value compiles a fresh program",
            hint="statics must come from bounded domains (constants, "
            "config fields, enum-like module constants) or through the "
            "bucket helpers (`bucket_length`/`_pow2`) so the dispatch "
            "cache stays finite",
        )
    ]


def _shape_findings(module, fn, call, callee, flow) -> list[Finding]:
    out: list[Finding] = []
    for arg in [*call.args, *[kw.value for kw in call.keywords]]:
        ctor = _np_constructor(arg)
        if ctor is None and isinstance(arg, ast.Name):
            ctor = flow.constructed.get(arg.id)
        if ctor is None:
            continue
        dim = _trailing_dim(ctor)
        if dim is not None and flow.classify(dim) == RAW:
            out.append(
                module.finding(
                    RULE,
                    call,
                    f"unbucketed trailing dimension: a buffer passed to "
                    f"`{callee}` from `{fn.name}` has a data-dependent "
                    "trailing axis — the program recompiles per distinct "
                    "size",
                    hint="round trailing axes through `bucket_length`/"
                    "`_pow2` before building the buffer; only the "
                    "LEADING batch axis is re-bucketed by the callee",
                )
            )
    return out


def _check_per_call_jit(module) -> list[Finding]:
    """`jax.jit(...)` evaluated inside a function body (including a
    nested def's decorator): a fresh callable — and a fresh empty
    dispatch cache — per enclosing call. `__init__` is the sanctioned
    cache-per-instance site."""
    out: list[Finding] = []

    def wraps_jit(call: ast.Call) -> bool:
        if _is_jax_jit(call.func):
            return True
        d = dotted(call.func)
        if d in ("partial", "functools.partial"):
            return bool(call.args) and _is_jax_jit(call.args[0])
        return False

    def scan_expr(expr: ast.AST, inside: str) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call) and wraps_jit(node):
                out.append(_per_call_finding(module, node, inside))

    def visit(body, inside: str | None):
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if inside not in (None, "__init__"):
                    # a jitted nested def: the decorator runs per
                    # enclosing call
                    for deco in stmt.decorator_list:
                        if _is_jax_jit(deco) or (
                            isinstance(deco, ast.Call) and wraps_jit(deco)
                        ):
                            out.append(
                                _per_call_finding(module, deco, inside)
                            )
                visit(stmt.body, stmt.name)
                continue
            if isinstance(stmt, ast.ClassDef):
                visit(stmt.body, inside)
                continue
            if inside not in (None, "__init__"):
                for _f, value in ast.iter_fields(stmt):
                    if isinstance(value, ast.AST):
                        scan_expr(value, inside)
            for _f, value in ast.iter_fields(stmt):
                if isinstance(value, list) and value:
                    if isinstance(value[0], ast.stmt):
                        visit(value, inside)
                    elif isinstance(value[0], ast.excepthandler):
                        for h in value:
                            visit(h.body, inside)
                    elif hasattr(value[0], "body") and isinstance(
                        getattr(value[0], "body", None), list
                    ):  # match cases
                        for case in value:
                            visit(case.body, inside)

    visit(module.tree.body, None)
    return sorted(set(out), key=Finding.sort_key)


def _per_call_finding(module, node, fn_name: str) -> Finding:
    return module.finding(
        RULE,
        node,
        f"per-call `jax.jit` inside `{fn_name}`: every call builds a new "
        "callable with an empty dispatch cache — a recompile per "
        "invocation",
        hint="hoist the jit to module scope (decorator) or cache the "
        "wrapper once in `__init__`",
    )
