"""metrics-contract: constructed families, the lint registry, and the
docs can never drift.

Before this rule there were THREE places a metric family lived — the
constructor call in code, the `observe/metrics_lint.py` registry
(`ALLOWED_LABELS`, which pins label sets), and the hand-written tables
in `docs/observability.md` — and nothing tied them together: the
PR-3 pipeline families shipped constructed-but-unregistered, so their
label sets were never checked (this rule's first real catch; they are
registered now).

The contract, machine-checked on every `make check`:

  1. every LITERAL ``foremast*`` family name passed to a metric
     constructor (`Counter`/`Gauge`/`Histogram`/`*MetricFamily`/the
     `counter()` helper) anywhere in the package must appear in
     `ALLOWED_LABELS` (collected names — a counter's ``_total`` suffix
     is stripped) AND carry a one-line meaning in `FAMILY_DOCS`;
  2. every registry entry must be constructed somewhere (or be
     declared in `DYNAMIC_FAMILIES` — names built with f-strings, like
     the gauge-family drop counter) — the registry shrinks when code
     does;
  3. the "family index" table in `docs/observability.md` between the
     markers below is GENERATED from the registry (`make metrics-docs`
     / ``--update-metrics-docs``) and a stale committed table is a
     finding — the same mechanism as `make env-docs`.

The per-series model-output gauges (``foremastbrain_<series>_upper``
etc.) are name-templated per job config and stay outside the registry
on purpose; `BrainGauges` builds them with f-strings, so rule 1 never
sees them.
"""

from __future__ import annotations

import ast
import os
import re

from foremast_tpu.analysis.core import Checker, Finding, Module

RULE = "metrics-contract"

DOCS_RELPATH = "docs/observability.md"
DOCS_BEGIN = "<!-- BEGIN METRIC FAMILIES (generated: make metrics-docs) -->"
DOCS_END = "<!-- END METRIC FAMILIES -->"

_CONSTRUCTORS = frozenset(
    {
        "Counter",
        "Gauge",
        "Histogram",
        "Summary",
        "Info",
        "Enum",
        "CounterMetricFamily",
        "GaugeMetricFamily",
        "HistogramMetricFamily",
        "SummaryMetricFamily",
        "counter",  # observe.spans.counter shared-family helper
    }
)

_FAMILY_RE = re.compile(r"^foremast(brain)?_[a-z0-9_]+$")

# families whose NAMES are built dynamically (f-strings) and therefore
# invisible to the literal scan — rule 2's explicit exemptions
DYNAMIC_FAMILIES = frozenset({"foremastbrain_gauge_families_dropped"})


def collected_name(name: str) -> str:
    """prometheus_client collects counters without the `_total` suffix."""
    return name[:-6] if name.endswith("_total") else name


def _registry():
    from foremast_tpu.observe import metrics_lint

    return metrics_lint.ALLOWED_LABELS, metrics_lint.FAMILY_DOCS


def scan_constructions(module: Module) -> list[tuple[str, int]]:
    """(literal family name, line) for every metric-constructor call —
    first positional arg or the `name=` keyword (both are legal
    prometheus_client spellings)."""
    out: list[tuple[str, int]] = []
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        name = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else None
        )
        if name not in _CONSTRUCTORS:
            continue
        first = node.args[0] if node.args else next(
            (kw.value for kw in node.keywords if kw.arg == "name"), None
        )
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            if _FAMILY_RE.match(first.value):
                out.append((first.value, node.lineno))
    return out


class MetricsContractChecker(Checker):
    rule = RULE
    description = (
        "every constructed foremast_* family must be registered in "
        "metrics_lint (ALLOWED_LABELS + FAMILY_DOCS)"
    )

    def __init__(self, registry=None, docs=None):
        self._reg = registry
        self._docs = docs

    def _load(self):
        if self._reg is None:
            self._reg, self._docs = _registry()
        return self._reg, self._docs

    def check(self, module: Module) -> list[Finding]:
        allowed, docs = self._load()
        findings: list[Finding] = []
        for name, line in scan_constructions(module):
            coll = collected_name(name)
            if coll not in allowed:
                findings.append(
                    module.finding(
                        self.rule,
                        line,
                        f"metric family {name!r} is constructed here but "
                        "not registered in metrics_lint.ALLOWED_LABELS — "
                        "its label set is unchecked and the docs table "
                        "cannot list it",
                        hint="add it to ALLOWED_LABELS (exact label set) "
                        "+ FAMILY_DOCS (one-line meaning) in "
                        "observe/metrics_lint.py, then `make metrics-docs`",
                    )
                )
            elif coll not in docs:
                findings.append(
                    module.finding(
                        self.rule,
                        line,
                        f"metric family {name!r} has no FAMILY_DOCS entry "
                        "— the generated observability table cannot "
                        "describe it",
                        hint="add a one-line meaning to FAMILY_DOCS in "
                        "observe/metrics_lint.py, then `make metrics-docs`",
                    )
                )
        return findings


# ---------------------------------------------------------------------------
# repo-level checks: registry coverage + generated docs table
# ---------------------------------------------------------------------------


def check_registry_coverage(modules) -> list[Finding]:
    """Rule 2: registry entries must be constructed (or declared
    dynamic) and FAMILY_DOCS must cover ALLOWED_LABELS exactly."""
    allowed, docs = _registry()
    constructed = set()
    for m in modules:
        if not m.relpath.startswith("foremast_tpu/"):
            continue
        for name, _ in scan_constructions(m):
            constructed.add(collected_name(name))
    lint_path = "foremast_tpu/observe/metrics_lint.py"
    findings = []
    for name in sorted(set(allowed) - constructed - DYNAMIC_FAMILIES):
        findings.append(
            Finding(
                rule=RULE,
                path=lint_path,
                line=1,
                message=f"registry entry {name!r} is never constructed in "
                "the package — dead registry weight (or a construction "
                "the literal scan cannot see)",
                hint="remove the entry, or add the family name to "
                "metrics_contract.DYNAMIC_FAMILIES if it is built "
                "dynamically",
            )
        )
    for name in sorted(set(allowed) ^ set(docs)):
        where = "ALLOWED_LABELS" if name in allowed else "FAMILY_DOCS"
        findings.append(
            Finding(
                rule=RULE,
                path=lint_path,
                line=1,
                message=f"family {name!r} appears in {where} only — "
                "ALLOWED_LABELS and FAMILY_DOCS must cover the same set",
                hint="keep the two dicts in observe/metrics_lint.py "
                "key-for-key identical",
            )
        )
    return findings


def render_family_table() -> str:
    """The generated family-index block for docs/observability.md."""
    allowed, docs = _registry()
    lines = [
        DOCS_BEGIN,
        "",
        "| Family (as collected) | Labels | Meaning |",
        "|---|---|---|",
    ]
    for name in sorted(allowed):
        labels = ", ".join(f"`{lb}`" for lb in sorted(allowed[name])) or "—"
        doc = docs.get(name, "").replace("|", "\\|")
        lines.append(f"| `{name}` | {labels} | {doc} |")
    lines.append("")
    lines.append(
        "Counters are listed as collected (without their `_total` "
        "suffix). This table is generated from "
        "`observe/metrics_lint.py`'s registry — edit "
        "`ALLOWED_LABELS`/`FAMILY_DOCS`, then run `make metrics-docs`. "
        "`make check` fails when the table, the registry, and the "
        "constructor calls in code drift (rule `metrics-contract`)."
    )
    lines.append(DOCS_END)
    return "\n".join(lines)


def _split_docs(text: str) -> tuple[str, str, str] | None:
    try:
        head, rest = text.split(DOCS_BEGIN, 1)
        _, tail = rest.split(DOCS_END, 1)
    except ValueError:
        return None
    return head, text[len(head): len(text) - len(tail)], tail


def check_metrics_docs(root: str) -> list[Finding]:
    path = os.path.join(root, DOCS_RELPATH)
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        text = f.read()
    parts = _split_docs(text)
    hint = (
        "run `make metrics-docs` (or python -m foremast_tpu.analysis "
        "--update-metrics-docs)"
    )
    if parts is None:
        return [
            Finding(
                rule=RULE,
                path=DOCS_RELPATH,
                line=1,
                message="METRIC FAMILIES markers missing from "
                "observability docs",
                hint=hint,
            )
        ]
    if parts[1] != render_family_table():
        return [
            Finding(
                rule=RULE,
                path=DOCS_RELPATH,
                line=text[: text.index(DOCS_BEGIN)].count("\n") + 1,
                message="generated metric-family table is stale vs the "
                "metrics_lint registry",
                hint=hint,
            )
        ]
    return []


def update_metrics_docs(root: str) -> bool:
    path = os.path.join(root, DOCS_RELPATH)
    with open(path, encoding="utf-8") as f:
        text = f.read()
    parts = _split_docs(text)
    if parts is None:
        raise SystemExit(
            f"{DOCS_RELPATH}: METRIC FAMILIES markers not found; add\n"
            f"{DOCS_BEGIN}\n{DOCS_END}\nwhere the table belongs"
        )
    head, old, tail = parts
    new = render_family_table()
    if old == new:
        return False
    with open(path, "w", encoding="utf-8") as f:
        f.write(head + new + tail)
    return True
