"""jit-hygiene: host syncs and retrace hazards inside jitted scoring code.

The engine's throughput contract (CONTRIBUTING.md) is that everything
under `jit` stays fixed-shape and device-resident: ragged data becomes
masks, config becomes gathered operand vectors, branches become selects.
The failure modes this checker encodes are the ones that silently tax
the hot path instead of crashing:

  * ``.item()`` / ``float()`` / ``bool()`` on a traced value — a
    blocking device->host sync per call (or a ConcretizationTypeError at
    trace time for the builtins);
  * ``np.asarray``/``np.array`` of a traced value — host materialization
    inside the program, which breaks tracing or forces a transfer;
  * Python ``if``/``while`` on a traced value — either a trace-time
    error or, where it happens to concretize, a retrace per distinct
    value;
  * unhashable defaults (list/dict/set) on ``static_argnames`` params —
    every call site raises or, worse, retraces.

Scope and precision: jit ROOTS are functions carrying a ``jax.jit``
decorator (including ``partial(jax.jit, ...)``) or bound by a
``name = jax.jit(fn)`` assignment; their traced set is parameters minus
``static_argnames``/``static_argnums``. Tracedness then propagates
INTERPROCEDURALLY through intra-module call sites (a helper's parameter
is traced iff some jit-reachable caller passes it a traced expression)
and INTRAPROCEDURALLY through local assignments (``t = x.astype(...)``
taints ``t``), to a fixpoint. This is what lets the checker flag
``float(level)`` in a shared helper while staying silent on
``float(t_scale)`` where every caller passes a Python scalar.

Idioms never flagged (static under tracing): ``x is None`` branching,
``len(x)``, ``isinstance``, and ``.shape``/``.ndim``/``.dtype``/
``.size`` access.
"""

from __future__ import annotations

import ast
from typing import Iterable

from foremast_tpu.analysis.core import Checker, Finding, Module

_NP_NAMES = frozenset({"np", "numpy"})
_NP_MATERIALIZERS = frozenset(
    {"asarray", "array", "asanyarray", "ascontiguousarray"}
)
_SYNC_BUILTINS = frozenset({"float", "bool"})
_STATIC_ATTRS = frozenset({"shape", "ndim", "dtype", "size"})
_UNHASHABLE_DEFAULTS = (
    ast.List,
    ast.Dict,
    ast.Set,
    ast.ListComp,
    ast.DictComp,
    ast.SetComp,
)


def _dotted(node: ast.AST) -> str | None:
    """`a.b.c` -> "a.b.c" for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jax_jit(node: ast.AST) -> bool:
    return _dotted(node) in ("jax.jit", "jit")


def _const_str_seq(node: ast.AST, consts: dict[str, ast.AST]) -> list[str] | None:
    """Resolve a tuple/list of string constants, following one level of
    module-constant indirection (the `_STATIC = (...)` pattern)."""
    if isinstance(node, ast.Name) and node.id in consts:
        node = consts[node.id]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.append(elt.value)
            else:
                return None
        return out
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    return None


def _const_int_seq(node: ast.AST, consts: dict[str, ast.AST]) -> list[int] | None:
    if isinstance(node, ast.Name) and node.id in consts:
        node = consts[node.id]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
            else:
                return None
        return out
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    return None


def _jit_call_statics(
    call: ast.Call, consts: dict[str, ast.AST]
) -> tuple[list[str], list[int]] | None:
    """(static_argnames, static_argnums) if `call` wraps jax.jit:
    `jax.jit(...)` or `[functools.]partial(jax.jit, ...)`; else None."""
    is_partial = _dotted(call.func) in ("partial", "functools.partial")
    if is_partial:
        if not (call.args and _is_jax_jit(call.args[0])):
            return None
    elif not _is_jax_jit(call.func):
        return None
    names: list[str] = []
    nums: list[int] = []
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            names = _const_str_seq(kw.value, consts) or []
        elif kw.arg == "static_argnums":
            nums = _const_int_seq(kw.value, consts) or []
    return names, nums


def _param_names(fn: ast.FunctionDef) -> list[str]:
    a = fn.args
    return [p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)]


def _defaults_by_param(fn: ast.FunctionDef) -> dict[str, ast.AST]:
    a = fn.args
    positional = [*a.posonlyargs, *a.args]
    out: dict[str, ast.AST] = {}
    for param, default in zip(
        positional[len(positional) - len(a.defaults):], a.defaults
    ):
        out[param.arg] = default
    for param, default in zip(a.kwonlyargs, a.kw_defaults):
        if default is not None:
            out[param.arg] = default
    return out


class _FnInfo:
    def __init__(self, node: ast.FunctionDef):
        self.node = node
        self.name = node.name
        self.is_root = False
        self.static: set[str] = set()
        self.traced_params: set[str] = set()
        self.call_sites: list[tuple[str, ast.Call]] = []  # (callee, node)


class JitHygieneChecker(Checker):
    rule = "jit-hygiene"
    description = (
        "host syncs, traced-value branching, and unhashable static args "
        "inside jax.jit-reachable functions"
    )

    PATH_PREFIXES = (
        "foremast_tpu/engine/",
        "foremast_tpu/models/",
        "foremast_tpu/ops/",
    )

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith(self.PATH_PREFIXES)

    # -- module scan -----------------------------------------------------

    def check(self, module: Module) -> list[Finding]:
        consts = self._module_consts(module.tree)
        fns = self._collect_functions(module.tree)
        self._mark_roots(module.tree, fns, consts)
        reachable = self._propagate_tracedness(fns)
        findings: list[Finding] = []
        for info in reachable:
            findings.extend(self._check_function(module, info))
        return findings

    @staticmethod
    def _module_consts(tree: ast.Module) -> dict[str, ast.AST]:
        consts: dict[str, ast.AST] = {}
        for stmt in tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                t = stmt.targets[0]
                if isinstance(t, ast.Name):
                    consts[t.id] = stmt.value
        return consts

    @staticmethod
    def _collect_functions(tree: ast.Module) -> dict[str, _FnInfo]:
        fns: dict[str, _FnInfo] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # first definition wins on name collisions (same-named
                # methods across classes share an entry; over-connecting
                # the call graph only widens coverage)
                fns.setdefault(node.name, _FnInfo(node))
        for info in fns.values():
            for sub in ast.walk(info.node):
                if isinstance(sub, ast.Call):
                    callee = None
                    if isinstance(sub.func, ast.Name):
                        callee = sub.func.id
                    elif isinstance(sub.func, ast.Attribute):
                        callee = sub.func.attr
                    if callee and callee in fns and callee != info.name:
                        info.call_sites.append((callee, sub))
        return fns

    def _mark_roots(
        self,
        tree: ast.Module,
        fns: dict[str, _FnInfo],
        consts: dict[str, ast.AST],
    ) -> None:
        def apply_statics(info: _FnInfo, names: list[str], nums: list[int]):
            params = _param_names(info.node)
            info.is_root = True
            info.static.update(names)
            info.static.update(params[i] for i in nums if i < len(params))

        for info in fns.values():
            for deco in info.node.decorator_list:
                if _is_jax_jit(deco):
                    info.is_root = True
                elif isinstance(deco, ast.Call):
                    statics = _jit_call_statics(deco, consts)
                    if statics is not None:
                        apply_statics(info, *statics)
        # assignment form: `scored = jax.jit(fn)` / `partial(jax.jit, ..)(fn)`
        for stmt in ast.walk(tree):
            if not isinstance(stmt, ast.Assign):
                continue
            call = stmt.value
            if not (isinstance(call, ast.Call) and call.args):
                continue
            target_fn = call.args[0]
            if not (isinstance(target_fn, ast.Name) and target_fn.id in fns):
                continue
            statics = None
            if _is_jax_jit(call.func):
                names = []
                nums = []
                for kw in call.keywords:
                    if kw.arg == "static_argnames":
                        names = _const_str_seq(kw.value, consts) or []
                    elif kw.arg == "static_argnums":
                        nums = _const_int_seq(kw.value, consts) or []
                statics = (names, nums)
            elif isinstance(call.func, ast.Call):
                statics = _jit_call_statics(call.func, consts)
            if statics is not None:
                apply_statics(fns[target_fn.id], *statics)

    # -- tracedness ------------------------------------------------------

    def _propagate_tracedness(self, fns: dict[str, _FnInfo]) -> list[_FnInfo]:
        """Fixpoint: roots' traced params flow through call-site argument
        positions into callee params. Returns the jit-reachable set."""
        for info in fns.values():
            if info.is_root:
                info.traced_params = (
                    set(_param_names(info.node)) - info.static - {"self", "cls"}
                )
        # reachability first (call graph is static)
        frontier = [i for i in fns.values() if i.is_root]
        reach = {i.name for i in frontier}
        order = list(frontier)
        while frontier:
            info = frontier.pop()
            for callee, _ in info.call_sites:
                if callee not in reach:
                    reach.add(callee)
                    frontier.append(fns[callee])
                    order.append(fns[callee])
        changed = True
        while changed:
            changed = False
            for info in order:
                tainted = self._tainted_names(info)
                for callee, call in info.call_sites:
                    target = fns[callee]
                    params = [
                        p
                        for p in _param_names(target.node)
                        if p not in ("self", "cls")
                    ]
                    mapped: list[tuple[str, ast.AST]] = []
                    for i, arg in enumerate(call.args):
                        if i < len(params):
                            mapped.append((params[i], arg))
                    for kw in call.keywords:
                        if kw.arg in params:
                            mapped.append((kw.arg, kw.value))
                    for pname, arg in mapped:
                        if pname not in target.traced_params and self._references(
                            arg, tainted
                        ):
                            target.traced_params.add(pname)
                            changed = True
        return order

    def _tainted_names(self, info: _FnInfo) -> set[str]:
        """Traced params plus locals assigned from traced expressions
        (its own fixpoint — assignment order in source need not match
        dataflow order)."""
        tainted = set(info.traced_params)
        changed = True
        while changed:
            changed = False
            for node in ast.walk(info.node):
                value = None
                targets: list[ast.AST] = []
                if isinstance(node, ast.Assign):
                    value, targets = node.value, node.targets
                elif isinstance(node, ast.AugAssign):
                    value, targets = node.value, [node.target]
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    value, targets = node.value, [node.target]
                elif isinstance(node, ast.For):
                    value, targets = node.iter, [node.target]
                if value is None or not self._references(value, tainted):
                    continue
                for target in targets:
                    for sub in ast.walk(target):
                        if (
                            isinstance(sub, ast.Name)
                            and sub.id not in tainted
                        ):
                            tainted.add(sub.id)
                            changed = True
        return tainted

    @staticmethod
    def _static_exempt_ids(node: ast.AST) -> set[int]:
        """AST node ids inside `node` that only touch STATIC facts about
        traced values — `x.shape/ndim/dtype/size`, `len(x)`,
        `isinstance(...)`, `x is (not) None` — and therefore must not
        propagate or trigger taint (`b, t = values.shape` is a Python
        int under tracing, not a traced scalar)."""
        exempt: set[int] = set()
        for n in ast.walk(node):
            if isinstance(n, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in n.ops
            ):
                for sub in ast.walk(n):
                    exempt.add(id(sub))
            elif (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Name)
                and n.func.id in ("len", "isinstance")
            ):
                for sub in ast.walk(n):
                    exempt.add(id(sub))
            elif isinstance(n, ast.Attribute) and n.attr in _STATIC_ATTRS:
                for sub in ast.walk(n):
                    exempt.add(id(sub))
        return exempt

    @classmethod
    def _references(cls, node: ast.AST, names: set[str]) -> bool:
        """True when `node` references a tainted name OUTSIDE the static
        idioms (shape/len/is-None/...)."""
        exempt = cls._static_exempt_ids(node)
        return any(
            isinstance(n, ast.Name) and n.id in names and id(n) not in exempt
            for n in ast.walk(node)
        )

    # -- per-function checks ---------------------------------------------

    def _check_function(self, module: Module, info: _FnInfo) -> Iterable[Finding]:
        fn = info.node
        tainted = self._tainted_names(info)
        findings: list[Finding] = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                findings.extend(self._check_call(module, fn, node, tainted))
            elif isinstance(node, (ast.If, ast.While)):
                name = self._traced_branch_name(node.test, tainted)
                if name is not None:
                    findings.append(
                        module.finding(
                            self.rule,
                            node,
                            f"jit-reachable `{fn.name}` branches in Python "
                            f"on traced value `{name}`",
                            hint=(
                                "use jnp.where/lax.cond, or declare the "
                                "argument in static_argnames if it is "
                                "genuinely compile-time"
                            ),
                        )
                    )
        if info.is_root:
            findings.extend(self._check_static_defaults(module, fn, info))
        return findings

    def _check_call(
        self,
        module: Module,
        fn: ast.FunctionDef,
        node: ast.Call,
        tainted: set[str],
    ) -> Iterable[Finding]:
        out: list[Finding] = []
        func = node.func
        if (
            isinstance(func, ast.Attribute)
            and func.attr == "item"
            and not node.args
            and self._references(func.value, tainted)
        ):
            out.append(
                module.finding(
                    self.rule,
                    node,
                    f"`.item()` on traced value inside jit-reachable "
                    f"`{fn.name}` forces a blocking device->host sync",
                    hint="keep the value on device; fetch once per batch "
                    "with jax.device_get after the program returns",
                )
            )
            return out
        dotted = _dotted(func)
        if (
            dotted
            and "." in dotted
            and dotted.split(".", 1)[0] in _NP_NAMES
            and dotted.rsplit(".", 1)[1] in _NP_MATERIALIZERS
            and node.args
            and self._references(node.args[0], tainted)
        ):
            out.append(
                module.finding(
                    self.rule,
                    node,
                    f"`{dotted}` materializes traced value on host inside "
                    f"jit-reachable `{fn.name}`",
                    hint="use jnp.* inside jitted code; np.* belongs on the "
                    "host side of the batch boundary",
                )
            )
        elif (
            isinstance(func, ast.Name)
            and func.id in _SYNC_BUILTINS
            and node.args
            and self._references(node.args[0], tainted)
        ):
            out.append(
                module.finding(
                    self.rule,
                    node,
                    f"`{func.id}()` on traced value inside jit-reachable "
                    f"`{fn.name}` concretizes (sync or trace error)",
                    hint="keep it a jax scalar, or hoist the conversion to "
                    "the caller outside jit",
                )
            )
        return out

    def _traced_branch_name(
        self, test: ast.AST, tainted: set[str]
    ) -> str | None:
        """First traced name the branch condition concretizes, or None.

        Exempt idioms (static under tracing): `x is (not) None`,
        `len(x)`, `isinstance(...)`, and `x.shape/ndim/dtype/size` —
        these shape program structure, not runtime values."""
        exempt = self._static_exempt_ids(test)
        for n in ast.walk(test):
            if (
                isinstance(n, ast.Name)
                and n.id in tainted
                and id(n) not in exempt
            ):
                return n.id
        return None

    def _check_static_defaults(
        self, module: Module, fn: ast.FunctionDef, info: _FnInfo
    ) -> Iterable[Finding]:
        defaults = _defaults_by_param(fn)
        for name in sorted(info.static):
            default = defaults.get(name)
            if default is not None and isinstance(default, _UNHASHABLE_DEFAULTS):
                yield module.finding(
                    self.rule,
                    default,
                    f"static arg `{name}` of jitted `{fn.name}` defaults to "
                    "an unhashable value",
                    hint="static args key the compile cache; use a tuple / "
                    "frozenset / hashable sentinel",
                )
