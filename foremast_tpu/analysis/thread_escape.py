"""thread-escape: lock-guarded state mutated from code a thread can reach.

PR-2's lock-discipline rule is single-class: it infers a class's
guarded attributes and flags unlocked access WITHIN that class. The
threaded data plane broke out of that box — state escapes through hook
attributes (``store.journal``), through callables handed to thread
pools and ``threading.Thread(target=...)``, through HTTP handler
classes, and through collectors registered on a Prometheus registry
that scrape threads walk. This rule follows the state across modules:

  1. THREAD ROOTS are collected package-wide: ``Thread(target=f)``
     targets, ``pool.submit(f, ...)`` submissions, ``do_GET``/
     ``do_POST``-style methods of ``*RequestHandler`` subclasses,
     ``collect`` methods of classes registered via ``.register(...)``,
     and every function reference recorded in the callback table
     (journal hooks, claim filters — they run on whatever thread
     invokes the hook).
  2. Everything REACHABLE from those roots through the resolver is the
     escaped surface.
  3. For every lock-owning class, the GUARDED map records which lock
     each attribute is mutated under, program-wide (the owning class's
     methods plus typed cross-class writes).

Findings:

  * **escaped mutation** — a guarded attribute mutated through a typed
    receiver OUTSIDE its owning class without holding the guarding
    lock, when the mutation site (or any method of the owning class)
    is thread-reachable;
  * **mixed guard** — one attribute mutated under TWO different locks
    in different places: each critical section is atomic only against
    itself, so the two sides race exactly as if unlocked (the
    Tracer ``_last_flush`` bug this rule was built on).
"""

from __future__ import annotations

import ast

from foremast_tpu.analysis.core import Finding
from foremast_tpu.analysis.interproc import (
    FunctionInfo,
    Program,
    mutated_attr,
)

RULE = "thread-escape"

_HANDLER_METHODS = frozenset(
    {"do_GET", "do_POST", "do_PUT", "do_DELETE", "do_HEAD", "handle"}
)


# ---------------------------------------------------------------------------
# roots + reachability
# ---------------------------------------------------------------------------


def thread_roots(program: Program) -> set:
    roots: set = set()
    # callback-table targets: hooks run on the registering thread's
    # peers (receiver handlers calling the journal, claims calling the
    # mesh filter)
    for targets in program.callbacks.values():
        roots.update(targets)
    for fn in program.functions:
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            callee = node.func
            # Thread(target=f) / Thread(..., target=f)
            if (
                isinstance(callee, ast.Name)
                and callee.id == "Thread"
                or isinstance(callee, ast.Attribute)
                and callee.attr == "Thread"
            ):
                for kw in node.keywords:
                    if kw.arg == "target":
                        roots.update(program._ref_targets(kw.value, fn))
            # pool.submit(f, ...)
            elif isinstance(callee, ast.Attribute) and callee.attr == "submit":
                if node.args:
                    roots.update(program._ref_targets(node.args[0], fn))
            # registry.register(Collector(...)) — the collector's
            # collect() runs on scrape-handler threads
            elif isinstance(callee, ast.Attribute) and callee.attr == "register":
                for arg in node.args:
                    if isinstance(arg, ast.Call):
                        ckey = program._value_class(arg, fn)
                        if ckey is not None:
                            m = program._lookup_method(ckey, "collect")
                            if m is not None:
                                roots.add(m)
    # HTTP handler classes: request threads enter through do_*
    for cls in program.classes.values():
        if any(b and b.endswith("RequestHandler") for b in cls.bases):
            for name, m in cls.methods.items():
                if name in _HANDLER_METHODS:
                    roots.add(m)
    return roots


def reachable_functions(program: Program, roots: set) -> set:
    seen = set(roots)
    frontier = list(roots)
    while frontier:
        fn = frontier.pop()
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Call):
                for callee in program.resolve_call(node, fn):
                    if callee not in seen:
                        seen.add(callee)
                        frontier.append(callee)
    return seen


# ---------------------------------------------------------------------------
# guarded map
# ---------------------------------------------------------------------------


def guarded_map(program: Program) -> dict:
    """class key -> attr -> list of (held-lock-name frozenset, site):
    one entry per LOCKED mutation site of the attribute, package-wide.
    An attribute is consistently guarded when some single lock is held
    at EVERY locked mutation site (`guard_locks` — the intersection);
    two sites with disjoint held sets are a mixed guard."""
    out: dict[str, dict[str, list]] = {}

    for fn in program.functions:
        if fn.name == "__init__":
            continue  # construction happens-before sharing

        def visit_mut(ckey, attr, held, node, fn=fn):
            cls = program.classes.get(ckey)
            if cls is None or attr in cls.lock_attrs:
                return
            # only the OWNER's locks are guard evidence: a foreign
            # class mutating b.attr under its own unrelated lock must
            # not teach us that attr is "guarded" by it
            names = frozenset(
                lk.name
                for lk in held
                if fn.class_key == ckey or lk.name.split(".")[0] == cls.name
            )
            if names:
                out.setdefault(ckey, {}).setdefault(attr, []).append(
                    (names, fn.site(node))
                )

        _walk_mutations(program, fn, visit_mut)
    return out


def guard_locks(sites: list) -> frozenset:
    """The lock(s) held at every locked mutation site (empty = mixed)."""
    locks = sites[0][0]
    for names, _ in sites[1:]:
        locks &= names
    return locks


def _walk_mutations(program: Program, fn: FunctionInfo, visit_mut) -> None:
    """Call visit_mut(owner_class_key, attr, held_locks, node) for every
    attribute mutation in `fn` whose receiver's class resolves — over
    the shared pruned traversal, so a mutation inside a nested def
    (a thread target defined in a locked region) is never credited
    with the definition site's locks."""
    from foremast_tpu.analysis.interproc import locked_walk

    for node, held, acquired in locked_walk(program, fn):
        if acquired is not None:
            continue
        attr, recv = mutated_attr(node)
        if attr is None or recv is None:
            continue
        ckey = program.receiver_class(recv, fn)
        if ckey is not None:
            visit_mut(ckey, attr, held, node)


# ---------------------------------------------------------------------------
# the rule
# ---------------------------------------------------------------------------


def check_thread_escape(program: Program) -> list[Finding]:
    roots = thread_roots(program)
    reachable = reachable_functions(program, roots)
    guards = guarded_map(program)
    findings: list[Finding] = []

    # mixed guard: no single lock common to every locked mutation site
    for ckey, attrs in sorted(guards.items()):
        cls = program.classes[ckey]
        for attr, sites in sorted(attrs.items()):
            if guard_locks(sites):
                continue
            uniq = sorted({(tuple(sorted(n)), s) for n, s in sites})[:4]
            detail = ", ".join(
                f"{'+'.join(names)} at {site}" for names, site in uniq
            )
            first_site = sorted(s for _, s in sites)[0]
            path, _, line = first_site.partition(":")
            findings.append(
                Finding(
                    rule=RULE,
                    path=path,
                    line=int(line or 1),
                    message=f"`{cls.name}.{attr}` is mutated under "
                    f"DIFFERENT locks ({detail}) — the critical sections "
                    "do not exclude each other, so the writes race as if "
                    "unlocked",
                    hint="pick ONE lock for the attribute and hold it at "
                    "every mutation site",
                )
            )

    # escaped mutation: guarded attr written cross-class without the lock
    reachable_classes = {
        fn.class_key for fn in reachable if fn.class_key is not None
    }
    for fn in program.functions:
        if fn.name == "__init__":
            continue

        def check_mut(ckey, attr, held, node, fn=fn):
            if ckey == fn.class_key:
                return  # same-class discipline is lock-discipline's rule
            sites = guards.get(ckey, {}).get(attr)
            if not sites:
                return  # unguarded attribute
            common = guard_locks(sites)
            if not common:
                return  # already a mixed-guard finding
            lock_name = sorted(common)[0]
            if any(lk.name in common for lk in held):
                return
            if fn not in reachable and ckey not in reachable_classes:
                return  # nothing threaded ever reaches this state
            cls = program.classes[ckey]
            findings.append(
                Finding(
                    rule=RULE,
                    path=fn.module.relpath,
                    line=getattr(node, "lineno", fn.node.lineno),
                    message=f"`{cls.name}.{attr}` is guarded by "
                    f"{lock_name} but mutated here (in `{fn.qualname}`) "
                    "without it — thread-reachable state escaped its "
                    "lock",
                    hint=f"mutate through a {cls.name} method that takes "
                    "the lock, or mark a deliberate exception with "
                    "`# foremast: ignore[thread-escape]` and say why",
                )
            )

        _walk_mutations(program, fn, check_mut)
    return sorted(set(findings), key=Finding.sort_key)
