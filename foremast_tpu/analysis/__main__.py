"""The gated runner: `python -m foremast_tpu.analysis` (== `make check`).

Exit codes: 0 clean (modulo the committed baseline), 1 findings, 2 bad
usage. Folds in the metric naming lint (observe/metrics_lint.py) so ONE
command gates every machine-checked contract; `--write-baseline`
snapshots today's findings as grandfathered debt (the committed
`analysis_baseline.json` should only ever shrink).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from foremast_tpu.analysis import all_checkers
from foremast_tpu.analysis.core import (
    Baseline,
    Finding,
    analyze_modules,
    collect_modules,
    repo_root,
)
from foremast_tpu.analysis.env_contract import check_env_docs, update_env_docs

BASELINE_NAME = "analysis_baseline.json"


def metrics_lint_findings() -> list[Finding]:
    """The pre-existing metric naming lint as findings under rule
    `metrics-lint` — same gate, same reporting."""
    from foremast_tpu.observe import metrics_lint

    problems = metrics_lint.lint_registry(
        metrics_lint.default_registry_families()
    )
    return [
        Finding(
            rule="metrics-lint",
            path="foremast_tpu/observe/metrics_lint.py",
            line=1,
            message=p,
            hint="metric families must match the dashboard contract "
            "(docs/observability.md)",
        )
        for p in problems
    ]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m foremast_tpu.analysis",
        description="foremast-check: jit-hygiene, async-blocking, "
        "lock-discipline, env-contract, metrics-lint",
    )
    p.add_argument(
        "paths",
        nargs="*",
        help="files/directories to scan (default: the foremast_tpu package)",
    )
    p.add_argument("--json", action="store_true", help="machine-readable output")
    p.add_argument(
        "--baseline",
        default=None,
        help=f"baseline file (default: <repo>/{BASELINE_NAME})",
    )
    p.add_argument(
        "--write-baseline",
        action="store_true",
        help="snapshot current findings as the new baseline and exit 0",
    )
    p.add_argument(
        "--no-metrics-lint",
        action="store_true",
        help="skip the metric naming lint fold-in",
    )
    p.add_argument(
        "--update-env-docs",
        action="store_true",
        help="regenerate the env-knob table in docs/operations.md and exit",
    )
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    root = repo_root()
    if args.update_env_docs:
        changed = update_env_docs(root)
        print(
            "env docs regenerated"
            if changed
            else "env docs already in sync"
        )
        return 0

    modules = collect_modules(root, args.paths or None)
    findings = analyze_modules(modules, all_checkers())
    if not args.paths:
        # repo-level contracts only make sense on the default full scan
        findings.extend(check_env_docs(root))
        if not args.no_metrics_lint:
            findings.extend(metrics_lint_findings())
    findings.sort(key=Finding.sort_key)

    baseline_path = args.baseline or os.path.join(root, BASELINE_NAME)
    if args.write_baseline:
        Baseline.from_findings(findings).save(baseline_path)
        print(
            f"wrote {len(findings)} grandfathered finding(s) to "
            f"{os.path.relpath(baseline_path, root)}"
        )
        return 0

    baseline = Baseline.load(baseline_path)
    new, grandfathered = baseline.split(findings)
    stale = baseline.stale(findings)

    if args.json:
        json.dump(
            {
                "findings": [f.to_json() for f in new],
                "grandfathered": [f.to_json() for f in grandfathered],
                "stale_baseline": stale,
            },
            sys.stdout,
            indent=2,
        )
        print()
    else:
        for f in new:
            print(f.render())
        if grandfathered:
            print(
                f"foremast-check: {len(grandfathered)} grandfathered "
                f"finding(s) suppressed by {BASELINE_NAME}"
            )
        for e in stale:
            print(
                "foremast-check: stale baseline entry (debt paid — remove "
                f"it): [{e['rule']}] {e['path']}: {e['message']}"
            )
        if new:
            print(
                f"foremast-check: {len(new)} new finding(s); fix, suppress "
                "with `# foremast: ignore[rule]` + justification, or (last "
                "resort) re-baseline — docs/static-analysis.md"
            )
        else:
            print("foremast-check: clean")
    return 1 if new else 0


if __name__ == "__main__":
    raise SystemExit(main())
