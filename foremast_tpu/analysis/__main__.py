"""The gated runner: `python -m foremast_tpu.analysis` (== `make check`).

Exit codes: 0 clean (modulo the committed baseline), 1 findings, 2 bad
usage. Folds in the metric naming lint (observe/metrics_lint.py) so ONE
command gates every machine-checked contract; `--write-baseline`
snapshots today's findings as grandfathered debt (the committed
`analysis_baseline.json` should only ever shrink).

The default (no-paths) run is the full gate: per-module rules over the
package + benchmarks/ + tests/, the whole-program concurrency rules
(lock-order / thread-escape / blocking-under-lock — they need the
complete package, so explicit path runs skip them), and the three
generated-artifact contracts (env table, metric-family table, lock
graph). `--sarif` emits SARIF 2.1.0 for code-review UIs; `make ci`
chains this gate with the fast tier-1 tests.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from foremast_tpu.analysis import all_checkers
from foremast_tpu.analysis.core import (
    Baseline,
    Finding,
    analyze_modules,
    collect_modules,
    repo_root,
)
from foremast_tpu.analysis.env_contract import check_env_docs, update_env_docs

BASELINE_NAME = "analysis_baseline.json"


def metrics_lint_findings() -> list[Finding]:
    """The pre-existing metric naming lint as findings under rule
    `metrics-lint` — same gate, same reporting."""
    from foremast_tpu.observe import metrics_lint

    problems = metrics_lint.lint_registry(
        metrics_lint.default_registry_families()
    )
    return [
        Finding(
            rule="metrics-lint",
            path="foremast_tpu/observe/metrics_lint.py",
            line=1,
            message=p,
            hint="metric families must match the dashboard contract "
            "(docs/observability.md)",
        )
        for p in problems
    ]


def program_findings(root: str, modules) -> list[Finding]:
    """The whole-program rules (full-scan only): the concurrency set
    (static lock graph + cycle/staleness gate, thread-escape,
    blocking-under-lock) and the v3 device/state set (device-flow,
    recompile-hazard, sharding-contract, status-machine + statusgraph
    drift gate), with per-line suppressions applied."""
    from foremast_tpu.analysis.blocking_under_lock import (
        apply_suppressions,
        check_blocking_under_lock,
    )
    from foremast_tpu.analysis.device_flow import check_device_flow
    from foremast_tpu.analysis.interproc import Program
    from foremast_tpu.analysis.lock_order import check_lock_order
    from foremast_tpu.analysis.recompile_hazard import check_recompile_hazard
    from foremast_tpu.analysis.sharding_contract import check_sharding_contract
    from foremast_tpu.analysis.status_machine import check_status_machine
    from foremast_tpu.analysis.thread_escape import check_thread_escape

    pkg = [m for m in modules if m.relpath.startswith("foremast_tpu/")]
    program = Program(pkg)
    findings = (
        check_lock_order(root, program)
        + check_thread_escape(program)
        + check_blocking_under_lock(program)
        + check_device_flow(program)
        + check_recompile_hazard(program)
        + check_sharding_contract(program)
        + check_status_machine(root, program)
    )
    return apply_suppressions(findings, pkg)


def to_sarif(new: list[Finding], grandfathered: list[Finding]) -> dict:
    """SARIF 2.1.0: new findings as error-level results, grandfathered
    ones carried with an `accepted` suppression so viewers can show
    (but not gate on) the known debt."""
    rules = sorted({f.rule for f in [*new, *grandfathered]})

    def result(f: Finding, suppressed: bool) -> dict:
        out = {
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message + (f" — {f.hint}" if f.hint else "")},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {
                            "uri": f.path,
                            "uriBaseId": "SRCROOT",
                        },
                        "region": {"startLine": max(1, f.line)},
                    }
                }
            ],
            "fingerprints": {"foremastCheck/v1": f.fingerprint()},
        }
        if suppressed:
            out["suppressions"] = [
                {
                    "kind": "external",
                    "status": "accepted",
                    "justification": f"grandfathered in {BASELINE_NAME}",
                }
            ]
        return out

    return {
        "$schema": (
            "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
            "master/Schemata/sarif-schema-2.1.0.json"
        ),
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "foremast-check",
                        "informationUri": "docs/static-analysis.md",
                        "rules": [{"id": r} for r in rules],
                    }
                },
                "originalUriBaseIds": {"SRCROOT": {"uri": "file:///"}},
                "results": [result(f, False) for f in new]
                + [result(f, True) for f in grandfathered],
            }
        ],
    }


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m foremast_tpu.analysis",
        description="foremast-check: jit-hygiene, async-blocking, "
        "lock-discipline, env-contract, metrics-contract, lock-order, "
        "thread-escape, blocking-under-lock, device-flow, "
        "recompile-hazard, sharding-contract, status-machine, "
        "metrics-lint",
    )
    p.add_argument(
        "paths",
        nargs="*",
        help="files/directories to scan (default: foremast_tpu + "
        "benchmarks + tests; whole-program rules need the default scan)",
    )
    p.add_argument("--json", action="store_true", help="machine-readable output")
    p.add_argument(
        "--sarif",
        action="store_true",
        help="emit SARIF 2.1.0 on stdout (new findings as results, "
        "baselined ones as accepted suppressions)",
    )
    p.add_argument(
        "--baseline",
        default=None,
        help=f"baseline file (default: <repo>/{BASELINE_NAME})",
    )
    p.add_argument(
        "--write-baseline",
        action="store_true",
        help="snapshot current findings as the new baseline and exit 0",
    )
    p.add_argument(
        "--no-metrics-lint",
        action="store_true",
        help="skip the metric naming lint fold-in",
    )
    p.add_argument(
        "--update-env-docs",
        action="store_true",
        help="regenerate the env-knob table in docs/operations.md and exit",
    )
    p.add_argument(
        "--update-metrics-docs",
        action="store_true",
        help="regenerate the metric-family table in docs/observability.md "
        "and exit",
    )
    p.add_argument(
        "--write-lockgraph",
        action="store_true",
        help="recompute the static lock-acquisition graph, write "
        "analysis_lockgraph.json, and exit",
    )
    p.add_argument(
        "--write-statusgraph",
        action="store_true",
        help="recompute the doc status transition graph, write "
        "analysis_statusgraph.json, and exit",
    )
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    root = repo_root()
    if args.update_env_docs:
        changed = update_env_docs(root)
        print(
            "env docs regenerated"
            if changed
            else "env docs already in sync"
        )
        return 0
    if args.update_metrics_docs:
        from foremast_tpu.analysis.metrics_contract import update_metrics_docs

        changed = update_metrics_docs(root)
        print(
            "metric-family docs regenerated"
            if changed
            else "metric-family docs already in sync"
        )
        return 0
    if args.write_lockgraph:
        from foremast_tpu.analysis.interproc import Program
        from foremast_tpu.analysis.lock_order import (
            GRAPH_NAME,
            build_graph,
            write_graph,
        )

        pkg = [
            m
            for m in collect_modules(root)
            if m.relpath.startswith("foremast_tpu/")
        ]
        graph = build_graph(Program(pkg))
        write_graph(root, graph)
        print(
            f"wrote {GRAPH_NAME}: {len(graph['nodes'])} lock(s), "
            f"{len(graph['edges'])} edge(s)"
        )
        return 0
    if args.write_statusgraph:
        from foremast_tpu.analysis.interproc import Program
        from foremast_tpu.analysis.status_machine import (
            GRAPH_NAME as STATUS_GRAPH,
            build_graph as build_status_graph,
            write_graph as write_status_graph,
        )

        pkg = [
            m
            for m in collect_modules(root)
            if m.relpath.startswith("foremast_tpu/")
        ]
        graph = build_status_graph(Program(pkg))
        if graph is None:
            print("no status registry found (jobs/models.py)", file=sys.stderr)
            return 2
        write_status_graph(root, graph)
        print(
            f"wrote {STATUS_GRAPH}: {len(graph['statuses'])} status(es), "
            f"{len(graph['transitions'])} transition(s), "
            f"{len(graph['writes'])} write site(s)"
        )
        return 0

    modules = collect_modules(root, args.paths or None)
    findings = analyze_modules(modules, all_checkers())
    if not args.paths:
        # repo-level + whole-program contracts only make sense on the
        # default full scan
        from foremast_tpu.analysis.metrics_contract import (
            check_metrics_docs,
            check_registry_coverage,
        )

        findings.extend(check_env_docs(root))
        findings.extend(check_metrics_docs(root))
        findings.extend(check_registry_coverage(modules))
        findings.extend(program_findings(root, modules))
        if not args.no_metrics_lint:
            findings.extend(metrics_lint_findings())
    findings.sort(key=Finding.sort_key)

    baseline_path = args.baseline or os.path.join(root, BASELINE_NAME)
    if args.write_baseline:
        Baseline.from_findings(findings).save(baseline_path)
        print(
            f"wrote {len(findings)} grandfathered finding(s) to "
            f"{os.path.relpath(baseline_path, root)}"
        )
        return 0

    baseline = Baseline.load(baseline_path)
    new, grandfathered = baseline.split(findings)
    stale = baseline.stale(findings)

    if args.sarif:
        json.dump(to_sarif(new, grandfathered), sys.stdout, indent=2)
        print()
    elif args.json:
        json.dump(
            {
                "findings": [f.to_json() for f in new],
                "grandfathered": [f.to_json() for f in grandfathered],
                "stale_baseline": stale,
            },
            sys.stdout,
            indent=2,
        )
        print()
    else:
        for f in new:
            print(f.render())
        if grandfathered:
            print(
                f"foremast-check: {len(grandfathered)} grandfathered "
                f"finding(s) suppressed by {BASELINE_NAME}"
            )
        for e in stale:
            print(
                "foremast-check: stale baseline entry (debt paid — remove "
                f"it): [{e['rule']}] {e['path']}: {e['message']}"
            )
        if new:
            print(
                f"foremast-check: {len(new)} new finding(s); fix, suppress "
                "with `# foremast: ignore[rule]` + justification, or (last "
                "resort) re-baseline — docs/static-analysis.md"
            )
        else:
            print("foremast-check: clean")
    return 1 if new else 0


if __name__ == "__main__":
    raise SystemExit(main())
