"""Cross-module program model for the concurrency rules.

The PR-2 checkers are each a pure function of ONE parsed module, which
is exactly why they could not see the bugs the last three hardening
rounds found by hand: a lock taken in `ingest/shards.py` while a hook
defined in `ingest/snapshot.py` takes another, a callback registered in
`jobs/worker.py` and invoked under a lock in `jobs/store.py`. This
module builds the whole-package view those rules need:

  * every class, its methods, and the ``threading.Lock``/``RLock``
    attributes it owns (with the creation site, so the runtime witness
    can map a live lock object back to its static identity);
  * attribute and local-variable TYPES where they are statically
    evident (``self.store = store`` with an annotation,
    ``self._shards = tuple(RingShard(...) ...)`` including
    container-element types, ``x = ClassName(...)`` locals);
  * a CALL RESOLVER: ``self.m()``, ``self.attr.m()``, typed locals,
    module functions, imported names, constructors — plus a CALLBACK
    TABLE for the hook pattern this codebase leans on
    (``store.journal = self._journal``,
    ``store.claim(claim_filter=self.mesh.claim_filter)``,
    ``ring.evict_unowned(self.router.owns_series)``): a function
    reference assigned to an attribute/dict slot or passed as an
    argument is recorded under that attribute/parameter NAME, and a
    later call of that bare name (a parameter, a read-back attribute)
    resolves to the recorded targets;
  * fixpoint summaries over the resolved call graph: which locks a
    function may eventually acquire, and which blocking operations it
    may eventually perform.

Resolution is deliberately OVER-approximate (a callback name with two
registered targets resolves to both): for lock-ordering the static
graph must be a superset of every runtime acquisition order, and for
blocking-under-lock a may-block answer is the conservative one. All of
it stays pure-AST — nothing here imports the checked code.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable

from foremast_tpu.analysis.core import Module

_LOCK_FACTORIES = {
    "threading.Lock": "Lock",
    "threading.RLock": "RLock",
    "Lock": "Lock",
    "RLock": "RLock",
}

_MUTATORS = frozenset(
    {
        "append", "appendleft", "extend", "insert", "add", "remove",
        "discard", "pop", "popitem", "clear", "update", "setdefault",
        "move_to_end", "sort", "reverse",
    }
)


def dotted(node: ast.AST) -> str | None:
    """`a.b.c` as a string, or None for non-name-rooted expressions."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def annotation_name(node: ast.AST | None) -> str | None:
    """A (possibly string-quoted) annotation as a dotted name. PEP 604
    optionals (`X | None`, the codebase's idiom for optional typed
    params) unwrap to the class side — an optional dependency still
    types the attribute it is assigned to."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.BitOr):
        left = annotation_name(node.left)
        if left and left != "None":
            return left
        return annotation_name(node.right)
    return dotted(node)


def module_stem(relpath: str) -> str:
    """`foremast_tpu/observe/spans.py` -> `observe.spans` — the short
    module identity lock IDs and messages use."""
    stem = relpath
    if stem.startswith("foremast_tpu/"):
        stem = stem[len("foremast_tpu/"):]
    if stem.endswith(".py"):
        stem = stem[:-3]
    return stem.replace("/", ".").removesuffix(".__init__")


@dataclasses.dataclass(frozen=True)
class LockId:
    """One static lock identity. Per-instance locks of one class attr
    (the shard locks) share an identity on purpose: the ordering
    contract is per-SITE, not per-object."""

    name: str      # "RingShard._lock" or "native._lock"
    kind: str      # "Lock" | "RLock"
    site: str      # "foremast_tpu/ingest/shards.py:56" (the factory call)

    def __str__(self) -> str:
        return self.name


@dataclasses.dataclass(eq=False)  # identity hash: one info per def site
class FunctionInfo:
    module: Module
    node: ast.AST                    # FunctionDef | AsyncFunctionDef
    qualname: str                    # "RingShard.push" / "observe.spans._run"
    class_key: str | None            # owning ClassInfo key, or None
    # filled by the summary fixpoint:
    acquires_all: set = dataclasses.field(default_factory=set)   # {LockId}
    blocks_all: dict = dataclasses.field(default_factory=dict)   # desc -> site

    @property
    def name(self) -> str:
        return self.node.name

    def site(self, node: ast.AST | None = None) -> str:
        line = getattr(node, "lineno", self.node.lineno)
        return f"{self.module.relpath}:{line}"


@dataclasses.dataclass
class ClassInfo:
    key: str                         # unique: "relpath::Qual.Name"
    name: str
    module: Module
    node: ast.ClassDef
    bases: list[str] = dataclasses.field(default_factory=list)
    methods: dict = dataclasses.field(default_factory=dict)      # name -> FunctionInfo
    lock_attrs: dict = dataclasses.field(default_factory=dict)   # attr -> LockId
    attr_types: dict = dataclasses.field(default_factory=dict)   # attr -> class key


class Program:
    """The whole-package index + call resolver."""

    def __init__(self, modules: Iterable[Module]):
        self.modules = list(modules)
        self.classes: dict[str, ClassInfo] = {}
        self.functions: list[FunctionInfo] = []
        # per module relpath: top-level def name -> FunctionInfo
        self.module_functions: dict[str, dict[str, FunctionInfo]] = {}
        # per module relpath: local name -> fully qualified import target
        self.imports: dict[str, dict[str, str]] = {}
        # per module relpath: module-level lock name -> LockId
        self.module_locks: dict[str, dict[str, LockId]] = {}
        # simple class name -> [class keys] (collision-aware lookup)
        self._by_name: dict[str, list[str]] = {}
        # method name -> [FunctionInfo] across all classes
        self._methods_by_name: dict[str, list[FunctionInfo]] = {}
        # callback name -> {FunctionInfo}: function refs stored in
        # attributes/dict slots or passed as call arguments
        self.callbacks: dict[str, set] = {}
        self._index()
        self._collect_callbacks()
        self._summarize()

    # -- indexing --------------------------------------------------------

    def _index(self) -> None:
        for module in self.modules:
            self.imports[module.relpath] = self._module_imports(module)
            self.module_functions[module.relpath] = {}
            self.module_locks[module.relpath] = self._find_module_locks(module)
            self._index_scope(
                module, module.tree.body, prefix="", cls=None, direct=True
            )
        # attribute/parameter TYPE resolution needs the complete class
        # index (modules are indexed in path order, and `node.py` must
        # see `routing.py`'s classes) — second pass
        for cls in self.classes.values():
            for fn in cls.methods.values():
                self._scan_method_for_class_state(cls, fn)

    @staticmethod
    def _module_imports(module: Module) -> dict[str, str]:
        out: dict[str, str] = {}
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    out[alias.asname or alias.name] = (
                        f"{node.module}.{alias.name}"
                    )
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    out[alias.asname or alias.name.split(".")[0]] = alias.name
        return out

    def _find_module_locks(self, module: Module) -> dict[str, LockId]:
        out: dict[str, LockId] = {}
        for stmt in module.tree.body:
            if isinstance(stmt, ast.Assign):
                kind = self._lock_kind(stmt.value)
                if kind is None:
                    continue
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = LockId(
                            name=f"{module_stem(module.relpath)}.{t.id}",
                            kind=kind,
                            site=f"{module.relpath}:{stmt.value.lineno}",
                        )
        return out

    @staticmethod
    def _lock_kind(node: ast.AST) -> str | None:
        if isinstance(node, ast.Call) and not node.args and not node.keywords:
            return _LOCK_FACTORIES.get(dotted(node.func))
        return None

    def _index_scope(
        self, module, body, prefix: str, cls: ClassInfo | None, direct: bool
    ):
        """`direct` is True only while iterating a module or class BODY
        — a def nested inside another def is its own FunctionInfo but
        neither a method of the class nor a module-level function."""
        for stmt in body:
            if isinstance(stmt, ast.ClassDef):
                qual = f"{prefix}{stmt.name}"
                key = f"{module.relpath}::{qual}"
                info = ClassInfo(
                    key=key,
                    name=stmt.name,
                    module=module,
                    node=stmt,
                    bases=[dotted(b) for b in stmt.bases if dotted(b)],
                )
                self.classes[key] = info
                self._by_name.setdefault(stmt.name, []).append(key)
                self._index_scope(module, stmt.body, f"{qual}.", info, True)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{stmt.name}"
                fn = FunctionInfo(
                    module=module,
                    node=stmt,
                    qualname=(
                        qual if prefix
                        else f"{module_stem(module.relpath)}.{qual}"
                    ),
                    class_key=cls.key if cls is not None else None,
                )
                self.functions.append(fn)
                if direct and cls is not None:
                    cls.methods[stmt.name] = fn
                    self._methods_by_name.setdefault(stmt.name, []).append(fn)
                elif direct and cls is None:
                    self.module_functions[module.relpath][stmt.name] = fn
                # nested defs (and defs inside defs) are their own
                # FunctionInfos, resolved by name from the enclosing
                # scope; `self` inside them still means the enclosing
                # class (a thread target defined in a method)
                self._index_scope(module, stmt.body, f"{qual}.", cls, False)
            else:
                # defs hide inside compound statements too (a thread
                # target defined under `with lock:`, a conditional
                # handler class) — walk every nested statement list
                for _f, value in ast.iter_fields(stmt):
                    if not (isinstance(value, list) and value):
                        continue
                    if isinstance(value[0], ast.stmt):
                        self._index_scope(module, value, prefix, cls, False)
                    elif isinstance(value[0], ast.excepthandler) or hasattr(
                        value[0], "body"
                    ):  # except handlers, match cases
                        for item in value:
                            self._index_scope(
                                module, item.body, prefix, cls, False
                            )

    def _scan_method_for_class_state(self, cls: ClassInfo, fn: FunctionInfo):
        """Lock attrs + attribute types assigned anywhere in a method."""
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Assign):
                continue
            for target in node.targets:
                attr = self_attr(target)
                if attr is None:
                    continue
                kind = self._lock_kind(node.value)
                if kind is not None:
                    cls.lock_attrs[attr] = LockId(
                        name=f"{cls.name}.{attr}",
                        kind=kind,
                        site=f"{cls.module.relpath}:{node.value.lineno}",
                    )
                    continue
                ckey = self._value_class(node.value, fn)
                if ckey is not None:
                    cls.attr_types.setdefault(attr, ckey)
        # parameter annotations: `def __init__(self, store: RingStore)`
        # + plain `self.x = param` aliasing
        params = {}
        args = fn.node.args
        for a in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs):
            ann = annotation_name(a.annotation)
            if ann:
                ckey = self.resolve_class_name(ann, fn.module)
                if ckey:
                    params[a.arg] = ckey
        if params:
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Name
                ):
                    ckey = params.get(node.value.id)
                    if ckey is None:
                        continue
                    for target in node.targets:
                        attr = self_attr(target)
                        if attr is not None:
                            cls.attr_types.setdefault(attr, ckey)

    def _value_class(self, value: ast.AST, fn: FunctionInfo) -> str | None:
        """Class key a value expression constructs, unwrapping the
        container shapes the codebase uses for lock-owning members:
        `X(...)`, `[X(...) for ...]`, `tuple(X(...) for ...)`,
        `X(...) if c else None`."""
        if isinstance(value, ast.IfExp):
            return (
                self._value_class(value.body, fn)
                or self._value_class(value.orelse, fn)
            )
        if isinstance(value, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            return self._value_class(value.elt, fn)
        if isinstance(value, ast.Call):
            callee = dotted(value.func)
            if callee in ("list", "tuple") and value.args:
                return self._value_class(value.args[0], fn)
            if callee is not None:
                return self.resolve_class_name(callee, fn.module)
        return None

    def resolve_class_name(self, name: str, module: Module) -> str | None:
        """A (possibly dotted / imported) name to a ClassInfo key."""
        # string annotations arrive quoted, possibly as `"X | None"`
        name = name.strip("'\"")
        if "|" in name:
            parts = [p.strip() for p in name.split("|")]
            name = next((p for p in parts if p and p != "None"), name)
        target = self.imports.get(module.relpath, {}).get(name, name)
        simple = target.rsplit(".", 1)[-1]
        keys = self._by_name.get(simple, [])
        if not keys:
            return None
        if len(keys) == 1:
            return keys[0]
        # prefer the class defined in this module, else give up
        for k in keys:
            if k.startswith(f"{module.relpath}::"):
                return k
        return None

    # -- callback table --------------------------------------------------

    def _collect_callbacks(self) -> None:
        for fn in self.functions:
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Assign):
                    targets = self._ref_targets(node.value, fn)
                    if not targets:
                        continue
                    for t in node.targets:
                        name = None
                        if isinstance(t, ast.Attribute):
                            name = t.attr
                        elif isinstance(t, ast.Subscript) and isinstance(
                            t.slice, ast.Constant
                        ) and isinstance(t.slice.value, str):
                            name = t.slice.value
                        if name is not None:
                            self.callbacks.setdefault(name, set()).update(
                                targets
                            )
                elif isinstance(node, ast.Call):
                    self._collect_call_arg_callbacks(node, fn)

    def _collect_call_arg_callbacks(self, call: ast.Call, fn: FunctionInfo):
        """`f(cb)` / `f(x=cb)` where cb is a function reference: bind cb
        to the parameter NAME it lands on, for every resolution
        candidate of f."""
        arg_refs = [
            (i, None, self._ref_targets(a, fn))
            for i, a in enumerate(call.args)
        ] + [
            (None, kw.arg, self._ref_targets(kw.value, fn))
            for kw in call.keywords
            if kw.arg is not None
        ]
        arg_refs = [(i, k, t) for i, k, t in arg_refs if t]
        if not arg_refs:
            return
        callees = self.resolve_call(call, fn) or self._callees_by_attr_name(
            call
        )
        for i, kw, targets in arg_refs:
            if kw is not None:
                self.callbacks.setdefault(kw, set()).update(targets)
                continue
            for callee in callees:
                params = [
                    a.arg
                    for a in callee.node.args.args
                    if a.arg not in ("self", "cls")
                ]
                if i < len(params):
                    self.callbacks.setdefault(params[i], set()).update(
                        targets
                    )

    def _callees_by_attr_name(self, call: ast.Call) -> list[FunctionInfo]:
        if isinstance(call.func, ast.Attribute):
            return list(self._methods_by_name.get(call.func.attr, ()))
        return []

    def _ref_targets(self, value: ast.AST, fn: FunctionInfo) -> set:
        """FunctionInfos a *reference* expression denotes (not a call):
        `self._journal`, `self.mesh.claim_filter`, `helper`."""
        out: set = set()
        if isinstance(value, ast.Attribute):
            recv_cls = self.receiver_class(value.value, fn)
            if recv_cls is not None:
                m = self._lookup_method(recv_cls, value.attr)
                if m is not None:
                    out.add(m)
                    return out
            # unique-method-name fallback: `self.mesh.claim_filter`
            # with an untyped `mesh` still resolves when exactly one
            # class in the package defines the method
            candidates = self._methods_by_name.get(value.attr, ())
            if len(candidates) == 1:
                out.add(candidates[0])
        elif isinstance(value, ast.Name):
            local = self._local_function(value.id, fn)
            if local is not None:
                out.add(local)
        return out

    def _local_function(self, name: str, fn: FunctionInfo) -> FunctionInfo | None:
        # nested def in the same function?
        for stmt in ast.walk(fn.node):
            if (
                isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
                and stmt.name == name
                and stmt is not fn.node
            ):
                for cand in self.functions:
                    if cand.node is stmt:
                        return cand
        mod_fns = self.module_functions.get(fn.module.relpath, {})
        if name in mod_fns:
            return mod_fns[name]
        target = self.imports.get(fn.module.relpath, {}).get(name)
        if target and "." in target:
            mod_target, simple = target.rsplit(".", 1)
            fns = self.module_functions.get(
                mod_target.replace(".", "/") + ".py", {}
            )
            if simple in fns:
                return fns[simple]
        return None

    # -- receivers and calls ---------------------------------------------

    def receiver_class(self, node: ast.AST, fn: FunctionInfo) -> str | None:
        """Class key of a receiver expression, or None. Handles `self`,
        `self.attr` (declared types), `x` locals constructed in this
        function or annotated parameters, and subscripts of typed
        containers (`self._shards[i]`)."""
        while isinstance(node, ast.Subscript):
            node = node.value
        if isinstance(node, ast.Name):
            if node.id == "self":
                return fn.class_key
            return self._local_type(node.id, fn)
        if isinstance(node, ast.Attribute):
            attr = self_attr(node)
            if attr is not None and fn.class_key is not None:
                cls = self.classes.get(fn.class_key)
                if cls is not None:
                    return cls.attr_types.get(attr)
        return None

    def _local_type(self, name: str, fn: FunctionInfo) -> str | None:
        args = fn.node.args
        for a in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs):
            if a.arg == name:
                ann = annotation_name(a.annotation)
                if ann:
                    return self.resolve_class_name(ann, fn.module)
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == name:
                        ckey = self._value_class(node.value, fn)
                        if ckey is not None:
                            return ckey
        return None

    def _lookup_method(self, class_key: str, name: str) -> FunctionInfo | None:
        cls = self.classes.get(class_key)
        seen = set()
        while cls is not None and cls.key not in seen:
            seen.add(cls.key)
            if name in cls.methods:
                return cls.methods[name]
            nxt = None
            for base in cls.bases:
                bkey = self.resolve_class_name(base, cls.module)
                if bkey is not None:
                    nxt = self.classes.get(bkey)
                    break
            cls = nxt
        return None

    def resolve_call(self, call: ast.Call, fn: FunctionInfo) -> list[FunctionInfo]:
        """Possible targets of a call expression (empty = unresolved).
        Unresolved calls of a NAME registered in the callback table
        resolve to the registered targets."""
        func = call.func
        out: list[FunctionInfo] = []
        if isinstance(func, ast.Name):
            local = self._local_function(func.id, fn)
            if local is not None:
                return [local]
            ckey = self.resolve_class_name(func.id, fn.module)
            if ckey is not None:
                init = self._lookup_method(ckey, "__init__")
                return [init] if init is not None else []
            if func.id in self.callbacks:
                return sorted(
                    self.callbacks[func.id], key=lambda f: f.qualname
                )
            return []
        if isinstance(func, ast.Attribute):
            recv_cls = self.receiver_class(func.value, fn)
            if recv_cls is not None:
                m = self._lookup_method(recv_cls, func.attr)
                if m is not None:
                    return [m]
            # `mod.fn()` through an `import pkg.mod [as mod]`
            d = dotted(func)
            if d is not None and "." in d:
                root, tail = d.split(".", 1)
                target = self.imports.get(fn.module.relpath, {}).get(root)
                if target is not None and "." not in tail:
                    fns = self.module_functions.get(
                        target.replace(".", "/") + ".py", {}
                    )
                    if tail in fns:
                        return [fns[tail]]
            if func.attr in self.callbacks:
                return sorted(
                    self.callbacks[func.attr], key=lambda f: f.qualname
                )
        return out

    def resolve_call_direct(
        self, call: ast.Call, fn: FunctionInfo
    ) -> list[FunctionInfo]:
        """`resolve_call` minus the callback-table spread: only callees
        the call site NAMES directly (same simple name, or a
        constructor call naming the class). The lock/blocking
        summaries WANT the over-approximation (a superset of runtime
        behavior is the conservative answer there); taint- and
        ownership-style analyses (device-flow, status-machine) must
        not — resolving a generic driver's `cb(...)` to every
        registered callback marries the driver's call sites to every
        callback's parameters and floods the whole package."""
        func = call.func
        if isinstance(func, ast.Name):
            name = func.id
        elif isinstance(func, ast.Attribute):
            name = func.attr
        else:
            return []
        out = []
        for callee in self.resolve_call(call, fn):
            if callee.name == name:
                out.append(callee)
            elif callee.name == "__init__" and callee.class_key is not None:
                cls = self.classes.get(callee.class_key)
                if cls is not None and cls.name == name:
                    out.append(callee)
        return out

    # -- lock identification ---------------------------------------------

    def lock_for_with_item(
        self, expr: ast.AST, fn: FunctionInfo
    ) -> LockId | None:
        """The LockId a `with <expr>:` item acquires, or None."""
        attr = self_attr(expr)
        if attr is not None and fn.class_key is not None:
            cls = self.classes.get(fn.class_key)
            if cls is not None and attr in cls.lock_attrs:
                return cls.lock_attrs[attr]
        if isinstance(expr, ast.Name):
            return self.module_locks.get(fn.module.relpath, {}).get(expr.id)
        if isinstance(expr, ast.Attribute):
            recv_cls = self.receiver_class(expr.value, fn)
            if recv_cls is not None:
                cls = self.classes.get(recv_cls)
                if cls is not None:
                    return cls.lock_attrs.get(expr.attr)
        return None

    def all_locks(self) -> list[LockId]:
        out: dict[str, LockId] = {}
        for cls in self.classes.values():
            for lock in cls.lock_attrs.values():
                out[lock.name] = lock
        for locks in self.module_locks.values():
            for lock in locks.values():
                out[lock.name] = lock
        return sorted(out.values(), key=lambda lk: lk.name)

    # -- summaries (fixpoint) --------------------------------------------

    def _summarize(self) -> None:
        from foremast_tpu.analysis.blocking_under_lock import classify_blocking

        direct_acquires: dict[int, set] = {}
        direct_blocks: dict[int, dict] = {}
        calls: dict[int, list] = {}
        for fn in self.functions:
            acq: set = set()
            blk: dict = {}
            cl: list = []
            for node in own_body_walk(fn.node):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        lock = self.lock_for_with_item(item.context_expr, fn)
                        if lock is not None:
                            acq.add(lock)
                elif isinstance(node, ast.Call):
                    desc = classify_blocking(node)
                    if desc is not None:
                        blk.setdefault(desc, fn.site(node))
                    cl.append(node)
            direct_acquires[id(fn)] = acq
            direct_blocks[id(fn)] = blk
            calls[id(fn)] = cl
            fn.acquires_all = set(acq)
            fn.blocks_all = dict(blk)
        changed = True
        while changed:
            changed = False
            for fn in self.functions:
                for call in calls[id(fn)]:
                    for callee in self.resolve_call(call, fn):
                        if not callee.acquires_all <= fn.acquires_all:
                            fn.acquires_all |= callee.acquires_all
                            changed = True
                        for desc, site in callee.blocks_all.items():
                            if desc not in fn.blocks_all:
                                fn.blocks_all[desc] = site
                                changed = True


def self_attr(node: ast.AST) -> str | None:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def walk_pruned(node: ast.AST):
    """`node` and its subtree, NEVER entering nested function
    definitions (neither the def node nor its children are yielded —
    a nested def runs when called, possibly on another thread, so
    nothing inside it belongs to the enclosing context). Lambdas ARE
    included: the codebase's lambdas are thin argument adapters
    executed by their consumer, and attributing their contents to the
    enclosing function is the harmless over-approximation."""
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            stack.append(child)


def locked_walk(program, fn: FunctionInfo):
    """THE shared traversal for the concurrency rules: yields
    ``(node, held, acquired)`` triples over `fn`'s inline body, where
    `held` is the list of LockIds held at that node and `acquired` is
    the LockId a lock-taking `with` statement acquires (None for every
    other node; `held` then excludes it, so the caller sees the
    ordering event outer-held -> acquired).

    Guarantees the bespoke per-rule walkers used to get wrong in three
    places at once (code-review finding): nested function definitions
    are never entered (they run when called, not where defined, so
    their bodies must neither inherit the lock context nor pollute
    guard inference), and nested compound-statement bodies are visited
    exactly once, with the correct held set."""
    out: list = []

    def emit(node, held):
        for n in walk_pruned(node):
            out.append((n, held, None))

    def visit(body, held: list):
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                acquired: list = []
                for item in stmt.items:
                    lock = program.lock_for_with_item(item.context_expr, fn)
                    if lock is not None:
                        out.append((stmt, held + acquired, lock))
                        acquired.append(lock)
                    else:
                        emit(item.context_expr, held + acquired)
                visit(stmt.body, held + acquired)
                continue
            # the statement node itself (Assign/AugAssign/Delete are
            # what mutation detection matches on), then its expression
            # fields; nested statement bodies recurse with `held`
            out.append((stmt, held, None))
            for _field, value in ast.iter_fields(stmt):
                if isinstance(value, ast.AST):
                    emit(value, held)
                elif isinstance(value, list) and value:
                    if isinstance(value[0], ast.stmt):
                        visit(value, held)
                    else:
                        for v in value:
                            if isinstance(v, ast.excepthandler):
                                if v.type is not None:
                                    emit(v.type, held)
                                visit(v.body, held)
                            elif hasattr(v, "body") and isinstance(
                                getattr(v, "body"), list
                            ):  # match_case
                                guard = getattr(v, "guard", None)
                                if guard is not None:
                                    emit(guard, held)
                                visit(v.body, held)
                            elif isinstance(v, ast.AST):
                                emit(v, held)

    visit(fn.node.body, [])
    return out


def own_body_walk(fn_node: ast.AST):
    """Walk a function body WITHOUT descending into nested function
    definitions — a nested def runs when called (possibly on another
    thread), never at its definition site, so its acquisitions and
    blocking calls must not be attributed inline. Lambdas ARE included:
    the codebase's lambdas are thin argument adapters executed by their
    consumer, and attributing their calls to the enclosing function is
    the harmless over-approximation."""
    stack = [
        stmt
        for stmt in fn_node.body
        if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            stack.append(child)


def mutated_attr(node: ast.AST) -> tuple[str | None, ast.AST | None]:
    """(attr, receiver) for an attribute mutation node: `recv.attr = v`,
    `recv.attr += v`, `recv.attr[k] = v`, `del recv.attr`, or a
    mutating method call `recv.attr.append(v)` / `recv.attr.update(d)`.
    Returns (None, None) for anything else."""
    if isinstance(node, ast.Assign):
        for t in node.targets:
            a, r = _mut_target(t)
            if a is not None:
                return a, r
        return None, None
    if isinstance(node, ast.AugAssign):
        return _mut_target(node.target)
    if isinstance(node, ast.Delete):
        for t in node.targets:
            a, r = _mut_target(t)
            if a is not None:
                return a, r
        return None, None
    if isinstance(node, ast.Call):
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr in _MUTATORS:
            if isinstance(func.value, ast.Attribute):
                return func.value.attr, func.value.value
    return None, None


def _mut_target(t: ast.AST) -> tuple[str | None, ast.AST | None]:
    while isinstance(t, ast.Subscript):
        t = t.value
    if isinstance(t, ast.Attribute):
        return t.attr, t.value
    return None, None
