"""device-flow: implicit device->host syncs outside the gather stages.

The warm tick's latency contract (CHANGES PR 13–15) is that exactly ONE
blocking device->host transfer happens per dispatch: the gather inside
`ColumnarPending.wait` / `HealthJudge._fetch`. Everything upstream is
async dispatch; everything downstream decodes HOST arrays. The failure
mode this rule encodes is the quiet regression: somebody touches a
device value with `np.asarray`, `float()`, `.item()`, Python iteration
or a per-element `x[i]` loop, and the tick grows a synchronous
round-trip per call that no test times but every Prometheus user feels.

The model is an interprocedural taint over `interproc.Program`:

  * SOURCES — values returned by the dispatch roots (`judge_columnar`,
    `judge_columnar_async`, a ``.wait()`` on a pending), by any
    function the package jits (``jax.jit`` decorators and the
    ``name = jax.jit(fn)`` assignment form), by ``jnp.*`` /
    ``jax.device_put`` expressions, and by the placement hooks
    (`_place`/`_place_cols`);
  * PROPAGATION — through local assignments, through call arguments
    into resolved callees' parameters, and through return values of
    functions whose returns are tainted (fixpoint over the resolved
    call graph);
  * SINKS — the D2H idioms above, applied to a tainted value;
  * BOUNDARY — a function annotated ``# foremast: device-boundary``
    (on/above its ``def``) is a sanctioned gather/decode stage: sinks
    inside it are the design, its RESULT is host, and the values it
    hands to callees are host-side products of that decode (a boundary
    neither returns nor propagates taint — annotations therefore go on
    the STAGE ENTRY POINTS, not on every helper a decode stage feeds).
    The annotation inventory lives in docs/static-analysis.md — adding
    one is a reviewed contract change, not a suppression.

Static-under-tracing accesses (``.shape``/``.ndim``/``.dtype``/
``.size``, ``len()``, ``isinstance()``) neither taint nor sink, same
as jit-hygiene.

SINKS are checked only on the dispatch path (``engine/``, ``jobs/``,
``parallel/``) and never inside a jitted function: `ops/` and
`models/` are traced-interior libraries where Python iteration and
`x[i]` unroll at TRACE time (fixed-shape idiom, jit-hygiene's domain),
and the host-only packages (ingest/, metrics/, mesh/, cli, deploy,
observe) hold no device values by construction — scoping them out
keeps the taint fixpoint from amplifying resolver noise into
package-wide false positives. Taint still PROPAGATES through all of
them, so a device value that round-trips through a helper module is
caught when it reaches a scoped sink.
"""

from __future__ import annotations

import ast

from foremast_tpu.analysis.core import Finding
from foremast_tpu.analysis.interproc import (
    FunctionInfo,
    Program,
    dotted,
    own_body_walk,
)
from foremast_tpu.analysis.jit_hygiene import _is_jax_jit, _jit_call_statics

RULE = "device-flow"
BOUNDARY_MARKER = "device-boundary"

DISPATCH_ROOTS = frozenset({"judge_columnar", "judge_columnar_async"})
PLACEMENT_HOOKS = frozenset({"_place", "_place_cols"})
SINK_SCOPE = (
    "foremast_tpu/engine/",
    "foremast_tpu/jobs/",
    "foremast_tpu/parallel/",
)
_NP_NAMES = frozenset({"np", "numpy"})
_NP_MATERIALIZERS = frozenset(
    {"asarray", "array", "asanyarray", "ascontiguousarray"}
)
_SYNC_BUILTINS = frozenset({"float", "int", "bool"})
_ITER_BUILTINS = frozenset({"list", "tuple", "sorted", "sum"})
_STATIC_ATTRS = frozenset({"shape", "ndim", "dtype", "size", "nbytes"})
_STATIC_CALLS = frozenset({"len", "isinstance", "hasattr", "getattr"})


def _jit_root_names(program: Program) -> frozenset[str]:
    """Every name the package binds to a jitted callable: decorated
    defs plus `name = jax.jit(fn)` / `self.attr = jax.jit(fn)`
    assignment targets."""
    names: set[str] = set()
    for module in program.modules:
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in node.decorator_list:
                    if _is_jax_jit(deco) or (
                        isinstance(deco, ast.Call)
                        and _jit_call_statics(deco, {}) is not None
                    ):
                        names.add(node.name)
            elif isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                call = node.value
                wraps_jit = _is_jax_jit(call.func) or (
                    isinstance(call.func, ast.Call)
                    and _jit_call_statics(call.func, {}) is not None
                )
                if not wraps_jit:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
                    elif isinstance(t, ast.Attribute):
                        names.add(t.attr)
    return frozenset(names)


def _is_pending_wait(call: ast.Call) -> bool:
    """`<something pending-ish>.wait()` — the gather half of the
    async dispatch split. Receiver must mention "pending" so bare
    `event.wait()` never taints."""
    func = call.func
    if not (isinstance(func, ast.Attribute) and func.attr == "wait"):
        return False
    recv = dotted(func.value) or ""
    return "pending" in recv.lower()


class _Taint:
    """Per-function taint computation against shared program state."""

    def __init__(self, program: Program, jit_names: frozenset[str]):
        self.program = program
        self.jit_names = jit_names
        # interprocedural state, grown to a fixpoint by analyze():
        self.tainted_params: dict[int, set[str]] = {}
        self.returns_device: set[int] = set()
        self.boundary: set[int] = set()

    def is_boundary(self, fn: FunctionInfo) -> bool:
        return id(fn) in self.boundary

    # -- expression classification ---------------------------------------

    def _call_is_source(self, call: ast.Call, fn: FunctionInfo) -> bool:
        d = dotted(call.func)
        if d is not None:
            root = d.split(".", 1)[0]
            if root in ("jnp",) or d.startswith("jax.numpy."):
                return True
            if d in ("jax.device_put", "device_put"):
                return True
        name = None
        if isinstance(call.func, ast.Name):
            name = call.func.id
        elif isinstance(call.func, ast.Attribute):
            name = call.func.attr
        if name in DISPATCH_ROOTS or name in PLACEMENT_HOOKS:
            return True
        if name in self.jit_names:
            return True
        if _is_pending_wait(call):
            return True
        for callee in self.program.resolve_call_direct(call, fn):
            if id(callee) in self.returns_device and not self.is_boundary(
                callee
            ):
                return True
        return False

    def _call_is_barrier(self, call: ast.Call, fn: FunctionInfo) -> bool:
        """Calls whose RESULT is host even when their arguments are
        device values: the designated gathers (`_fetch`,
        `jax.device_get`, any `# foremast: device-boundary` function)
        and the explicit conversions (which the sink pass flags on
        their own — taint must not survive them and double-report
        downstream)."""
        d = dotted(call.func)
        if d in ("jax.device_get", "device_get"):
            return True
        if (
            d is not None
            and "." in d
            and d.split(".", 1)[0] in _NP_NAMES
            and d.rsplit(".", 1)[1] in _NP_MATERIALIZERS
        ):
            return True
        if d in _SYNC_BUILTINS or d in _ITER_BUILTINS:
            return True
        name = call.func.attr if isinstance(call.func, ast.Attribute) else None
        if name in ("_fetch", "item", "tolist"):
            return True
        return any(
            self.is_boundary(callee)
            for callee in self.program.resolve_call_direct(call, fn)
        )

    def expr_device(
        self, expr: ast.AST, tainted: set[str], fn: FunctionInfo
    ) -> bool:
        """Does `expr` evaluate to (or contain, outside static-safe
        subtrees) a device/traced value?"""
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
                continue  # x.shape and friends are host metadata
            if isinstance(node, ast.Call):
                d = dotted(node.func)
                if d in _STATIC_CALLS:
                    continue
                if self._call_is_barrier(node, fn):
                    continue  # gathered/converted: host from here on
                if self._call_is_source(node, fn):
                    return True
                # a non-source, non-barrier call's RESULT is unknown
                # (host by default) but its ARGUMENTS still flow into
                # it, so keep walking the whole call expression
            if isinstance(node, ast.Name) and node.id in tainted:
                return True
            stack.extend(ast.iter_child_nodes(node))
        return False

    # -- per-function local fixpoint -------------------------------------

    def local_taint(self, fn: FunctionInfo) -> set[str]:
        tainted = set(self.tainted_params.get(id(fn), ()))
        changed = True
        while changed:
            changed = False
            for node in own_body_walk(fn.node):
                if isinstance(node, ast.Assign):
                    value, targets = node.value, node.targets
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    value, targets = node.value, [node.target]
                elif isinstance(node, ast.AugAssign):
                    value, targets = node.value, [node.target]
                else:
                    continue
                if not self.expr_device(value, tainted, fn):
                    continue
                for t in targets:
                    for leaf in ast.walk(t):
                        if (
                            isinstance(leaf, ast.Name)
                            and leaf.id not in tainted
                        ):
                            tainted.add(leaf.id)
                            changed = True
        return tainted


def _callee_params(callee: FunctionInfo) -> list[str]:
    a = callee.node.args
    return [
        p.arg
        for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)
        if p.arg not in ("self", "cls")
    ]


def check_device_flow(program: Program) -> list[Finding]:
    taint = _Taint(program, _jit_root_names(program))
    for fn in program.functions:
        if fn.module.marked_def(fn.node, BOUNDARY_MARKER):
            taint.boundary.add(id(fn))

    # interprocedural fixpoint: parameter taint + device-returning fns
    changed = True
    while changed:
        changed = False
        for fn in program.functions:
            if taint.is_boundary(fn):
                # a boundary's whole body is the sanctioned decode:
                # nothing it returns or passes onward is device taint
                continue
            tainted = taint.local_taint(fn)
            for node in own_body_walk(fn.node):
                if isinstance(node, ast.Return) and node.value is not None:
                    if (
                        id(fn) not in taint.returns_device
                        and not taint.is_boundary(fn)
                        and taint.expr_device(node.value, tainted, fn)
                    ):
                        taint.returns_device.add(id(fn))
                        changed = True
                elif isinstance(node, ast.Call):
                    callees = program.resolve_call_direct(node, fn)
                    if not callees:
                        continue
                    for callee in callees:
                        params = _callee_params(callee)
                        into = taint.tainted_params.setdefault(
                            id(callee), set()
                        )
                        for i, arg in enumerate(node.args):
                            if i >= len(params) or params[i] in into:
                                continue
                            if taint.expr_device(arg, tainted, fn):
                                into.add(params[i])
                                changed = True
                        for kw in node.keywords:
                            if kw.arg is None or kw.arg in into:
                                continue
                            if kw.arg in params and taint.expr_device(
                                kw.value, tainted, fn
                            ):
                                into.add(kw.arg)
                                changed = True

    findings: list[Finding] = []
    for fn in program.functions:
        if taint.is_boundary(fn):
            continue
        if not fn.module.relpath.startswith(SINK_SCOPE):
            continue
        # a jitted function's body (and its nested defs) is traced
        # code: `for`/`x[i]` unroll at trace time, conversions raise
        # TracerError on their own — jit-hygiene's domain, not a sync
        if any(part in taint.jit_names for part in fn.qualname.split(".")):
            continue
        findings.extend(_sink_findings(taint, fn))
    return findings


def _sink_findings(taint: _Taint, fn: FunctionInfo) -> list[Finding]:
    tainted = taint.local_taint(fn)
    module = fn.module
    out: list[Finding] = []

    def dev(expr: ast.AST) -> bool:
        return taint.expr_device(expr, tainted, fn)

    def flag(node: ast.AST, what: str) -> None:
        out.append(
            module.finding(
                RULE,
                node,
                f"{what} on a device/traced value in `{fn.name}` — an "
                "implicit device->host sync outside the gather/decode "
                "stages",
                hint="keep the value device-resident until the designated "
                "gather (`ColumnarPending.wait` / `_fetch`), or — if this "
                "function IS a gather/decode stage — annotate the def with "
                "`# foremast: device-boundary` and document the contract "
                "(docs/static-analysis.md)",
            )
        )

    # per-element indexing: `buf[i]` where i is a range-loop variable
    range_vars: set[str] = set()
    for node in own_body_walk(fn.node):
        if isinstance(node, ast.For) and isinstance(node.iter, ast.Call):
            if dotted(node.iter.func) == "range":
                for leaf in ast.walk(node.target):
                    if isinstance(leaf, ast.Name):
                        range_vars.add(leaf.id)

    for node in own_body_walk(fn.node):
        if isinstance(node, ast.Call):
            d = dotted(node.func)
            if (
                d is not None
                and "." in d
                and d.split(".", 1)[0] in _NP_NAMES
                and d.rsplit(".", 1)[1] in _NP_MATERIALIZERS
            ):
                if any(dev(a) for a in node.args):
                    flag(node, f"`{d}()`")
            elif d in _SYNC_BUILTINS:
                if any(dev(a) for a in node.args):
                    flag(node, f"`{d}()`")
            elif d in _ITER_BUILTINS:
                if any(dev(a) for a in node.args):
                    flag(node, f"`{d}()` (Python iteration)")
            elif isinstance(node.func, ast.Attribute) and node.func.attr in (
                "item",
                "tolist",
            ):
                if dev(node.func.value):
                    flag(node, f"`.{node.func.attr}()`")
        elif isinstance(node, ast.For):
            if dev(node.iter):
                flag(node, "Python `for` iteration")
        elif isinstance(
            node, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)
        ):
            for gen in node.generators:
                if dev(gen.iter):
                    flag(node, "comprehension iteration")
        elif isinstance(node, ast.Subscript):
            if (
                isinstance(node.slice, ast.Name)
                and node.slice.id in range_vars
                and dev(node.value)
            ):
                flag(node, "per-element indexing")
    return out
