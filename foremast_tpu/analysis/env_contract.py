"""env-contract: every env read outside config.py maps to a declared knob.

The reference brain is configured entirely through environment
variables, and this framework inherited the habit: knobs accreted in
arena sizing, bf16 storage, gauge caps, UI endpoints, native-loader
gates... Each stray ``os.environ.get`` is configuration surface that
docs, `/debug/state` fingerprinting, and operators cannot enumerate.

The contract: ``foremast_tpu/config.py`` declares the registry
(``ENV_KNOBS`` — name, default, type, description); every literal env
read anywhere else in the package must name a registered knob. Reads of
*computed* names are flagged too (they defeat enumeration) — a thin
wrapper whose call sites pass literals documents itself with a
``# foremast: ignore[env-contract]`` at the single dynamic read.

The registry is also the single source for the operator docs: the env
table in ``docs/operations.md`` between the ``ENV REGISTRY`` markers is
GENERATED (``python -m foremast_tpu.analysis --update-env-docs`` or
``make env-docs``), and the default run reports a finding when the
committed table has drifted from the registry.
"""

from __future__ import annotations

import ast
import os

from foremast_tpu.analysis.core import Checker, Finding, Module, os_import_aliases

DOCS_RELPATH = "docs/operations.md"
DOCS_BEGIN = "<!-- BEGIN ENV REGISTRY (generated: make env-docs) -->"
DOCS_END = "<!-- END ENV REGISTRY -->"


def _dotted(node: ast.AST) -> str | None:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def registered_names() -> frozenset[str]:
    """Knob names from the config registry (imported lazily — config
    pulls numpy/jax, which the AST passes never need)."""
    from foremast_tpu.config import ENV_KNOBS

    return frozenset(k.name for k in ENV_KNOBS)


class EnvContractChecker(Checker):
    rule = "env-contract"
    description = (
        "os.environ reads outside config.py must name a registered knob"
    )
    # bench scripts and test helpers read knobs too — an unregistered
    # read there is config surface operators can't enumerate either
    scope = "repo"

    # the registry module itself, and the analysis package (which would
    # otherwise flag its own documentation strings' AST fixtures)
    EXEMPT = ("foremast_tpu/config.py",)

    def __init__(self, names: frozenset[str] | None = None):
        self._names = names

    @property
    def names(self) -> frozenset[str]:
        if self._names is None:
            self._names = registered_names()
        return self._names

    def applies_to(self, relpath: str) -> bool:
        return super().applies_to(relpath) and relpath not in self.EXEMPT

    def check(self, module: Module) -> list[Finding]:
        # bare `environ`/`getenv` only count when actually imported from
        # os — a WSGI handler's `environ` dict is not the process env
        environ_names = {"os.environ"} | {
            a for a in os_import_aliases(module.tree, "environ")
        }
        read_calls = {"os.getenv", "os.environ.get"} | {
            f"{a}.get" for a in environ_names if a != "os.environ"
        } | set(os_import_aliases(module.tree, "getenv"))
        findings: list[Finding] = []
        for node in ast.walk(module.tree):
            name_node = None
            if isinstance(node, ast.Call) and _dotted(node.func) in read_calls:
                if node.args:
                    name_node = node.args[0]
            elif (
                isinstance(node, ast.Subscript)
                and isinstance(node.ctx, ast.Load)
                and _dotted(node.value) in environ_names
            ):
                name_node = node.slice
            if name_node is None:
                continue
            if isinstance(name_node, ast.Constant) and isinstance(
                name_node.value, str
            ):
                if name_node.value not in self.names:
                    findings.append(
                        module.finding(
                            self.rule,
                            node,
                            f"env var {name_node.value!r} read here is not "
                            "declared in config.ENV_KNOBS",
                            hint="add an EnvKnob entry (name, default, "
                            "kind, description) in foremast_tpu/config.py, "
                            "then `make env-docs`",
                        )
                    )
            else:
                findings.append(
                    module.finding(
                        self.rule,
                        node,
                        "env read with a computed name defeats knob "
                        "enumeration",
                        hint="read literals (register each), or suppress a "
                        "thin wrapper whose call sites pass literals with "
                        "`# foremast: ignore[env-contract]`",
                    )
                )
        return findings


# ---------------------------------------------------------------------------
# generated operator docs
# ---------------------------------------------------------------------------


def render_env_table() -> str:
    """The docs/operations.md configuration-reference block, rendered
    from config.ENV_KNOBS. Deterministic: registry order within each
    group, groups in fixed order."""
    from foremast_tpu.config import ENV_KNOBS

    groups = [
        (
            "engine",
            "Engine (reference parity, `foremast-brain.yaml:21-81` + "
            "`foremast-brain/README.md:20-38`):",
        ),
        ("framework", "Framework-specific:"),
        ("deploy", "Deployment / platform integration:"),
    ]
    lines = [DOCS_BEGIN, ""]
    for group, heading in groups:
        knobs = [k for k in ENV_KNOBS if k.group == group]
        if not knobs:
            continue
        lines.append(heading)
        lines.append("")
        lines.append("| Var | Default | Meaning |")
        lines.append("|---|---|---|")
        for k in knobs:
            default = k.default if k.default not in (None, "") else "—"
            desc = k.description.replace("|", "\\|")
            lines.append(
                f"| `{k.name}` | {default.replace('|', chr(92) + '|')} "
                f"| {desc} |"
            )
        lines.append("")
    lines.append(
        "This table is generated from `foremast_tpu/config.py`'s "
        "`ENV_KNOBS` registry — edit the registry, then run `make "
        "env-docs`. `make check` fails when the two drift."
    )
    lines.append(DOCS_END)
    return "\n".join(lines)


def _split_docs(text: str) -> tuple[str, str, str] | None:
    try:
        head, rest = text.split(DOCS_BEGIN, 1)
        _, tail = rest.split(DOCS_END, 1)
    except ValueError:
        return None
    return head, text[len(head): len(text) - len(tail)], tail


def check_env_docs(root: str) -> list[Finding]:
    """Findings when the committed docs block is missing or stale."""
    path = os.path.join(root, DOCS_RELPATH)
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        text = f.read()
    parts = _split_docs(text)
    hint = "run `make env-docs` (or python -m foremast_tpu.analysis --update-env-docs)"
    if parts is None:
        return [
            Finding(
                rule="env-contract",
                path=DOCS_RELPATH,
                line=1,
                message="ENV REGISTRY markers missing from operator docs",
                hint=hint,
            )
        ]
    if parts[1] != render_env_table():
        return [
            Finding(
                rule="env-contract",
                path=DOCS_RELPATH,
                line=text[: text.index(DOCS_BEGIN)].count("\n") + 1,
                message="generated env table is stale vs config.ENV_KNOBS",
                hint=hint,
            )
        ]
    return []


def update_env_docs(root: str) -> bool:
    """Rewrite the generated block in place; returns True if changed."""
    path = os.path.join(root, DOCS_RELPATH)
    with open(path, encoding="utf-8") as f:
        text = f.read()
    parts = _split_docs(text)
    if parts is None:
        raise SystemExit(
            f"{DOCS_RELPATH}: ENV REGISTRY markers not found; add\n"
            f"{DOCS_BEGIN}\n{DOCS_END}\nwhere the table belongs"
        )
    head, old, tail = parts
    new = render_env_table()
    if old == new:
        return False
    with open(path, "w", encoding="utf-8") as f:
        f.write(head + new + tail)
    return True
