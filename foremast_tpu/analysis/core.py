"""Shared checker infrastructure: findings, modules, suppressions, baseline.

Everything here is import-light on purpose — the runner parses source
with ``ast`` and never imports the checked modules, so ``make check``
costs milliseconds and cannot touch an accelerator backend (the
environment's jax import path dials a TPU tunnel; a lint gate must never
wait on it).
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
import re
from typing import Iterable, Sequence

# Per-line opt-out: `# foremast: ignore[rule-a,rule-b]` or the bare
# `# foremast: ignore` (all rules). Valid on the finding's line or on a
# comment-only line directly above it — suppressions live next to the
# code they excuse, so a refactor that moves the code moves (or drops)
# the excuse with it. Whitespace is tolerated anywhere in the form:
# `ignore [rule]` used to silently degrade to the bare suppress-ALL
# (the bracket list failed to parse), which is the dangerous direction
# — a regression test pins the multi-rule and spaced forms.
_SUPPRESS_RE = re.compile(
    r"#\s*foremast:\s*ignore(?:\s*\[(?P<rules>[a-z0-9_,\- ]+)\])?"
)
_ALL_RULES = "*"

# Positive annotations: `# foremast: device-boundary` marks a function
# as a sanctioned gather/decode stage (rule device-flow allows host
# syncs inside it), `# foremast: sharded-arena` marks sharded code
# that touches arena rows under the data-axis row-placement contract
# (rule sharding-contract, ISSUE 19). Unlike `ignore[...]` these are CONTRACT
# declarations, not finding suppressions: they change what the rule
# checks, and the docs inventory them (docs/static-analysis.md).
_MARKER_RE = re.compile(r"#\s*foremast:\s*(?P<marker>[a-z][a-z-]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a file:line, with a fix hint.

    The fingerprint deliberately excludes the line number: baselined
    findings must survive unrelated edits above them, and two findings
    with identical messages in one file are the same debt wherever it
    drifts to.
    """

    rule: str
    path: str  # repo-relative, '/'-separated
    line: int
    message: str
    hint: str = ""

    def fingerprint(self) -> str:
        key = f"{self.rule}|{self.path}|{self.message}"
        return hashlib.sha256(key.encode()).hexdigest()[:16]

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "hint": self.hint,
            "fingerprint": self.fingerprint(),
        }

    def render(self) -> str:
        text = f"{self.path}:{self.line}: [{self.rule}] {self.message}"
        if self.hint:
            text += f"\n    hint: {self.hint}"
        return text

    def sort_key(self) -> tuple:
        return (self.path, self.line, self.rule, self.message)


class Module:
    """One parsed source file plus its suppression map."""

    def __init__(self, relpath: str, source: str, abspath: str | None = None):
        self.relpath = relpath.replace(os.sep, "/")
        self.abspath = abspath or relpath
        self.source = source
        self.tree = ast.parse(source, filename=self.relpath)
        self.lines = source.splitlines()
        self._suppressions = self._parse_suppressions(self.lines)

    @staticmethod
    def _parse_suppressions(lines: Sequence[str]) -> dict[int, frozenset[str]]:
        out: dict[int, frozenset[str]] = {}
        for i, line in enumerate(lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            rules = m.group("rules")
            if rules:
                out[i] = frozenset(r.strip() for r in rules.split(",") if r.strip())
            else:
                out[i] = frozenset({_ALL_RULES})
        return out

    def suppressed(self, line: int, rule: str) -> bool:
        """True when `line` (or a comment-only line right above it)
        carries an ignore for `rule`."""
        for candidate in (line, line - 1):
            if candidate < 1:
                continue
            rules = self._suppressions.get(candidate)
            if rules is None:
                continue
            if candidate == line - 1:
                # the line above only counts when it is a pure comment —
                # a suppression on a different statement must not leak
                # downward
                text = self.lines[candidate - 1].strip()
                if not text.startswith("#"):
                    continue
            if _ALL_RULES in rules or rule in rules:
                return True
        return False

    def marked(self, line: int, marker: str) -> bool:
        """True when `line` or the comment line right above it carries
        the `# foremast: <marker>` annotation (same placement rules as
        suppressions: the declaration lives next to the code it
        covers)."""
        for candidate in (line, line - 1):
            if candidate < 1 or candidate > len(self.lines):
                continue
            text = self.lines[candidate - 1]
            if candidate == line - 1 and not text.strip().startswith("#"):
                continue
            for m in _MARKER_RE.finditer(text):
                if m.group("marker") == marker:
                    return True
        return False

    def marked_def(self, fn_node: ast.AST, marker: str) -> bool:
        """True when a function definition carries the marker: on the
        `def` line, the line above it, or above its first decorator."""
        lines = [fn_node.lineno]
        decos = getattr(fn_node, "decorator_list", [])
        if decos:
            lines.append(min(d.lineno for d in decos))
        return any(self.marked(ln, marker) for ln in lines)

    def finding(
        self, rule: str, node: ast.AST | int, message: str, hint: str = ""
    ) -> Finding:
        line = node if isinstance(node, int) else getattr(node, "lineno", 1)
        return Finding(
            rule=rule, path=self.relpath, line=line, message=message, hint=hint
        )


class Checker:
    """Base class: one rule ID, one `check(module)` pass."""

    rule: str = ""
    description: str = ""
    # "package": product sources only — tests/ and benchmarks/ are
    # excluded (fixture paths and ad-hoc files still count).
    # "repo": the rule also runs over tests/ and benchmarks/ (the
    # async-blocking and env-contract contracts hold there too: bench
    # scripts read knobs, test helpers run on event loops).
    scope: str = "package"

    def applies_to(self, relpath: str) -> bool:
        if self.scope == "package" and relpath.startswith(
            ("tests/", "benchmarks/")
        ):
            return False
        return True

    def check(self, module: Module) -> list[Finding]:  # pragma: no cover
        raise NotImplementedError


def os_import_aliases(tree: ast.Module, member: str) -> frozenset[str]:
    """Bare names that alias `os.<member>` in this module (`from os
    import environ [as e]`). A WSGI handler's `environ` dict parameter
    must NOT match the env checkers — only a real import makes a bare
    name mean the process environment."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "os":
            for alias in node.names:
                if alias.name == member:
                    out.add(alias.asname or alias.name)
    return frozenset(out)


def repo_root() -> str:
    """The tree the default run scans: the directory holding the
    `foremast_tpu` package (and `analysis_baseline.json`)."""
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def collect_modules(
    root: str, paths: Sequence[str] | None = None
) -> list[Module]:
    """Parse every .py file under `paths` (default: the foremast_tpu
    package plus benchmarks/ and tests/ — repo-scoped rules cover
    those, package-scoped rules skip them via `Checker.applies_to`).
    Files that fail to parse surface as a synthetic finding from
    `analyze_modules`, not a crash."""
    if paths:
        targets = list(paths)
    else:
        targets = [os.path.join(root, "foremast_tpu")]
        for extra in ("benchmarks", "tests"):
            d = os.path.join(root, extra)
            if os.path.isdir(d):
                targets.append(d)
    files: list[str] = []
    for target in targets:
        if os.path.isfile(target):
            files.append(target)
            continue
        for dirpath, dirnames, filenames in os.walk(target):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            files.extend(
                os.path.join(dirpath, f)
                for f in filenames
                if f.endswith(".py")
            )
    modules = []
    for path in sorted(set(files)):
        rel = os.path.relpath(path, root)
        with open(path, encoding="utf-8") as f:
            modules.append(Module(rel, f.read(), abspath=path))
    return modules


def analyze_source(
    source: str, relpath: str, checkers: Iterable[Checker]
) -> list[Finding]:
    """Run checkers over one source string — the fixture-test entry
    point, and the shape `analyze_modules` loops over."""
    module = Module(relpath, source)
    findings: list[Finding] = []
    for checker in checkers:
        if not checker.applies_to(module.relpath):
            continue
        for f in checker.check(module):
            if not module.suppressed(f.line, f.rule):
                findings.append(f)
    return sorted(set(findings), key=Finding.sort_key)


def analyze_modules(
    modules: Iterable[Module], checkers: Iterable[Checker]
) -> list[Finding]:
    findings: list[Finding] = []
    checkers = list(checkers)
    for module in modules:
        for checker in checkers:
            if not checker.applies_to(module.relpath):
                continue
            for f in checker.check(module):
                if not module.suppressed(f.line, f.rule):
                    findings.append(f)
    return sorted(set(findings), key=Finding.sort_key)


class Baseline:
    """Committed grandfather list (`analysis_baseline.json`).

    Matching is by fingerprint (rule+path+message, line-independent):
    a baselined finding may move around its file without churning the
    baseline, but any NEW message — including the same violation in a
    new file — fails the gate. `stale()` reports entries whose debt has
    been paid so the file shrinks monotonically."""

    VERSION = 1

    def __init__(self, entries: Sequence[dict] | None = None):
        self.entries = list(entries or [])
        self._by_fp = {e["fingerprint"]: e for e in self.entries}

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls()
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        return cls(data.get("findings", []))

    @classmethod
    def from_findings(cls, findings: Sequence[Finding]) -> "Baseline":
        return cls(
            [
                {
                    "rule": f.rule,
                    "path": f.path,
                    "message": f.message,
                    "fingerprint": f.fingerprint(),
                }
                for f in sorted(set(findings), key=Finding.sort_key)
            ]
        )

    def save(self, path: str) -> None:
        payload = {
            "version": self.VERSION,
            "comment": (
                "Grandfathered static-analysis findings. New findings are "
                "build failures; shrink this file, never grow it "
                "(docs/static-analysis.md)."
            ),
            "findings": self.entries,
        }
        with open(path, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
            f.write("\n")

    def split(
        self, findings: Sequence[Finding]
    ) -> tuple[list[Finding], list[Finding]]:
        """(new, grandfathered) partition of `findings`."""
        new, old = [], []
        for f in findings:
            (old if f.fingerprint() in self._by_fp else new).append(f)
        return new, old

    def stale(self, findings: Sequence[Finding]) -> list[dict]:
        live = {f.fingerprint() for f in findings}
        return [e for e in self.entries if e["fingerprint"] not in live]
