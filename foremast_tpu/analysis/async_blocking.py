"""async-blocking: synchronous I/O and sleeps on the aiohttp event loops.

The service gateway (service/app.py), the dashboard (ui/app.py) and any
future async surface share one failure mode: a single blocking call in a
handler stalls EVERY in-flight request on that loop — including the
k8s liveness probes, so one slow Elasticsearch round trip can turn into
a pod restart. The repo's convention (service/app.py `create`) is to
push blocking work through ``asyncio.to_thread`` — which passes the
function *uncalled*, so this checker's call-site detection naturally
permits it.

Flagged inside ``async def`` bodies (nested sync defs excluded — they
may legitimately run on executor threads):

  * ``time.sleep`` (use ``asyncio.sleep``);
  * ``requests.*`` / ``urllib.request.*`` / raw ``socket`` dials (use
    the app's aiohttp session);
  * ``subprocess.*`` and ``os.system``/``os.popen`` (use
    ``asyncio.create_subprocess_exec``);
  * direct calls of the synchronous JobStore / Elasticsearch surface —
    ``store.create(...)``, ``store.claim(...)`` etc. on a receiver named
    ``store``/``*_store`` (wrap in ``asyncio.to_thread``);
  * bare ``open()`` (read at startup, or ``asyncio.to_thread``).
"""

from __future__ import annotations

import ast

from foremast_tpu.analysis.core import Checker, Finding, Module

# exact dotted names
_BLOCKING_EXACT = {
    "time.sleep": "use `await asyncio.sleep(...)`",
    "os.system": "use `asyncio.create_subprocess_shell`",
    "os.popen": "use `asyncio.create_subprocess_shell`",
    "socket.create_connection": "use loop-native I/O (aiohttp / asyncio streams)",
}
# dotted-prefix families
_BLOCKING_PREFIXES = {
    "requests.": "use the app's aiohttp ClientSession",
    "subprocess.": "use `asyncio.create_subprocess_exec`",
    "urllib.request.": "use the app's aiohttp ClientSession",
}
# the synchronous JobStore/ES surface (jobs/store.py): calling any of
# these directly on the loop blocks on HTTP to Elasticsearch
_STORE_METHODS = frozenset(
    {
        "create",
        "get",
        "claim",
        "update",
        "update_many",
        "list_open",
        "count_open",
        "wait_ready",
        "ensure_index",
    }
)


def _dotted(node: ast.AST) -> str | None:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _store_receiver(node: ast.AST) -> str | None:
    """Name of a store-like receiver (`store`, `job_store`,
    `self.store`), or None."""
    if isinstance(node, ast.Attribute):
        name = node.attr
    elif isinstance(node, ast.Name):
        name = node.id
    else:
        return None
    if name == "store" or name.endswith("_store"):
        return name
    return None


class AsyncBlockingChecker(Checker):
    rule = "async-blocking"
    description = "blocking calls inside async def bodies (event-loop stalls)"
    scope = "repo"  # async test/bench helpers stall loops just as well

    def check(self, module: Module) -> list[Finding]:
        findings: list[Finding] = []
        for node in ast.walk(module.tree):
            if isinstance(node, ast.AsyncFunctionDef):
                findings.extend(self._check_async_fn(module, node))
        return findings

    def _check_async_fn(
        self, module: Module, fn: ast.AsyncFunctionDef
    ) -> list[Finding]:
        findings: list[Finding] = []
        for node in self._own_body_walk(fn):
            if isinstance(node, ast.Call):
                f = self._classify(module, fn, node)
                if f is not None:
                    findings.append(f)
        return findings

    @staticmethod
    def _own_body_walk(fn: ast.AsyncFunctionDef):
        """Walk the async function's body without descending into nested
        function definitions: nested async defs are visited on their own
        by `check`, and nested sync defs may target executor threads."""
        stack: list[ast.AST] = [
            stmt
            for stmt in fn.body
            if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        while stack:
            node = stack.pop()
            yield node
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                stack.append(child)

    def _classify(
        self, module: Module, fn: ast.AsyncFunctionDef, call: ast.Call
    ) -> Finding | None:
        func = call.func
        dotted = _dotted(func)
        if dotted:
            hint = _BLOCKING_EXACT.get(dotted)
            if hint is None:
                for prefix, h in _BLOCKING_PREFIXES.items():
                    if dotted.startswith(prefix):
                        hint = h
                        break
            if hint is not None:
                return module.finding(
                    self.rule,
                    call,
                    f"blocking call `{dotted}(...)` inside `async def "
                    f"{fn.name}` stalls the event loop",
                    hint=hint,
                )
        if isinstance(func, ast.Name) and func.id == "open":
            return module.finding(
                self.rule,
                call,
                f"blocking `open()` inside `async def {fn.name}` stalls "
                "the event loop",
                hint="read at startup, or wrap in `asyncio.to_thread`",
            )
        if isinstance(func, ast.Attribute) and func.attr in _STORE_METHODS:
            recv = _store_receiver(func.value)
            if recv is not None:
                return module.finding(
                    self.rule,
                    call,
                    f"sync store call `{recv}.{func.attr}(...)` inside "
                    f"`async def {fn.name}` blocks the event loop on "
                    "store I/O",
                    hint="wrap it: `await asyncio.to_thread("
                    f"{recv}.{func.attr}, ...)`",
                )
        return None
