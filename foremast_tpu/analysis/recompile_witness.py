"""Runtime recompile witness: pin the zero-warm-recompile contract.

The static rules (`recompile_hazard.py`) catch the *shapes* of dispatch
-cache blowups — unbucketed dims, unbounded statics, per-call `jax.jit`
— but the contract users feel is dynamic: after the cold tick has
compiled every bucket program, a warm tick must execute entirely from
XLA's compilation cache. A single warm-path recompile is a multi-second
latency cliff on TPU (PAPER.md §design: the health manager's verdict
cadence is the product surface), and nothing in tier-1 used to notice.

This module closes that loop the way `witness.py` does for lock order:

  * `install()` registers a ``jax.monitoring`` duration listener for
    the ``/jax/core/compile/backend_compile_duration`` event — fired
    once per ACTUAL backend compile, never on a cache hit — so the
    count is the ground truth the static rules approximate;
  * `phase("warm")` scopes counts to a named region: benches wrap the
    cold tick and the warm loop separately and assert the warm count is
    ZERO in-run (`benchmarks/latency_bench.py`,
    `benchmarks/mixed_bench.py`), and the counts land in the round's
    ``BENCH_rNN.json`` via `benchmarks.report.write_summary`'s
    ``recompiles`` field;
  * production workers run it under ``FOREMAST_RECOMPILE_WITNESS=1``
    (`cli.cmd_worker`), which logs the total compile count at exit —
    a warm fleet whose count keeps growing has a cache-key leak.

Everything jax-touching is imported lazily inside `install()`: the
static runner (`make check`) imports this package and must never pay —
or wedge on — an accelerator backend init (see `core.py`).
"""

from __future__ import annotations

import atexit
import contextlib
import logging
import os

log = logging.getLogger("foremast_tpu.analysis")

# one event per actual backend (XLA) compile; cache hits fire nothing
COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


class RecompileWitness:
    """Counts backend compiles, total and per named phase."""

    def __init__(self):
        self.total = 0
        self.phases: dict[str, int] = {}
        self._phase: str | None = None
        self._installed = False

    # -- the jax.monitoring listener -------------------------------------

    def _on_event(self, event: str, duration: float, **_kw) -> None:
        if not self._installed or not event.startswith(COMPILE_EVENT):
            return
        self.total += 1
        if self._phase is not None:
            self.phases[self._phase] = self.phases.get(self._phase, 0) + 1

    def install(self) -> "RecompileWitness":
        if not self._installed:
            try:
                from jax import monitoring
            except Exception:  # no jax: stay a zero-counting stub
                return self
            monitoring.register_event_duration_secs_listener(self._on_event)
            self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        # flip the flag first: even if the listener cannot be
        # unregistered (older jax keeps the private helper elsewhere),
        # a dead witness must stop counting
        self._installed = False
        try:
            from jax._src import monitoring as _m

            _m._unregister_event_duration_listener_by_callback(self._on_event)
        except Exception:
            pass

    # -- phases and counts -----------------------------------------------

    @contextlib.contextmanager
    def phase(self, name: str):
        """Attribute compiles inside the block to `name`. Phases do not
        nest (benches are sequential cold/warm regions); the previous
        phase resumes on exit."""
        prev, self._phase = self._phase, name
        try:
            yield self
        finally:
            self._phase = prev

    def count(self, phase: str | None = None) -> int:
        if phase is None:
            return self.total
        return self.phases.get(phase, 0)

    def snapshot(self) -> dict:
        """JSON-shaped counts for BENCH_rNN.json's `recompiles` field."""
        return {"total": self.total, **dict(sorted(self.phases.items()))}

    def assert_zero(self, phase: str) -> None:
        """The in-run bench gate: a warm phase that compiled ANYTHING is
        a dispatch-cache regression, not a slow run."""
        n = self.count(phase)
        assert n == 0, (
            f"recompile witness: {n} backend compile(s) during the "
            f"'{phase}' phase — the warm path must run entirely from the "
            f"dispatch cache (docs/static-analysis.md, rule "
            f"recompile-hazard); counts: {self.snapshot()}"
        )


# ---------------------------------------------------------------------------
# module-level lifecycle (mirrors analysis/witness.py)
# ---------------------------------------------------------------------------

_current: RecompileWitness | None = None


def install() -> RecompileWitness:
    """Install (or return the already-installed) process witness."""
    global _current
    if _current is None:
        _current = RecompileWitness()
    return _current.install()


def uninstall() -> None:
    global _current
    if _current is not None:
        _current.uninstall()
        _current = None


def current() -> RecompileWitness | None:
    return _current


def install_from_env(env=None) -> RecompileWitness | None:
    """`FOREMAST_RECOMPILE_WITNESS=1` wiring for long-lived entry
    points (cli worker): install before the first dispatch, log the
    compile count at interpreter exit — never raise."""
    e = os.environ if env is None else env
    if e.get("FOREMAST_RECOMPILE_WITNESS", "") != "1":
        return None
    witness = install()

    def _report():
        log.info(
            "recompile witness: %d backend compile(s) this process "
            "(a warm fleet whose count keeps growing has a dispatch "
            "cache-key leak): %s",
            witness.total, witness.snapshot(),
        )

    atexit.register(_report)
    return witness
