"""foremast-check: the repo's correctness contracts as machine-checked rules.

The codebase encodes several invariants that survive only as docstrings
and review lore: nothing host-syncing inside the jitted judgment
(CONTRIBUTING.md "everything under jit stays fixed-shape"), nothing
blocking on the aiohttp event loops, every lock-owning class touching its
guarded state only under the lock, and every env knob declared in ONE
registry so the config surface stays enumerable. ROADMAP.md explicitly
invites aggressive refactoring, which is exactly how such invariants die
silently — so this package turns them into AST-level checkers with a
single gated runner:

    python -m foremast_tpu.analysis        # or `make check`

Architecture (core.py): each checker is a pure function of a parsed
``Module`` (no imports of the checked code, no jax — the runner never
dials an accelerator), emitting ``Finding``s with file:line, a stable
rule ID, and a fix hint. Per-line ``# foremast: ignore[rule]``
suppressions mark *deliberate* exceptions in place; the committed
``analysis_baseline.json`` grandfathers pre-existing findings without
letting new ones in. The runner folds in the metric naming lint
(observe/metrics_lint.py — the bespoke precedent this generalizes) and
exits non-zero on any new finding, which a tier-1 test enforces.

Rules: jit-hygiene, async-blocking, lock-discipline, env-contract,
metrics-contract (per-module); lock-order, thread-escape,
blocking-under-lock, device-flow, recompile-hazard, sharding-contract,
status-machine (whole-program, over `interproc.Program`'s cross-module
call resolution — they model the threaded data plane and the device
boundary the per-class rules cannot see); metrics-lint (registry
fold-in). Two graphs are committed and drift-gated: the static
lock-acquisition graph (``analysis_lockgraph.json``, cross-checked at
runtime by ``analysis/witness.py`` / FOREMAST_LOCK_WITNESS) and the doc
status transition graph (``analysis_statusgraph.json``, rule
status-machine). The device-side twin of the lock witness is
``analysis/recompile_witness.py`` (FOREMAST_RECOMPILE_WITNESS): it
counts actual backend compiles per phase so the benches can assert the
warm path never recompiles — the runtime witness for what
recompile-hazard checks statically. See docs/static-analysis.md.
"""

from __future__ import annotations

from foremast_tpu.analysis.core import (
    Baseline,
    Checker,
    Finding,
    Module,
    analyze_modules,
    analyze_source,
    collect_modules,
    repo_root,
)


def all_checkers() -> list[Checker]:
    """One instance of every per-module AST checker, in report order.
    The whole-program concurrency rules (lock-order, thread-escape,
    blocking-under-lock) live outside this list — they need the
    complete package and run from the default full scan only
    (`__main__.program_findings`)."""
    from foremast_tpu.analysis.async_blocking import AsyncBlockingChecker
    from foremast_tpu.analysis.env_contract import EnvContractChecker
    from foremast_tpu.analysis.jit_hygiene import JitHygieneChecker
    from foremast_tpu.analysis.lock_discipline import LockDisciplineChecker
    from foremast_tpu.analysis.metrics_contract import MetricsContractChecker

    return [
        JitHygieneChecker(),
        AsyncBlockingChecker(),
        LockDisciplineChecker(),
        EnvContractChecker(),
        MetricsContractChecker(),
    ]


__all__ = [
    "Baseline",
    "Checker",
    "Finding",
    "Module",
    "all_checkers",
    "analyze_modules",
    "analyze_source",
    "collect_modules",
    "repo_root",
]
