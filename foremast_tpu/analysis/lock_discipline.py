"""lock-discipline: unlocked access to lock-guarded state, env mutation.

The long-lived worker is a small thread swarm: the tick thread, the
ThreadingHTTPServer varz/scrape handlers, the trace autoflush daemon,
metric-fetch pool workers. The classes they share (jobs/store.py,
models/cache.py, observe/spans.py, observe/gauges.py) each own a
``threading.Lock``; the contract — every access to the guarded state
goes through the lock — lives only in docstrings, where a refactor can
silently break it.

This checker makes the contract structural. For every class that
assigns a ``threading.Lock``/``RLock`` to an attribute, the guarded set
is INFERRED: attributes *written or mutated* (assignment, augmented
assignment, ``self.x[k] = v``, ``del``, or a mutating method call like
``.append``/``.pop``/``.update``) inside a ``with self._lock:`` block.
Any read or write of a guarded attribute outside a locked region, in
any method but ``__init__`` (construction happens-before sharing), is a
finding. Deliberate lock-free fast paths (e.g. ``ModelCache.peek``)
carry a ``# foremast: ignore[lock-discipline]`` with their
justification — the suppression is the documentation.

Module-level locks get the same treatment for ``global``-declared names
(native.py's loader state), with nested function bodies conservatively
treated as NOT holding the lock of their definition site (they run when
called, not when defined).

Separately, ``os.environ`` WRITES anywhere in the package are flagged:
CPython's environ mutation is not thread-safe against concurrent
readers, and a knob change after threads start (the bug fixed in
parallel/distributed.py) reaches only code that happens to re-read the
env — plumb explicit setters instead.
"""

from __future__ import annotations

import ast
import dataclasses

from foremast_tpu.analysis.core import Checker, Finding, Module

_LOCK_FACTORIES = frozenset(
    {"threading.Lock", "threading.RLock", "Lock", "RLock"}
)
_MUTATORS = frozenset(
    {
        "append",
        "appendleft",
        "extend",
        "insert",
        "add",
        "remove",
        "discard",
        "pop",
        "popitem",
        "clear",
        "update",
        "setdefault",
        "move_to_end",
        "sort",
        "reverse",
        "inc",
        "dec",
        "set",
        "observe",
    }
)
_ENV_WRITE_CALLS = frozenset({"update", "setdefault", "pop", "clear", "popitem"})


def _dotted(node: ast.AST) -> str | None:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_lock_factory(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and not node.args
        and _dotted(node.func) in _LOCK_FACTORIES
    )


def _self_attr(node: ast.AST) -> str | None:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _subscript_base_attr(node: ast.Subscript) -> str | None:
    base = node.value
    while isinstance(base, ast.Subscript):
        base = base.value
    return _self_attr(base)


@dataclasses.dataclass
class _Access:
    attr: str
    node: ast.AST
    write: bool
    locked: bool
    method: str


class _ClassScanner(ast.NodeVisitor):
    """Collect self-attribute accesses with their locked-ness for one
    class body."""

    def __init__(self, lock_attrs: set[str]):
        self.lock_attrs = lock_attrs
        self.accesses: list[_Access] = []
        self._locked = 0
        self._method = ""

    def scan_method(self, fn: ast.FunctionDef) -> None:
        self._method = fn.name
        for stmt in fn.body:
            self.visit(stmt)

    # -- lock regions ----------------------------------------------------

    def _with_holds_lock(self, node: ast.With | ast.AsyncWith) -> bool:
        for item in node.items:
            attr = _self_attr(item.context_expr)
            if attr in self.lock_attrs:
                return True
        return False

    def _visit_with(self, node):
        holds = self._with_holds_lock(node)
        for item in node.items:
            self.visit(item.context_expr)
        if holds:
            self._locked += 1
        for stmt in node.body:
            self.visit(stmt)
        if holds:
            self._locked -= 1

    visit_With = _visit_with
    visit_AsyncWith = _visit_with

    def _visit_nested_fn(self, node):
        # a nested def runs when CALLED, not where defined: its body must
        # not inherit the definition site's lock state
        saved = self._locked
        self._locked = 0
        for stmt in node.body:
            self.visit(stmt)
        self._locked = saved

    visit_FunctionDef = _visit_nested_fn
    visit_AsyncFunctionDef = _visit_nested_fn

    # -- accesses --------------------------------------------------------

    def _record(self, attr: str | None, node: ast.AST, write: bool) -> None:
        if attr is None or attr in self.lock_attrs:
            return
        self.accesses.append(
            _Access(attr, node, write, self._locked > 0, self._method)
        )

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = _self_attr(node)
        if attr is not None:
            self._record(attr, node, isinstance(node.ctx, (ast.Store, ast.Del)))
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        attr = _self_attr(node.target)
        if attr is not None:
            self._record(attr, node, True)
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            self._record(_subscript_base_attr(node), node, True)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr in _MUTATORS:
            self._record(_self_attr(func.value), node, True)
        self.generic_visit(node)


class _ModuleLockScanner(ast.NodeVisitor):
    """Same idea for module-level locks guarding `global`-declared names."""

    def __init__(self, lock_names: set[str], module_names: set[str]):
        self.lock_names = lock_names
        self.module_names = module_names
        self.accesses: list[_Access] = []
        self._locked = 0
        self._fn = ""
        self._globals: set[str] = set()
        self._locals: set[str] = set()

    def scan_function(self, fn: ast.FunctionDef) -> None:
        self._fn = fn.name
        self._globals = set()
        self._locals = {a.arg for a in ast.walk(fn) if isinstance(a, ast.arg)}
        for node in ast.walk(fn):
            if isinstance(node, ast.Global):
                self._globals.update(node.names)
            elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
                self._locals.add(node.id)
        self._locals -= self._globals
        for stmt in fn.body:
            self.visit(stmt)

    def _visit_with(self, node):
        holds = any(
            isinstance(item.context_expr, ast.Name)
            and item.context_expr.id in self.lock_names
            for item in node.items
        )
        if holds:
            self._locked += 1
        for stmt in node.body:
            self.visit(stmt)
        if holds:
            self._locked -= 1

    visit_With = _visit_with
    visit_AsyncWith = _visit_with

    def _visit_nested_fn(self, node):
        saved = self._locked
        self._locked = 0
        for stmt in node.body:
            self.visit(stmt)
        self._locked = saved

    visit_FunctionDef = _visit_nested_fn
    visit_AsyncFunctionDef = _visit_nested_fn

    def visit_Name(self, node: ast.Name) -> None:
        name = node.id
        if (
            name in self.module_names
            and name not in self.lock_names
            and name not in self._locals
        ):
            write = isinstance(node.ctx, (ast.Store, ast.Del))
            if write and name not in self._globals:
                return  # a plain Store without `global` is a new local
            self.accesses.append(
                _Access(name, node, write, self._locked > 0, self._fn)
            )
        self.generic_visit(node)


class LockDisciplineChecker(Checker):
    rule = "lock-discipline"
    description = (
        "lock-guarded attributes accessed without the lock; runtime "
        "os.environ mutation"
    )

    def check(self, module: Module) -> list[Finding]:
        findings: list[Finding] = []
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class(module, node))
        findings.extend(self._check_module_locks(module))
        findings.extend(self._check_env_writes(module))
        return findings

    # -- classes ---------------------------------------------------------

    def _check_class(self, module: Module, cls: ast.ClassDef) -> list[Finding]:
        methods = [
            n
            for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        lock_attrs: set[str] = set()
        for fn in methods:
            for node in ast.walk(fn):
                if (
                    isinstance(node, ast.Assign)
                    and _is_lock_factory(node.value)
                ):
                    for target in node.targets:
                        attr = _self_attr(target)
                        if attr is not None:
                            lock_attrs.add(attr)
        if not lock_attrs:
            return []
        scanner = _ClassScanner(lock_attrs)
        for fn in methods:
            if fn.name == "__init__":
                continue  # construction happens-before sharing
            scanner.scan_method(fn)
        guarded = {
            a.attr for a in scanner.accesses if a.locked and a.write
        }
        findings = []
        for a in scanner.accesses:
            if a.attr in guarded and not a.locked:
                kind = "write to" if a.write else "read of"
                findings.append(
                    module.finding(
                        self.rule,
                        a.node,
                        f"unlocked {kind} `self.{a.attr}` in "
                        f"`{cls.name}.{a.method}` (guarded by "
                        f"{'/'.join(sorted(lock_attrs))} elsewhere)",
                        hint="take the lock, or mark a deliberate "
                        "lock-free path with "
                        "`# foremast: ignore[lock-discipline]` and say why",
                    )
                )
        return findings

    # -- module-level locks ----------------------------------------------

    def _check_module_locks(self, module: Module) -> list[Finding]:
        lock_names: set[str] = set()
        module_names: set[str] = set()
        for stmt in module.tree.body:
            targets: list[ast.AST] = []
            if isinstance(stmt, ast.Assign):
                targets = stmt.targets
                if _is_lock_factory(stmt.value):
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            lock_names.add(t.id)
            elif isinstance(stmt, ast.AnnAssign):
                targets = [stmt.target]
            for t in targets:
                if isinstance(t, ast.Name):
                    module_names.add(t.id)
        if not lock_names:
            return []
        scanner = _ModuleLockScanner(lock_names, module_names)
        for stmt in module.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scanner.scan_function(stmt)
        guarded = {a.attr for a in scanner.accesses if a.locked and a.write}
        findings = []
        for a in scanner.accesses:
            if a.attr in guarded and not a.locked:
                kind = "write to" if a.write else "read of"
                findings.append(
                    module.finding(
                        self.rule,
                        a.node,
                        f"unlocked {kind} module global `{a.attr}` in "
                        f"`{a.method}` (guarded by "
                        f"{'/'.join(sorted(lock_names))} elsewhere)",
                        hint="take the module lock, or suppress a "
                        "deliberate racy read with "
                        "`# foremast: ignore[lock-discipline]`",
                    )
                )
        return findings

    # -- os.environ writes -----------------------------------------------

    def _check_env_writes(self, module: Module) -> list[Finding]:
        from foremast_tpu.analysis.core import os_import_aliases

        # bare `environ` only counts when imported from os (a WSGI
        # handler's `environ` dict is not the process environment)
        environ_names = {"os.environ"} | set(
            os_import_aliases(module.tree, "environ")
        )
        write_calls = {
            f"{base}.{m}" for base in environ_names for m in _ENV_WRITE_CALLS
        }
        findings = []
        for node in ast.walk(module.tree):
            msg = None
            if isinstance(node, ast.Subscript) and isinstance(
                node.ctx, (ast.Store, ast.Del)
            ):
                if _dotted(node.value) in environ_names:
                    msg = "os.environ item assignment"
            elif isinstance(node, ast.Call):
                dotted = _dotted(node.func)
                if dotted in ("os.putenv", "os.unsetenv"):
                    msg = f"`{dotted}` call"
                elif dotted in write_calls:
                    msg = f"`{dotted}` call"
            if msg is not None:
                findings.append(
                    module.finding(
                        self.rule,
                        node,
                        f"{msg} mutates process env at runtime — a "
                        "cross-thread race that only reaches code which "
                        "re-reads the env",
                        hint="plumb an explicit value (setter / argument) "
                        "instead; see engine.arena.set_arena_budget and "
                        "engine.scoring.set_bf16_delta",
                    )
                )
        return findings
