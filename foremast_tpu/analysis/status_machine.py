"""status-machine: the doc status transition graph, pinned as an artifact.

The exactly-once ledger every chaos/sweep test asserts DYNAMICALLY
(zero lost, zero duplicated verdicts) rests on a static invariant
nobody had written down: every `.status` write in the worker/store path
is one of a small set of legal transitions, and every claim path ends
— even through its exception edges — at a terminal write or a release
back to claimable. This module extracts that machine from the code the
way `lock_order.py` extracts the lock graph, and commits it:

  * the STATUS REGISTRY comes from `jobs/models.py` (the ``STATUS_*``
    constants plus the ``TERMINAL_STATUSES`` / ``INPROGRESS_STATUSES``
    / ``CLAIMABLE_STATUSES`` classification sets — byte-compatible
    with the reference service's converter.go, so the registry IS the
    wire contract);
  * the LEGAL TRANSITIONS derive from the classification sets: every
    claimable status may move to ``preprocess_inprogress`` (the claim
    CAS, stuck takeover included), and an in-progress status may move
    to any terminal status (judged / failed / aborted) or to
    ``preprocess_completed`` (the release — REASON_* sentinels in
    `chaos/degrade.py` stamp WHY, the status write itself is always
    the same re-claimable state);
  * every WRITE SITE (``<recv>.status = <expr>`` in the jobs/ modules
    and `chaos/degrade.py`) is recorded with its guard-derived
    from-set: a write dominated by an ``x.status in (A, B)`` test
    contributes the edges ``A -> to`` and ``B -> to``; an unguarded
    write must target a status that is a legal transition TARGET;
  * the whole machine is COMMITTED as ``analysis_statusgraph.json``
    with the same drift gate as the lock graph (`make statusgraph`
    regenerates; a stale artifact is a finding), so a new status,
    write site, or transition is a reviewable diff;
  * findings: a write of a raw string literal (drift the registry
    cannot see), a DYNAMIC write (computed status values defeat the
    whole analysis), a write outside the legal transition set, and —
    the static form of exactly-once — a CLAIM PATH whose exception
    edges reach neither a terminal write nor a release: a function
    that (transitively) claims and settles must either contain a
    ``try`` whose handler/finally settles (the `_sweep_sliced` shape:
    ``finally: _release_docs(rest, REASON_ABORT, ...)``) or delegate
    the claim-to-settle span to a callee that does (the `_tick` →
    `_run_slow_chunks` shape).
"""

from __future__ import annotations

import ast
import json
import os

from foremast_tpu.analysis.core import Finding
from foremast_tpu.analysis.interproc import (
    FunctionInfo,
    Program,
    own_body_walk,
)

RULE = "status-machine"
GRAPH_NAME = "analysis_statusgraph.json"
GRAPH_VERSION = 1

# modules whose `.status` writes are DOC status writes (mesh membership
# and chaos-plan objects have their own unrelated status fields)
WRITE_SCOPE = ("foremast_tpu/jobs/", "foremast_tpu/chaos/degrade.py")
REGISTRY_SETS = (
    "TERMINAL_STATUSES",
    "INPROGRESS_STATUSES",
    "CLAIMABLE_STATUSES",
)


class StatusRegistry:
    """STATUS_* constants + classification sets, parsed from the module
    that defines ``TERMINAL_STATUSES`` (jobs/models.py in the real
    tree, a fixture module in tests)."""

    def __init__(self, names: dict[str, str], sets: dict[str, set[str]]):
        self.names = names              # STATUS_X -> "value"
        self.values = set(names.values())
        self.terminal = sets.get("TERMINAL_STATUSES", set())
        self.inprogress = sets.get("INPROGRESS_STATUSES", set())
        self.claimable = sets.get("CLAIMABLE_STATUSES", set())

    @classmethod
    def from_program(cls, program: Program) -> "StatusRegistry | None":
        for module in program.modules:
            names: dict[str, str] = {}
            set_nodes: dict[str, ast.AST] = {}
            for stmt in module.tree.body:
                if not (
                    isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                ):
                    continue
                t = stmt.targets[0]
                if not isinstance(t, ast.Name):
                    continue
                if (
                    t.id.startswith("STATUS_")
                    and isinstance(stmt.value, ast.Constant)
                    and isinstance(stmt.value.value, str)
                ):
                    names[t.id] = stmt.value.value
                elif t.id in REGISTRY_SETS:
                    set_nodes[t.id] = stmt.value
            if "TERMINAL_STATUSES" not in set_nodes:
                continue
            sets = {
                key: _resolve_status_set(node, names, sets_so_far={})
                for key, node in set_nodes.items()
            }
            # second pass for starred references between the sets
            # (CLAIMABLE = (INITIAL, COMPLETED, *INPROGRESS))
            sets = {
                key: _resolve_status_set(node, names, sets_so_far=sets)
                for key, node in set_nodes.items()
            }
            return cls(names, sets)
        return None

    def legal_transitions(self) -> list[dict]:
        """Edges derived from the classification sets (see module
        docstring): claim edges + judge/fail/abort edges + release."""
        edges: list[dict] = []
        inprog = "preprocess_inprogress"
        for s in sorted(self.claimable):
            edges.append({"from": s, "to": inprog, "via": "claim"})
        for s in sorted(self.inprogress):
            for t in sorted(self.terminal):
                edges.append({"from": s, "to": t, "via": "judge"})
            edges.append(
                {"from": s, "to": "preprocess_completed", "via": "release"}
            )
        return edges

    def legal_pairs(self) -> set[tuple[str, str]]:
        return {(e["from"], e["to"]) for e in self.legal_transitions()}


def _resolve_status_set(
    node: ast.AST, names: dict[str, str], sets_so_far: dict
) -> set[str]:
    """frozenset({...}) / tuple / set / list of STATUS_* names, string
    constants, and `*OTHER_SET` splices."""
    if isinstance(node, ast.Call) and node.args:
        return _resolve_status_set(node.args[0], names, sets_so_far)
    out: set[str] = set()
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        for elt in node.elts:
            if isinstance(elt, ast.Starred) and isinstance(
                elt.value, ast.Name
            ):
                out |= sets_so_far.get(elt.value.id, set())
            elif isinstance(elt, ast.Name) and elt.id in names:
                out.add(names[elt.id])
            elif isinstance(elt, ast.Constant) and isinstance(
                elt.value, str
            ):
                out.add(elt.value)
    return out


# ---------------------------------------------------------------------------
# write-site extraction
# ---------------------------------------------------------------------------


def _status_value(expr: ast.AST, registry: StatusRegistry) -> list[str] | None:
    """The status value(s) an assignment RHS denotes: a STATUS_* name,
    a raw string, or a conditional over those. None = dynamic."""
    if isinstance(expr, ast.Name) and expr.id in registry.names:
        return [registry.names[expr.id]]
    if isinstance(expr, ast.Attribute) and expr.attr in registry.names:
        return [registry.names[expr.attr]]  # models.STATUS_X
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return [expr.value]
    if isinstance(expr, ast.IfExp):
        a = _status_value(expr.body, registry)
        b = _status_value(expr.orelse, registry)
        if a is not None and b is not None:
            return a + b
    return None


def _guard_statuses(
    test: ast.AST, registry: StatusRegistry
) -> list[str] | None:
    """`x.status in (A, B)` -> [a, b]; `x.status == A` -> [a]."""
    if not (isinstance(test, ast.Compare) and len(test.ops) == 1):
        return None
    left = test.left
    if not (isinstance(left, ast.Attribute) and left.attr == "status"):
        return None
    comp = test.comparators[0]
    if isinstance(test.ops[0], ast.In):
        vals = _resolve_status_set(comp, registry.names, {})
        return sorted(vals) if vals else None
    if isinstance(test.ops[0], ast.Eq):
        v = _status_value(comp, registry)
        return v
    return None


def collect_writes(program: Program, registry: StatusRegistry) -> list[dict]:
    """Every `.status = <expr>` write in scope, with its site, target
    value(s) ("?" = dynamic, the finding pass flags it) and guard-
    derived from-set (["*"] = unguarded)."""
    writes: list[dict] = []
    for fn in program.functions:
        if not fn.module.relpath.startswith(WRITE_SCOPE):
            continue
        _walk_writes(fn, fn.node.body, ["*"], registry, writes)
    writes.sort(key=lambda w: (w["site"], w["status"]))
    return writes


def _walk_writes(
    fn: FunctionInfo, body, fromset: list[str], registry, writes
) -> None:
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if isinstance(t, ast.Attribute) and t.attr == "status":
                    vals = _status_value(stmt.value, registry)
                    for v in vals if vals is not None else ["?"]:
                        writes.append(
                            {
                                "site": fn.site(stmt),
                                "fn": fn.qualname,
                                "status": v,
                                "from": list(fromset),
                            }
                        )
        inner = fromset
        if isinstance(stmt, (ast.If, ast.While)):
            guard = _guard_statuses(stmt.test, registry)
            if guard is not None:
                inner = guard
        for field, value in ast.iter_fields(stmt):
            if not (isinstance(value, list) and value):
                continue
            if isinstance(value[0], ast.stmt):
                # the guard narrows only the THEN branch
                scope = inner if field == "body" else fromset
                _walk_writes(fn, value, scope, registry, writes)
            elif isinstance(value[0], ast.excepthandler):
                for h in value:
                    _walk_writes(fn, h.body, fromset, registry, writes)
            elif hasattr(value[0], "body") and isinstance(
                getattr(value[0], "body", None), list
            ):
                for case in value:
                    _walk_writes(fn, case.body, fromset, registry, writes)


# ---------------------------------------------------------------------------
# claim-path protection (the exactly-once exception edge)
# ---------------------------------------------------------------------------


class _ClaimAnalysis:
    """Fixpoint summaries: which functions (transitively) CLAIM and
    which (transitively) SETTLE (write a terminal status or release to
    preprocess_completed)."""

    def __init__(self, program: Program, registry: StatusRegistry):
        self.program = program
        self.registry = registry
        self.claims: set[int] = set()
        self.settles: set[int] = set()
        self._settle_values = registry.terminal | {"preprocess_completed"}
        self._compute()

    def _writes_settle(self, fn: FunctionInfo) -> bool:
        for node in own_body_walk(fn.node):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and t.attr == "status":
                        vals = _status_value(node.value, self.registry)
                        if vals and set(vals) & self._settle_values:
                            return True
        return False

    @staticmethod
    def _calls_claim(fn: FunctionInfo) -> bool:
        for node in own_body_walk(fn.node):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "claim"
            ):
                return True
        return False

    def _compute(self) -> None:
        for fn in self.program.functions:
            if self._calls_claim(fn):
                self.claims.add(id(fn))
            if self._writes_settle(fn):
                self.settles.add(id(fn))
        changed = True
        while changed:
            changed = False
            for fn in self.program.functions:
                for node in own_body_walk(fn.node):
                    if not isinstance(node, ast.Call):
                        continue
                    for callee in self.program.resolve_call_direct(node, fn):
                        if id(callee) in self.claims and id(fn) not in self.claims:
                            self.claims.add(id(fn))
                            changed = True
                        if (
                            id(callee) in self.settles
                            and id(fn) not in self.settles
                        ):
                            self.settles.add(id(fn))
                            changed = True

    def _try_protected(self, fn: FunctionInfo) -> bool:
        """A `try` in `fn` whose finally/handler (transitively)
        settles."""

        def body_settles(body) -> bool:
            for stmt in body:
                for node in ast.walk(stmt):
                    if isinstance(node, ast.Assign):
                        for t in node.targets:
                            if (
                                isinstance(t, ast.Attribute)
                                and t.attr == "status"
                            ):
                                vals = _status_value(
                                    node.value, self.registry
                                )
                                if vals and set(vals) & self._settle_values:
                                    return True
                    elif isinstance(node, ast.Call):
                        for callee in self.program.resolve_call_direct(node, fn):
                            if id(callee) in self.settles:
                                return True
            return False

        for node in own_body_walk(fn.node):
            if not isinstance(node, ast.Try):
                continue
            if node.finalbody and body_settles(node.finalbody):
                return True
            for h in node.handlers:
                if body_settles(h.body):
                    return True
        return False

    def _owns_span(self, fn: FunctionInfo) -> bool:
        """True when `fn` is the frame where a BARE claim meets the
        settle obligation: it settles (transitively) and either calls
        `.claim` itself or calls a callee that claims without settling.
        A frame whose claiming callees all settle too merely wraps a
        lower owner — reporting every frame of the call cone (or of a
        tick/preemption cycle, where each member trivially inherits
        claims AND settles from the next) would turn one contract gap
        into a dozen findings at frames that cannot fix it."""
        if id(fn) not in self.settles:
            return False
        if self._calls_claim(fn):
            return True
        for node in own_body_walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            for c in self.program.resolve_call_direct(node, fn):
                if id(c) in self.claims and id(c) not in self.settles:
                    return True
        return False

    def unprotected_owners(self) -> list[FunctionInfo]:
        """Span owners (see `_owns_span`) with no protected exception
        edge: no settling try of their own and no call into a
        compliant callee to delegate the span to."""
        compliant: set[int] = set()
        for fn in self.program.functions:
            if id(fn) in self.settles and self._try_protected(fn):
                compliant.add(id(fn))
        changed = True
        while changed:
            changed = False
            for fn in self.program.functions:
                if id(fn) in compliant:
                    continue
                for node in own_body_walk(fn.node):
                    if not isinstance(node, ast.Call):
                        continue
                    if any(
                        id(c) in compliant
                        for c in self.program.resolve_call_direct(node, fn)
                    ):
                        compliant.add(id(fn))
                        changed = True
                        break
        return [
            fn
            for fn in self.program.functions
            if self._owns_span(fn)
            and id(fn) not in compliant
            and fn.module.relpath.startswith(WRITE_SCOPE)
        ]


# ---------------------------------------------------------------------------
# the artifact + the gate
# ---------------------------------------------------------------------------


def build_graph(program: Program) -> dict | None:
    registry = StatusRegistry.from_program(program)
    if registry is None:
        return None
    return {
        "version": GRAPH_VERSION,
        "comment": (
            "Doc status transition graph (rule: status-machine). "
            "`statuses` is the jobs/models.py registry with its "
            "classification flags; `transitions` is the legal edge set "
            "derived from it (claim/judge/release); `writes` is every "
            "`.status =` site in the worker/store/degrade path with its "
            "guard-derived from-set. Regenerate with `make statusgraph`; "
            "the default run fails when this drifts from the computed "
            "graph. docs/static-analysis.md"
        ),
        "statuses": [
            {
                "name": name,
                "value": value,
                "terminal": value in registry.terminal,
                "claimable": value in registry.claimable,
                "inprogress": value in registry.inprogress,
            }
            for name, value in sorted(registry.names.items())
        ],
        "transitions": registry.legal_transitions(),
        "writes": collect_writes(program, registry),
    }


def graph_path(root: str) -> str:
    return os.path.join(root, GRAPH_NAME)


def write_graph(root: str, graph: dict) -> None:
    with open(graph_path(root), "w", encoding="utf-8") as f:
        json.dump(graph, f, indent=2, sort_keys=True)
        f.write("\n")


def load_graph(root: str) -> dict | None:
    path = graph_path(root)
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def _normalize(graph: dict) -> tuple:
    return (
        graph.get("version"),
        tuple(
            (s["name"], s["value"], s["terminal"], s["claimable"],
             s["inprogress"])
            for s in sorted(
                graph.get("statuses", ()), key=lambda s: s["name"]
            )
        ),
        tuple(
            (e["from"], e["to"], e["via"])
            for e in sorted(
                graph.get("transitions", ()),
                key=lambda e: (e["from"], e["to"], e["via"]),
            )
        ),
        tuple(
            (w["site"], w["fn"], w["status"], tuple(w["from"]))
            for w in sorted(
                graph.get("writes", ()),
                key=lambda w: (w["site"], w["status"]),
            )
        ),
    )


def check_status_machine(root: str, program: Program) -> list[Finding]:
    registry = StatusRegistry.from_program(program)
    if registry is None:
        return []  # no status registry in scope (path-scoped fixture run)
    findings: list[Finding] = []
    legal = registry.legal_pairs()
    legal_targets = {to for _f, to in legal}

    for w in collect_writes(program, registry):
        path, _, line = w["site"].partition(":")
        line = int(line or 1)
        if w["status"] == "?":
            findings.append(
                Finding(
                    rule=RULE,
                    path=path,
                    line=line,
                    message=f"dynamic status write in `{w['fn']}` — a "
                    "computed status value defeats the transition-graph "
                    "analysis (and the exactly-once ledger it encodes)",
                    hint="assign one of the STATUS_* constants (branch on "
                    "the condition, not on the value)",
                )
            )
            continue
        if w["status"] not in registry.values:
            findings.append(
                Finding(
                    rule=RULE,
                    path=path,
                    line=line,
                    message=f"unknown status `{w['status']}` written in "
                    f"`{w['fn']}` — not in the jobs/models.py registry "
                    "(the wire contract with the reference service)",
                    hint="use a STATUS_* constant; new statuses must be "
                    "added to models.py and `make statusgraph` re-run",
                )
            )
            continue
        _check_write_legality(w, registry, legal, legal_targets, findings,
                              path, line)

    for fn in _ClaimAnalysis(program, registry).unprotected_owners():
        findings.append(
            Finding(
                rule=RULE,
                path=fn.module.relpath,
                line=fn.node.lineno,
                message=f"claim path `{fn.qualname}` has an exception edge "
                "that reaches neither a terminal status write nor a "
                "release — a crash mid-path strands claimed docs until "
                "the stuck-takeover window",
                hint="wrap the claim-to-settle span in try/finally (or an "
                "except edge) that releases unjudged docs "
                "(`_release_docs` -> preprocess_completed), or delegate "
                "to a helper that does — the `_sweep_sliced` shape",
            )
        )

    findings.extend(_artifact_findings(root, program))
    return findings


def _check_write_legality(
    w, registry, legal, legal_targets, findings, path, line
) -> None:
    if w["status"] == "initial":
        # doc (re)creation — constructors set it, `.status =` never
        # legally does: nothing transitions BACK to fresh work
        froms = ["(any)"]
    elif w["from"] == ["*"]:
        froms = [] if w["status"] in legal_targets else ["(unguarded)"]
    else:
        froms = [s for s in w["from"] if (s, w["status"]) not in legal]
    if froms:
        findings.append(
            Finding(
                rule=RULE,
                path=path,
                line=line,
                message=f"illegal status transition {froms} -> "
                f"`{w['status']}` written in `{w['fn']}` — outside the "
                "legal set (claim -> in-progress -> judged/released/"
                "terminal)",
                hint="see `transitions` in analysis_statusgraph.json; if "
                "the machine legitimately grew, change jobs/models.py's "
                "classification sets and re-run `make statusgraph`",
            )
        )


def _artifact_findings(root: str, program: Program) -> list[Finding]:
    computed = build_graph(program)
    if computed is None:
        return []
    committed = load_graph(root)
    if committed is None:
        return [
            Finding(
                rule=RULE,
                path=GRAPH_NAME,
                line=1,
                message=f"{GRAPH_NAME} missing — the status transition "
                "graph must be committed so state-machine changes are "
                "reviewable diffs",
                hint="run `make statusgraph` and commit the artifact",
            )
        ]
    if _normalize(committed) != _normalize(computed):
        return [
            Finding(
                rule=RULE,
                path=GRAPH_NAME,
                line=1,
                message=f"committed {GRAPH_NAME} is stale vs the computed "
                "status graph (statuses, transitions or write sites "
                "changed)",
                hint="run `make statusgraph`, review the diff, and commit "
                "it",
            )
        ]
    return []
