"""lock-order: the static lock-acquisition graph, pinned as an artifact.

Two of the last three review-hardening rounds were lock-ordering bugs
found by reading diffs (the journal-outside-shard-lock inversion, the
Tracer flush-flag race). This rule makes the ordering structural:

  * every ``threading.Lock``/``RLock`` the package creates is a NODE
    (per creation site — all shard locks share one identity, because
    the ordering contract is per-site, not per-object);
  * an EDGE ``A -> B`` means "B may be acquired while A is held",
    computed over the whole package with the interprocedural resolver
    (`analysis/interproc.py`): direct nesting, calls through typed
    attributes, and the callback table (`store.journal = ...`,
    ``claim(claim_filter=...)``) all contribute;
  * a CYCLE is a finding — two threads walking the cycle from
    different ends deadlock;
  * the graph is COMMITTED as ``analysis_lockgraph.json`` and the
    default run fails when the computed graph drifts from the artifact
    (`make lockgraph` regenerates it) — so every ordering change shows
    up as a reviewable diff, the way `make env-docs` pins the knob
    table.

The static graph is deliberately a SUPERSET of runtime behavior (the
resolver over-approximates); the runtime witness
(`analysis/witness.py`) closes the loop from the other side by
asserting every OBSERVED acquisition edge exists here.

Self-edges on RLocks are reentrancy, not deadlock, and are recorded in
the artifact (``reentrant``) but excluded from cycle detection. A
self-edge on a plain Lock is an immediate single-thread deadlock and
always a finding.
"""

from __future__ import annotations

import ast
import json
import os

from foremast_tpu.analysis.core import Finding
from foremast_tpu.analysis.interproc import FunctionInfo, Program

RULE = "lock-order"
GRAPH_NAME = "analysis_lockgraph.json"
GRAPH_VERSION = 1


def build_graph(program: Program) -> dict:
    """The lock graph as the JSON-shaped dict the artifact stores:
    ``nodes`` (id, kind, site) and ``edges`` (from, to, via = one
    example site of the inner acquisition/call), both sorted."""
    edges: dict[tuple[str, str], str] = {}
    reentrant: dict[str, str] = {}

    def record(outer, inner, site: str):
        if outer.name == inner.name:
            if inner.kind == "RLock":
                reentrant.setdefault(inner.name, site)
                return
        edges.setdefault((outer.name, inner.name), site)

    for fn in program.functions:
        _walk_function(program, fn, record)

    nodes = [
        {"id": lk.name, "kind": lk.kind, "site": lk.site}
        for lk in program.all_locks()
    ]
    return {
        "version": GRAPH_VERSION,
        "comment": (
            "Static lock-acquisition graph (rule: lock-order). An edge "
            "A -> B means B may be acquired while A is held; `via` is "
            "one example site. Regenerate with `make lockgraph`; the "
            "runtime witness (FOREMAST_LOCK_WITNESS) asserts observed "
            "orders stay inside this graph. docs/static-analysis.md"
        ),
        "nodes": nodes,
        "edges": [
            {"from": a, "to": b, "via": site}
            for (a, b), site in sorted(edges.items())
        ],
        "reentrant": [
            {"id": name, "via": site}
            for name, site in sorted(reentrant.items())
        ],
    }


def _walk_function(program: Program, fn: FunctionInfo, record) -> None:
    from foremast_tpu.analysis.interproc import locked_walk

    for node, held, acquired in locked_walk(program, fn):
        if acquired is not None:
            for outer in held:
                record(outer, acquired, fn.site(node))
        elif held and isinstance(node, ast.Call):
            for callee in program.resolve_call(node, fn):
                for inner in sorted(
                    callee.acquires_all, key=lambda lk: lk.name
                ):
                    for outer in held:
                        record(outer, inner, fn.site(node))


def find_cycles(graph: dict) -> list[list[str]]:
    """Every elementary cycle reachable in the edge set (self-edges on
    plain Locks included — they deadlock a single thread). Returned as
    node-id paths, deterministic order."""
    adj: dict[str, list[str]] = {}
    for e in graph["edges"]:
        adj.setdefault(e["from"], []).append(e["to"])
    for targets in adj.values():
        targets.sort()
    cycles: list[list[str]] = []
    seen_cycles: set[tuple] = set()

    def dfs(start: str, node: str, path: list[str], on_path: set):
        for nxt in adj.get(node, ()):
            if nxt == start:
                canon = min(
                    tuple(path[i:] + path[:i]) for i in range(len(path))
                )
                if canon not in seen_cycles:
                    seen_cycles.add(canon)
                    cycles.append(path + [start])
            elif nxt not in on_path and nxt > start:
                # only explore nodes ordered after `start`: each cycle
                # is found exactly once, rooted at its smallest node
                dfs(start, nxt, path + [nxt], on_path | {nxt})

    for start in sorted(adj):
        dfs(start, start, [start], {start})
    return cycles


def graph_path(root: str) -> str:
    return os.path.join(root, GRAPH_NAME)


def write_graph(root: str, graph: dict) -> None:
    with open(graph_path(root), "w", encoding="utf-8") as f:
        json.dump(graph, f, indent=2, sort_keys=True)
        f.write("\n")


def load_graph(root: str) -> dict | None:
    path = graph_path(root)
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def _site_of(graph: dict, node_id: str) -> str:
    for n in graph["nodes"]:
        if n["id"] == node_id:
            return n["site"]
    return GRAPH_NAME


def check_lock_order(root: str, program: Program) -> list[Finding]:
    """Cycle findings + artifact-drift finding, for the default run."""
    graph = build_graph(program)
    findings: list[Finding] = []
    for cycle in find_cycles(graph):
        chain = " -> ".join(cycle)
        site = _site_of(graph, cycle[0])
        path, _, line = site.partition(":")
        findings.append(
            Finding(
                rule=RULE,
                path=path,
                line=int(line or 1),
                message=f"lock-order cycle: {chain} — two threads walking "
                "this cycle from different ends deadlock",
                hint="impose one global order (acquire the earlier lock "
                "first everywhere), or split the critical sections so "
                "the nesting disappears",
            )
        )
    committed = load_graph(root)
    if committed is None:
        findings.append(
            Finding(
                rule=RULE,
                path=GRAPH_NAME,
                line=1,
                message=f"{GRAPH_NAME} missing — the lock-acquisition "
                "graph must be committed so ordering changes are "
                "reviewable diffs",
                hint="run `make lockgraph` and commit the artifact",
            )
        )
    elif _normalize(committed) != _normalize(graph):
        findings.append(
            Finding(
                rule=RULE,
                path=GRAPH_NAME,
                line=1,
                message=f"committed {GRAPH_NAME} is stale vs the computed "
                "lock graph (locks or acquisition edges changed)",
                hint="run `make lockgraph`, review the diff, and commit it",
            )
        )
    return findings


def _normalize(graph: dict) -> tuple:
    return (
        graph.get("version"),
        tuple(
            (n["id"], n["kind"], n["site"])
            for n in sorted(graph.get("nodes", ()), key=lambda n: n["id"])
        ),
        tuple(
            (e["from"], e["to"], e["via"])
            for e in sorted(
                graph.get("edges", ()), key=lambda e: (e["from"], e["to"])
            )
        ),
        tuple(
            (r["id"], r["via"])
            for r in sorted(graph.get("reentrant", ()), key=lambda r: r["id"])
        ),
    )
