"""Runtime lock witness: pin the static lock-order model to reality.

The static graph (`analysis/lock_order.py`, committed as
``analysis_lockgraph.json``) is built by an over-approximating
resolver — useful only if it is actually a SUPERSET of what the
threads do. This module closes the loop from the runtime side:

  * `install()` replaces ``threading.Lock``/``RLock`` with factories
    that wrap locks CREATED BY PACKAGE CODE (decided by the immediate
    caller's frame, so stdlib internals — queue, logging, Event's
    Condition — keep their raw locks and semantics);
  * each wrapped lock remembers its creation site (``path:line`` —
    exactly the site the static graph records for the
    ``threading.Lock()`` call);
  * every acquisition records, per thread, an edge from each lock
    already held to the one being acquired (re-entrant RLock
    acquisitions are reentrancy, not ordering, and are skipped);
  * `unobserved_edges(graph)` maps the observed creation-site edges
    back to static node IDs and returns every edge the static graph is
    MISSING — the assertion tier-1 makes in ``tests/test_snapshot.py``
    and ``tests/test_pod_failure.py``, the two suites that exercise
    the journal hooks, the snapshot pass, and the mesh claim filter
    concurrently.

Production workers can run the same witness under
``FOREMAST_LOCK_WITNESS=1`` (`cli` installs it at worker startup and
logs any unknown edge at exit): the per-acquisition cost is one
thread-local list append, so it is cheap enough to leave on while
qualifying a new deployment.

Edges involving locks the static model does not know (test-local
locks, third-party code that slipped past the caller-frame check) are
ignored — the contract is "every observed edge BETWEEN PACKAGE LOCKS
exists statically", not "the witness sees every lock in the process".
"""

from __future__ import annotations

import atexit
import logging
import os
import sys
import threading
from _thread import allocate_lock as _raw_lock

log = logging.getLogger("foremast_tpu.analysis")

_PACKAGE_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO_DIR = os.path.dirname(_PACKAGE_DIR)
_THIS_FILE = os.path.abspath(__file__)


class LockWitness:
    """Collects (creation-site -> creation-site) acquisition edges."""

    def __init__(self):
        self._edges: set[tuple[str, str]] = set()
        self._edges_lock = _raw_lock()
        self._tls = threading.local()
        self._installed = False
        self._orig_lock = None
        self._orig_rlock = None

    # -- recording -------------------------------------------------------

    def _held(self) -> list:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def record_acquire(self, lock: "_WitnessLockBase") -> None:
        held = self._held()
        if any(entry is lock for entry in held):
            # re-entrant RLock acquisition: same object, no ordering
            held.append(lock)
            return
        if held:
            new_edges = {
                (h.site, lock.site)
                for h in held
                if h.site != lock.site
            } - self._edges
            if new_edges:
                with self._edges_lock:
                    self._edges |= new_edges
        held.append(lock)

    def record_release(self, lock: "_WitnessLockBase") -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is lock:
                del held[i]
                return

    # -- install / uninstall ---------------------------------------------

    def install(self) -> "LockWitness":
        if not self._installed:
            self._orig_lock = threading.Lock
            self._orig_rlock = threading.RLock
            threading.Lock = self._factory(self._orig_lock, _WitnessLock)
            threading.RLock = self._factory(self._orig_rlock, _WitnessRLock)
            self._installed = True
        return self

    def uninstall(self) -> None:
        if self._installed:
            threading.Lock = self._orig_lock
            threading.RLock = self._orig_rlock
            self._installed = False

    def _factory(self, orig, wrapper_cls):
        witness = self

        def make():
            inner = orig()
            site = _caller_site()
            if site is None:
                return inner  # not package code: raw lock, raw semantics
            return wrapper_cls(inner, site, witness)

        return make

    # -- checking --------------------------------------------------------

    def edges(self) -> set[tuple[str, str]]:
        with self._edges_lock:
            return set(self._edges)

    def unobserved_edges(self, graph: dict) -> list[tuple[str, str]]:
        """Observed edges between package locks that the static graph
        is missing, as (from-id, to-id) pairs. Empty = the static
        model covers everything reality did."""
        site_to_id = {n["site"]: n["id"] for n in graph.get("nodes", ())}
        static = {(e["from"], e["to"]) for e in graph.get("edges", ())}
        reentrant = {r["id"] for r in graph.get("reentrant", ())}
        missing = []
        for a_site, b_site in sorted(self.edges()):
            a, b = site_to_id.get(a_site), site_to_id.get(b_site)
            if a is None or b is None:
                continue  # a lock the static model does not track
            if a == b and a in reentrant:
                continue
            if (a, b) not in static:
                missing.append((a, b))
        return missing


class _WitnessLockBase:
    """Wrapper sharing the real lock's blocking semantics; only
    successful acquisitions touch the witness."""

    __slots__ = ("_inner", "site", "_witness")

    def __init__(self, inner, site: str, witness: LockWitness):
        self._inner = inner
        self.site = site
        self._witness = witness

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._witness.record_acquire(self)
        return got

    def release(self):
        self._witness.record_release(self)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()


class _WitnessLock(_WitnessLockBase):
    __slots__ = ()


class _WitnessRLock(_WitnessLockBase):
    __slots__ = ()


def _caller_site() -> str | None:
    """`relpath:line` of the frame that called the lock factory, when
    that frame is package code (excluding this module); else None."""
    frame = sys._getframe(2)  # make() -> factory caller
    abspath = os.path.abspath(frame.f_code.co_filename)
    if abspath == _THIS_FILE or not abspath.startswith(_PACKAGE_DIR + os.sep):
        return None
    rel = os.path.relpath(abspath, _REPO_DIR).replace(os.sep, "/")
    return f"{rel}:{frame.f_lineno}"


# ---------------------------------------------------------------------------
# module-level lifecycle
# ---------------------------------------------------------------------------

_current: LockWitness | None = None


def install() -> LockWitness:
    """Install (or return the already-installed) process witness."""
    global _current
    if _current is None:
        _current = LockWitness()
    _current.install()
    return _current


def uninstall() -> None:
    global _current
    if _current is not None:
        _current.uninstall()
        _current = None


def current() -> LockWitness | None:
    return _current


def load_graph() -> dict | None:
    from foremast_tpu.analysis.core import repo_root
    from foremast_tpu.analysis.lock_order import load_graph as _load

    return _load(repo_root())


def install_from_env(env=None) -> LockWitness | None:
    """`FOREMAST_LOCK_WITNESS=1` wiring for long-lived entry points
    (cli worker): install early, verify against the committed graph at
    interpreter exit, log — never raise — on an unknown edge."""
    e = os.environ if env is None else env
    if e.get("FOREMAST_LOCK_WITNESS", "") != "1":
        return None
    witness = install()

    def _report():
        graph = load_graph()
        if graph is None:
            log.warning(
                "lock witness: no committed analysis_lockgraph.json to "
                "verify against (%d edges observed)", len(witness.edges()),
            )
            return
        missing = witness.unobserved_edges(graph)
        if missing:
            log.warning(
                "lock witness: %d observed acquisition edge(s) MISSING "
                "from the static lock graph — the model is stale or the "
                "resolver has a hole; run `make lockgraph` and review: %s",
                len(missing), missing,
            )
        else:
            log.info(
                "lock witness: %d observed edge(s), all within the "
                "static graph", len(witness.edges()),
            )

    atexit.register(_report)
    return witness
