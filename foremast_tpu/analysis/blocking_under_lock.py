"""blocking-under-lock: I/O and sleeps while holding a lock.

Every lock in the data plane sits on a path some OTHER thread needs:
the receiver's handler threads contend on the shard locks the tick
thread fetches through, the scrape handler reads counters behind the
same locks the snapshot pass holds, and the fit journal's lock
serializes the judge's write-through. A blocking call inside any of
those critical sections turns one slow disk or one dead socket into a
fleet-wide stall — the failure class the PR-7 review rounds kept
finding by hand.

Flagged while any known lock is held (directly in the `with` body, or
transitively through calls the resolver can follow):

  * ``time.sleep``;
  * HTTP/network dials (``requests.*``, ``urllib.request.*``,
    ``socket.create_connection``) and requests-session verbs on
    session-shaped receivers (``self._s.post`` / ``*_session.get``);
  * ``subprocess.*`` / ``os.system`` / ``os.popen``;
  * ``open()``, ``os.fsync``, ``os.replace`` and file-handle
    ``.write``/``.flush`` on handle-shaped receivers (``fh``/``f``/
    ``self._fh``);
  * the synchronous JobStore surface (``store.claim(...)`` etc. — an
    ES round trip under a lock).

The DELIBERATE cases stay, suppressed in place with the reason — the
suppression is the documentation (docs/static-analysis.md). The
flagship example is the ring journal hook: PR 7's review hardening
moved it UNDER the owning shard's lock on purpose, because replayed
log order must equal apply order (see `RingShard.push`); the
``# foremast: ignore[blocking-under-lock]`` there cites that contract.
"""

from __future__ import annotations

import ast

from foremast_tpu.analysis.core import Finding, Module
from foremast_tpu.analysis.interproc import FunctionInfo, Program, dotted

RULE = "blocking-under-lock"

_BLOCKING_EXACT = {
    "time.sleep": "a sleep",
    "os.system": "a subprocess",
    "os.popen": "a subprocess",
    "os.fsync": "an fsync",
    "os.replace": "a rename",
    "socket.create_connection": "a socket dial",
}
_BLOCKING_PREFIXES = {
    "requests.": "an HTTP call",
    "subprocess.": "a subprocess",
    "urllib.request.": "an HTTP call",
}
_STORE_METHODS = frozenset(
    {
        "create", "get", "claim", "update", "update_many", "list_open",
        "list_app", "count_open", "wait_ready", "ensure_index",
    }
)
_SESSION_VERBS = frozenset({"get", "post", "put", "delete", "head", "request"})
_HANDLE_NAMES = frozenset({"fh", "f", "file", "_fh"})
_HANDLE_VERBS = frozenset({"write", "flush"})


def _receiver_name(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def classify_blocking(call: ast.Call) -> str | None:
    """A short description of the blocking operation this call
    performs, or None. Shared with the interprocedural summaries."""
    func = call.func
    d = dotted(func)
    if d is not None:
        desc = _BLOCKING_EXACT.get(d)
        if desc is not None:
            return f"{desc} (`{d}`)"
        for prefix, desc in _BLOCKING_PREFIXES.items():
            if d.startswith(prefix):
                return f"{desc} (`{d}`)"
    if isinstance(func, ast.Name) and func.id == "open":
        return "a file open (`open`)"
    if isinstance(func, ast.Attribute):
        recv = _receiver_name(func.value)
        if recv is not None:
            if func.attr in _STORE_METHODS and (
                recv == "store" or recv.endswith("_store")
            ):
                return f"a store round trip (`{recv}.{func.attr}`)"
            if func.attr in _SESSION_VERBS and (
                recv in ("_s", "_probe_s", "session")
                or recv.endswith("_session")
            ):
                return f"an HTTP call (`{recv}.{func.attr}`)"
            if func.attr in _HANDLE_VERBS and recv in _HANDLE_NAMES:
                return f"file I/O (`{recv}.{func.attr}`)"
    return None


def check_blocking_under_lock(program: Program) -> list[Finding]:
    """Whole-program pass: every function is walked with its own
    held-lock stack; a blocking call — or a call whose transitive
    summary blocks — inside a locked region is a finding."""
    findings: list[Finding] = []
    for fn in program.functions:
        findings.extend(_check_function(program, fn))
    return findings


def _check_function(program: Program, fn: FunctionInfo) -> list[Finding]:
    from foremast_tpu.analysis.interproc import locked_walk

    findings: list[Finding] = []
    for node, held, acquired in locked_walk(program, fn):
        if acquired is None and held and isinstance(node, ast.Call):
            findings.extend(_check_call(program, fn, node, held))
    return findings


def _check_call(
    program: Program, fn: FunctionInfo, call: ast.Call, held: list
) -> list[Finding]:
    lock_names = "/".join(str(lk) for lk in held)
    desc = classify_blocking(call)
    if desc is not None:
        return [
            _finding(
                fn,
                call,
                f"{desc} while holding {lock_names} in `{fn.qualname}` "
                "stalls every thread contending on that lock",
            )
        ]
    out = []
    for callee in program.resolve_call(call, fn):
        if callee.blocks_all:
            rep_desc, rep_site = sorted(callee.blocks_all.items())[0]
            out.append(
                _finding(
                    fn,
                    call,
                    f"call `{callee.qualname}(...)` reaches {rep_desc} at "
                    f"{rep_site} while holding {lock_names} in "
                    f"`{fn.qualname}`",
                )
            )
            break  # one finding per call site, not one per target
    return out


def _finding(fn: FunctionInfo, node: ast.AST, message: str) -> Finding:
    return Finding(
        rule=RULE,
        path=fn.module.relpath,
        line=getattr(node, "lineno", fn.node.lineno),
        message=message,
        hint="move the I/O outside the critical section (copy under the "
        "lock, write outside), or mark a deliberate hold with "
        "`# foremast: ignore[blocking-under-lock]` + the contract that "
        "makes it sound",
    )


def apply_suppressions(
    findings: list[Finding], modules: list[Module]
) -> list[Finding]:
    """Per-line `# foremast: ignore[...]` filtering for program-level
    rules (the per-module path applies this inside analyze_modules)."""
    by_path = {m.relpath: m for m in modules}
    out = []
    for f in findings:
        m = by_path.get(f.path)
        if m is not None and m.suppressed(f.line, f.rule):
            continue
        out.append(f)
    return sorted(set(out), key=Finding.sort_key)
