"""sharding-contract: device placement stays behind the `_place` seam.

The device-mesh fast path (CHANGES PR 13) works because placement is
CENTRALIZED: every buffer entering a sharded dispatch goes through
`HealthJudge._place` / `_place_cols`, which `ShardedJudge` overrides
with the mesh `device_put`. A direct `jnp.asarray`/`jax.device_put` in
warm-path code commits the buffer to the DEFAULT device first, turning
the sharded placement into a second copy (engine/judge.py's host-buffer
comment pins this), or — worse — silently bypassing the partition and
breaking byte parity across arms. ISSUE 19 rewrote the arena seam
(replicated rows -> data-axis-sharded row blocks); this rule keeps the
NEW layout's invariants from drifting into a parity break.

Two checks, both scoped to the warm-path modules:

  * PLACEMENT — in ``foremast_tpu/jobs/`` (the worker never touches
    jax directly: buffers stay host numpy until the judge places them)
    and ``foremast_tpu/parallel/batch.py`` (the sharded judge itself),
    a direct ``jnp.asarray``/``jnp.array``/``jax.device_put`` call
    outside the placement hooks (`_place`, `_place_cols`) is a
    finding. `parallel/mesh.py` is the placement LIBRARY (the hooks
    call into it) and `parallel/seqparallel.py`/`distributed.py` are
    jit-interior collective code, so they are out of scope by design.
  * SHARDED ARENA — arena references from sharded code
    (``foremast_tpu/parallel/``) must sit in a function annotated
    ``# foremast: sharded-arena``: the arena block-partitions its ROW
    space over the data axis (`ShardedJudge._arena_sharding`, ISSUE
    19) with row placement tied to batch position (position i of a
    B-row batch lives on shard ``i // (B / shards)``), so warm gathers
    take LOCAL indices inside shard_map and never cross chips. Any new
    arena touchpoint in parallel/ must declare it honors that
    contract — global indices fed to the local gather, or a
    concatenate/reshape that re-blocks the row axis, silently turns
    the device-local gather into garbage rows or an all-gather. The
    annotation inventory lives in docs/static-analysis.md.
"""

from __future__ import annotations

import ast

from foremast_tpu.analysis.core import Finding
from foremast_tpu.analysis.interproc import Program, dotted, own_body_walk

RULE = "sharding-contract"
ARENA_MARKER = "sharded-arena"

PLACEMENT_HOOKS = frozenset({"_place", "_place_cols"})
PLACEMENT_SCOPE = ("foremast_tpu/jobs/", "foremast_tpu/parallel/batch.py")
ARENA_SCOPE = ("foremast_tpu/parallel/",)
_PLACERS = frozenset({"jnp.asarray", "jnp.array", "jax.device_put",
                      "jax.numpy.asarray", "jax.numpy.array", "device_put"})


def check_sharding_contract(program: Program) -> list[Finding]:
    findings: list[Finding] = []
    for fn in program.functions:
        rel = fn.module.relpath
        if rel.startswith(PLACEMENT_SCOPE) and fn.name not in PLACEMENT_HOOKS:
            findings.extend(_placement_findings(fn))
        if rel.startswith(ARENA_SCOPE):
            findings.extend(_arena_findings(fn))
    return findings


def _placement_findings(fn) -> list[Finding]:
    out: list[Finding] = []
    for node in own_body_walk(fn.node):
        if not isinstance(node, ast.Call):
            continue
        d = dotted(node.func)
        if d in _PLACERS:
            out.append(
                fn.module.finding(
                    RULE,
                    node,
                    f"direct `{d}` in warm-path code (`{fn.name}`): "
                    "buffers entering a sharded dispatch must go through "
                    "`_place`/`_place_cols` — committing to the default "
                    "device first makes the mesh placement a second copy "
                    "(or bypasses the partition entirely)",
                    hint="keep the buffer host-side (numpy) and let the "
                    "judge's placement hook put it on the mesh; bench-only "
                    "constructors may suppress with `# foremast: "
                    "ignore[sharding-contract]` + a citation",
                )
            )
    return out


def _arena_findings(fn) -> list[Finding]:
    if fn.module.marked_def(fn.node, ARENA_MARKER):
        return []
    out: list[Finding] = []
    for node in own_body_walk(fn.node):
        name = None
        if isinstance(node, ast.Name):
            name = node.id
        elif isinstance(node, ast.Attribute):
            name = node.attr
        if name is None or "arena" not in name.lower():
            continue
        # line-level annotation also counts (single-expression touches)
        if fn.module.marked(getattr(node, "lineno", fn.node.lineno),
                            ARENA_MARKER):
            continue
        out.append(
            fn.module.finding(
                RULE,
                node,
                f"arena reference `{name}` in sharded code (`{fn.name}`) "
                "without the sharded-arena annotation — arena rows "
                "block-shard over the data axis with position-tied "
                "placement (ShardedJudge._arena_sharding, ISSUE 19); "
                "code that touches them from parallel/ must declare it "
                "honors that layout",
                hint="annotate the enclosing def (or this line) with "
                "`# foremast: sharded-arena` after checking the access "
                "keeps row placement aligned with batch position (local "
                "indices into shard_map gathers, no row-axis re-blocking) "
                "— docs/static-analysis.md",
            )
        )
        break  # one finding per function is enough signal
    return out
